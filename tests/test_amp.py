"""amp tests — modeled on the reference L0 amp suite (tests/L0/run_amp/):
cast correctness per opt level, loss-scaler dynamics (overflow/growth/skip),
master-weight flow, checkpoint round-trip, interposition casting."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import amp, optimizers


# ---------------------------------------------------------------------------
# Policy resolution (reference test: opt-level tables + overrides)
# ---------------------------------------------------------------------------

def test_opt_level_tables():
    o2 = amp.resolve("O2")
    assert o2.cast_model_type == jnp.float16
    assert o2.keep_batchnorm_fp32 is True
    assert o2.master_weights is True
    assert o2.loss_scale == "dynamic"
    o4 = amp.resolve("O4")
    assert o4.patch_functions and o4.patch_functions_type == jnp.bfloat16
    assert o4.loss_scale == 1.0
    o5 = amp.resolve("O5")
    assert o5.cast_model_type == jnp.bfloat16 and o5.master_weights


def test_opt_level_overrides():
    p = amp.resolve("O2", loss_scale=128.0, keep_batchnorm_fp32=False)
    assert p.loss_scale == 128.0 and p.keep_batchnorm_fp32 is False
    with pytest.raises(ValueError):
        amp.resolve("O8")  # O7 is the last level (the fp8 tier)
    with pytest.raises(ValueError):
        amp.resolve("O1", master_weights=True)  # needs cast_model_type


# ---------------------------------------------------------------------------
# cast_model / keep_batchnorm_fp32
# ---------------------------------------------------------------------------

def test_cast_model_keeps_bn_fp32():
    params = {
        "Dense_0": {"kernel": jnp.ones((4, 4)), "bias": jnp.zeros((4,))},
        "BatchNorm_0": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
    }
    cast = amp.cast_model(params, "O5")
    assert cast["Dense_0"]["kernel"].dtype == jnp.bfloat16
    assert cast["BatchNorm_0"]["scale"].dtype == jnp.float32
    cast3 = amp.cast_model(params, "O3")  # keep_batchnorm_fp32=False
    assert cast3["BatchNorm_0"]["scale"].dtype == jnp.float16


# ---------------------------------------------------------------------------
# Loss scaler dynamics (reference scaler.py semantics)
# ---------------------------------------------------------------------------

def test_scaler_overflow_halves_scale():
    s = amp.LossScaler("dynamic")
    st = s.init()
    assert float(st.loss_scale[0]) == 2.0 ** 16
    st = s.update(st, jnp.asarray(True))
    assert float(st.loss_scale[0]) == 2.0 ** 15
    assert int(st.unskipped[0]) == 0
    assert int(st.overflows[0]) == 1


def test_scaler_window_growth():
    s = amp.LossScaler("dynamic", scale_window=3, init_scale=2.0 ** 10)
    st = s.init()
    for _ in range(3):
        st = s.update(st, jnp.asarray(False))
    assert float(st.loss_scale[0]) == 2.0 ** 11
    assert int(st.unskipped[0]) == 0


def test_scaler_max_scale_clamp():
    s = amp.LossScaler("dynamic", scale_window=1, init_scale=2.0 ** 24)
    st = s.init()
    st = s.update(st, jnp.asarray(False))
    assert float(st.loss_scale[0]) == 2.0 ** 24  # clamped


def test_scaler_static():
    s = amp.LossScaler(128.0)
    st = s.init()
    assert float(st.loss_scale[0]) == 128.0
    st = s.update(st, jnp.asarray(True))
    assert float(st.loss_scale[0]) == 128.0  # static never changes


def test_scaler_unscale_roundtrip():
    s = amp.LossScaler("dynamic")
    st = s.init()
    grads = {"g": jnp.full((64,), 3.0) * st.loss_scale[0]}
    un, overflow = s.unscale(grads, st)
    assert not bool(overflow)
    np.testing.assert_allclose(np.asarray(un["g"]), 3.0, rtol=1e-6)


# ---------------------------------------------------------------------------
# AmpOptimizer: master weights, skip-on-overflow, checkpoint round-trip
# ---------------------------------------------------------------------------

def _mk_amp_opt(opt_level="O5", **kw):
    inner = optimizers.FusedSGD(lr=0.1)
    props = amp.resolve(opt_level, **kw)
    return amp.AmpOptimizer(inner, props)


def test_master_weight_flow_o5():
    aopt = _mk_amp_opt("O5")
    model_params = {"w": jnp.ones((32,), jnp.bfloat16)}
    st = aopt.init(model_params)
    assert st.master["w"].dtype == jnp.float32
    grads = {"w": jnp.full((32,), 0.01, jnp.bfloat16)}
    scaled = jax.tree.map(
        lambda g: g * st.scaler.loss_scale[0].astype(g.dtype), grads)
    new_p, st, info = aopt.step(scaled, model_params, st)
    assert new_p["w"].dtype == jnp.bfloat16
    # master keeps full precision: 1 - 0.1*0.01 = 0.999 (not representable
    # in bf16 — the model copy rounds, the master must not)
    np.testing.assert_allclose(np.asarray(st.master["w"]), 0.999, rtol=1e-5)
    assert not bool(info["overflow"])


def test_overflow_skips_step_and_halves_scale():
    aopt = _mk_amp_opt("O2")
    model_params = {"w": jnp.ones((16,), jnp.float16)}
    st = aopt.init(model_params)
    scale0 = float(st.scaler.loss_scale[0])
    grads = {"w": jnp.full((16,), float("inf"), jnp.float16)}
    new_p, st, info = aopt.step(grads, model_params, st)
    assert bool(info["overflow"])
    np.testing.assert_array_equal(np.asarray(new_p["w"], np.float32),
                                  np.asarray(model_params["w"], np.float32))
    np.testing.assert_allclose(np.asarray(st.master["w"]), 1.0)
    assert float(st.scaler.loss_scale[0]) == scale0 / 2


def test_amp_step_inside_jit():
    aopt = _mk_amp_opt("O5")
    model_params = {"w": jnp.ones((64,), jnp.bfloat16)}
    st = aopt.init(model_params)

    @jax.jit
    def step(g, p, s):
        return aopt.step(g, p, s)

    grads = {"w": jnp.full((64,), 0.5, jnp.bfloat16)}
    p1, st1, info = step(grads, model_params, st)
    assert not bool(info["overflow"])
    np.testing.assert_allclose(np.asarray(st1.master["w"]), 0.95, rtol=1e-5)


# ---------------------------------------------------------------------------
# Multi-loss scalers (reference
# tests/L0/run_amp/test_multiple_models_optimizers_losses.py: per-loss
# scaler independence under num_losses/loss_id)
# ---------------------------------------------------------------------------

def test_multi_loss_scaler_independence():
    s = amp.LossScaler("dynamic", num_losses=3)
    st = s.init()
    st = s.update(st, jnp.asarray(True), loss_id=0)
    assert float(st.loss_scale[0]) == 2.0 ** 15      # halved
    assert float(st.loss_scale[1]) == 2.0 ** 16      # untouched
    assert float(st.loss_scale[2]) == 2.0 ** 16
    assert int(st.overflows[0]) == 1
    assert int(st.overflows[1]) == 0


def test_multi_loss_growth_independent():
    s = amp.LossScaler("dynamic", num_losses=2, scale_window=2,
                       init_scale=2.0 ** 8)
    st = s.init()
    for _ in range(2):
        st = s.update(st, jnp.asarray(False), loss_id=1)
    assert float(st.loss_scale[1]) == 2.0 ** 9       # grew after window
    assert float(st.loss_scale[0]) == 2.0 ** 8       # loss 0 window untouched
    assert int(st.unskipped[0]) == 0


def test_multi_loss_scale_loss_uses_per_loss_scale():
    s = amp.LossScaler("dynamic", num_losses=2)
    st = s.init()
    st = s.update(st, jnp.asarray(True), loss_id=1)  # scale[1] != scale[0]
    loss = jnp.asarray(2.0)
    assert float(s.scale_loss(loss, st, 0)) == 2.0 * float(st.loss_scale[0])
    assert float(s.scale_loss(loss, st, 1)) == 2.0 * float(st.loss_scale[1])
    assert float(st.loss_scale[0]) != float(st.loss_scale[1])


def test_amp_optimizer_multi_loss_overflow_isolation():
    """Overflow during loss 0's step must not disturb loss 1's scale, and a
    subsequent loss-1 step must proceed normally (loss_id plumbing through
    AmpOptimizer.step)."""
    inner = optimizers.FusedSGD(lr=0.1)
    aopt = amp.AmpOptimizer(inner, amp.resolve("O2"), num_losses=2)
    model_params = {"w": jnp.ones((16,), jnp.float16)}
    st = aopt.init(model_params)
    s0 = float(st.scaler.loss_scale[0])
    s1 = float(st.scaler.loss_scale[1])

    bad = {"w": jnp.full((16,), float("inf"), jnp.float16)}
    p1, st, info = aopt.step(bad, model_params, st, loss_id=0)
    assert bool(info["overflow"])
    assert float(st.scaler.loss_scale[0]) == s0 / 2
    assert float(st.scaler.loss_scale[1]) == s1      # isolated
    np.testing.assert_array_equal(np.asarray(p1["w"], np.float32),
                                  np.asarray(model_params["w"], np.float32))

    good = {"w": (jnp.full((16,), 0.01)
                  * st.scaler.loss_scale[1]).astype(jnp.float16)}
    p2, st, info = aopt.step(good, p1, st, loss_id=1)
    assert not bool(info["overflow"])
    assert float(st.scaler.loss_scale[1]) == s1      # no overflow: unchanged
    assert float(st.scaler.loss_scale[0]) == s0 / 2  # still halved
    np.testing.assert_allclose(np.asarray(st.master["w"]), 0.999, rtol=1e-4)


def test_multi_loss_three_losses_jit_gan_shape():
    """DCGAN-shaped flow (examples/dcgan): one discriminator optimizer fed
    by two losses (real/fake, loss_id 0/1) + one generator optimizer
    (loss_id 2 on its own scaler) — all steps jitted; per-loss scales evolve
    independently when one loss overflows."""
    d_inner = optimizers.FusedSGD(lr=0.05)
    g_inner = optimizers.FusedSGD(lr=0.05)
    d_opt = amp.AmpOptimizer(d_inner, amp.resolve("O2"), num_losses=2)
    g_opt = amp.AmpOptimizer(g_inner, amp.resolve("O2"), num_losses=1)
    d_params = {"w": jnp.ones((8,), jnp.float16)}
    g_params = {"w": jnp.ones((8,), jnp.float16)}
    d_st, g_st = d_opt.init(d_params), g_opt.init(g_params)

    @jax.jit
    def gan_step(d_params, g_params, d_st, g_st, bad_fake):
        real_g = {"w": (jnp.full((8,), 0.01)
                        * d_st.scaler.loss_scale[0]).astype(jnp.float16)}
        d_params, d_st, _ = d_opt.step(real_g, d_params, d_st, loss_id=0)
        fake_val = jnp.where(bad_fake, jnp.inf, 0.01)
        fake_g = {"w": (jnp.full((8,), 1.0) * fake_val
                        * d_st.scaler.loss_scale[1]).astype(jnp.float16)}
        d_params, d_st, _ = d_opt.step(fake_g, d_params, d_st, loss_id=1)
        gen_g = {"w": (jnp.full((8,), 0.01)
                       * g_st.scaler.loss_scale[0]).astype(jnp.float16)}
        g_params, g_st, _ = g_opt.step(gen_g, g_params, g_st)
        return d_params, g_params, d_st, g_st

    s = float(d_st.scaler.loss_scale[0])
    d_params, g_params, d_st, g_st = gan_step(
        d_params, g_params, d_st, g_st, jnp.asarray(True))
    assert float(d_st.scaler.loss_scale[0]) == s        # real loss clean
    assert float(d_st.scaler.loss_scale[1]) == s / 2    # fake loss overflowed
    assert float(g_st.scaler.loss_scale[0]) == s        # generator untouched
    d_params, g_params, d_st, g_st = gan_step(
        d_params, g_params, d_st, g_st, jnp.asarray(False))
    assert float(d_st.scaler.loss_scale[1]) == s / 2    # recovered, no growth


def test_checkpoint_roundtrip():
    # reference test_checkpointing.py: save/load scaler state preserves scale
    aopt = _mk_amp_opt("O2")
    p = {"w": jnp.ones((8,), jnp.float16)}
    st = aopt.init(p)
    g = {"w": jnp.full((8,), float("inf"), jnp.float16)}
    _, st, _ = aopt.step(g, p, st)  # halves scale
    d = amp.state_dict(aopt, st)
    st2 = aopt.init(p)
    st2 = amp.load_state_dict(aopt, st2, d)
    assert float(st2.scaler.loss_scale[0]) == float(st.scaler.loss_scale[0])
    assert int(st2.scaler.overflows[0]) == 1


# ---------------------------------------------------------------------------
# O1/O4 interposition (reference test_basic_casts.py)
# ---------------------------------------------------------------------------

def test_autocast_matmul_bf16():
    a = jnp.ones((8, 8), jnp.float32)
    with amp.autocast(jnp.bfloat16):
        out = jnp.matmul(a, a)
    assert out.dtype == jnp.bfloat16
    # outside the context, no casting
    out2 = jnp.matmul(a, a)
    assert out2.dtype == jnp.float32


def test_autocast_blacklist_fp32():
    x = jnp.ones((16,), jnp.bfloat16)
    with amp.autocast(jnp.bfloat16):
        out = jax.nn.softmax(x)
    assert out.dtype == jnp.float32


def test_autocast_flax_dense():
    # The dot_general inside flax Dense must run in bf16 (MXU path); the
    # fp32 bias-add afterwards promotes the output back to fp32, which is
    # fine — the FLOPs went through the MXU in bf16.
    import flax.linen as nn
    model = nn.Dense(8, use_bias=True)
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 4), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)
    with amp.autocast(jnp.bfloat16):
        y = model.apply(params, x)
    k = params["params"]["kernel"]
    b = params["params"]["bias"]
    expected = (x.astype(jnp.bfloat16) @ k.astype(jnp.bfloat16)) + b
    np.testing.assert_array_equal(np.asarray(y), np.asarray(expected))
    # and differs from the pure-fp32 result (i.e. cast actually happened)
    y32 = model.apply(params, x)
    assert not np.array_equal(np.asarray(y), np.asarray(y32))


def test_autocast_under_jit():
    def f(a, b):
        with amp.autocast(jnp.bfloat16):
            return jnp.dot(a, b)
    a = jnp.ones((4, 4), jnp.float32)
    y = jax.jit(f)(a, a)
    assert y.dtype == jnp.bfloat16


def test_disable_casts():
    a = jnp.ones((4, 4), jnp.float32)
    with amp.autocast(jnp.bfloat16):
        with amp.disable_casts():
            y = jnp.matmul(a, a)
    assert y.dtype == jnp.float32


def test_integer_args_untouched():
    x = jnp.arange(16)
    with amp.autocast(jnp.bfloat16):
        s = jnp.sum(x)
    assert s.dtype in (jnp.int32, jnp.int64)


# ---------------------------------------------------------------------------
# initialize() end-to-end: tiny model trains under each opt level
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("opt_level", ["O0", "O1", "O2", "O3", "O4", "O5"])
def test_initialize_trains_tiny_model(opt_level):
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(16)(x)
            x = nn.relu(x)
            return nn.Dense(1)(x)

    model = MLP()
    x = jax.random.normal(jax.random.PRNGKey(0), (32, 4), jnp.float32)
    y = jnp.sum(x * 0.5, axis=1, keepdims=True)
    params32 = model.init(jax.random.PRNGKey(1), x)

    apply_fn, aopt = amp.initialize(model.apply, optimizers.FusedSGD(lr=0.05),
                                    opt_level=opt_level, verbosity=0)
    params = amp.cast_model(params32, opt_level)
    st = aopt.init(params)

    @jax.jit
    def train_step(params, st, x, y):
        def loss_fn(p):
            pred = apply_fn(p, x)
            return jnp.mean((pred.astype(jnp.float32) - y) ** 2)
        loss, grads = jax.value_and_grad(
            lambda p: aopt.scale_loss(loss_fn(p), st))(params)
        new_p, new_st, info = aopt.step(grads, params, st)
        return new_p, new_st, loss / st.scaler.loss_scale[0]

    losses = []
    for _ in range(40):
        params, st, loss = train_step(params, st, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, (opt_level, losses[0], losses[-1])


def test_keep_bn_warning_only_when_explicit():
    """The zero-BN-matches warning fires only when the USER asked for
    keep_batchnorm_fp32 — BN-free models under plain O2/O5 defaults must
    stay silent (r2 review fix)."""
    import warnings as _w
    params = {"dense": {"kernel": jnp.ones((4, 4))}}
    with _w.catch_warnings():
        _w.simplefilter("error")  # default O5: must NOT warn
        amp.cast_model(params, amp.resolve("O5"))
    with pytest.warns(UserWarning, match="batchnorm-like"):
        amp.cast_model(params, amp.resolve("O5", keep_batchnorm_fp32=True))


@pytest.mark.parametrize("opt_level", ["O1", "O2", "O5"])
def test_two_models_two_optimizers_joint_equals_separate(opt_level):
    """The heart of the reference's 764-line cross-product test
    (tests/L0/run_amp/test_multiple_models_optimizers_losses.py): training
    two models jointly — each with its own optimizer and loss — must be
    BITWISE identical to training each alone, across opt levels."""
    def make(seed):
        w = jax.random.normal(jax.random.PRNGKey(seed), (8, 8))
        props = amp.resolve(opt_level)
        p32 = {"w": w}
        p = amp.cast_model(p32, props)
        inner = optimizers.FusedSGD(lr=0.1, momentum=0.9)
        aopt = amp.AmpOptimizer(inner, props)
        return p, aopt, aopt.init(p)

    x = jax.random.normal(jax.random.PRNGKey(9), (4, 8))

    def loss_fn(p, shift):
        y = x.astype(p["w"].dtype) @ p["w"]
        return jnp.mean((y.astype(jnp.float32) - shift) ** 2)

    def step(p, aopt, st, shift):
        def scaled(pp):
            return aopt.scale_loss(loss_fn(pp, shift), st)
        grads = jax.grad(scaled)(p)
        new_p, new_st, _ = aopt.step(grads, p, st)
        return new_p, new_st

    # joint: interleave the two models' steps in one loop
    pa, oa, sa = make(1)
    pb, ob, sb = make(2)
    for _ in range(5):
        pa, sa = step(pa, oa, sa, 1.0)
        pb, sb = step(pb, ob, sb, -1.0)

    # separate runs, same seeds
    pa2, oa2, sa2 = make(1)
    for _ in range(5):
        pa2, sa2 = step(pa2, oa2, sa2, 1.0)
    pb2, ob2, sb2 = make(2)
    for _ in range(5):
        pb2, sb2 = step(pb2, ob2, sb2, -1.0)

    np.testing.assert_array_equal(np.asarray(pa["w"], np.float32),
                                  np.asarray(pa2["w"], np.float32))
    np.testing.assert_array_equal(np.asarray(pb["w"], np.float32),
                                  np.asarray(pb2["w"], np.float32))
    if oa.properties.master_weights:
        np.testing.assert_array_equal(np.asarray(sa.master["w"]),
                                      np.asarray(sa2.master["w"]))


@pytest.mark.parametrize("opt_level", ["O2", "O5"])
def test_one_optimizer_two_models_shared_step(opt_level):
    """One optimizer driving the concatenated params of two models (the
    reference's shared-optimizer rows): the shared step must equal per-model
    steps when the losses are independent (disjoint grad support)."""
    props = amp.resolve(opt_level)
    w1 = jax.random.normal(jax.random.PRNGKey(3), (6, 6))
    w2 = jax.random.normal(jax.random.PRNGKey(4), (6, 6))
    both32 = {"m1": {"w": w1}, "m2": {"w": w2}}
    both = amp.cast_model(both32, props)
    inner = optimizers.FusedSGD(lr=0.1, momentum=0.9)
    aopt = amp.AmpOptimizer(inner, props)
    st = aopt.init(both)

    x = jax.random.normal(jax.random.PRNGKey(5), (4, 6))

    def scaled(p):
        y1 = x.astype(p["m1"]["w"].dtype) @ p["m1"]["w"]
        y2 = x.astype(p["m2"]["w"].dtype) @ p["m2"]["w"]
        loss = (jnp.mean(y1.astype(jnp.float32) ** 2)
                + jnp.mean(y2.astype(jnp.float32) ** 2))
        return aopt.scale_loss(loss, st)

    grads = jax.grad(scaled)(both)
    new_both, _, _ = aopt.step(grads, both, st)

    # reference: stepping each model alone with its own optimizer
    for name in ("m1", "m2"):
        solo = {"w": both[name]["w"]}
        solo_opt = amp.AmpOptimizer(
            optimizers.FusedSGD(lr=0.1, momentum=0.9), props)
        solo_st = solo_opt.init(solo)

        def scaled_solo(p):
            y = x.astype(p["w"].dtype) @ p["w"]
            return solo_opt.scale_loss(
                jnp.mean(y.astype(jnp.float32) ** 2), solo_st)

        g = jax.grad(scaled_solo)(solo)
        new_solo, _, _ = solo_opt.step(g, solo, solo_st)
        np.testing.assert_array_equal(
            np.asarray(new_both[name]["w"], np.float32),
            np.asarray(new_solo["w"], np.float32))


# ---------------------------------------------------------------------------
# Per-entry cast sweeps (reference test_basic_casts.py exercises EVERY
# whitelist/blacklist entry)
# ---------------------------------------------------------------------------

_LOW_PREC_CASES = {
    ("jax.numpy", "matmul"): lambda jnp_, a, b: jnp_.matmul(a, b),
    ("jax.numpy", "dot"): lambda jnp_, a, b: jnp_.dot(a, b),
    ("jax.numpy", "vdot"): lambda jnp_, a, b: jnp_.vdot(a, b),
    ("jax.numpy", "inner"): lambda jnp_, a, b: jnp_.inner(a, b),
    ("jax.numpy", "tensordot"): lambda jnp_, a, b: jnp_.tensordot(a, b, 1),
    ("jax.numpy", "einsum"): lambda jnp_, a, b: jnp_.einsum("ij,jk->ik",
                                                            a, b),
    ("jax.lax", "dot"): lambda jnp_, a, b: jax.lax.dot(a, b),
}


@pytest.mark.parametrize("entry", sorted(_LOW_PREC_CASES),
                         ids=lambda e: f"{e[0]}.{e[1]}")
def test_autocast_each_whitelist_entry(entry):
    """Every LOW_PREC (whitelist) table entry with a callable jnp-level
    surface casts fp32 inputs down under autocast (test_basic_casts.py
    analog; conv entries are covered by the flax-Conv integration test)."""
    fn = _LOW_PREC_CASES[entry]
    a = jnp.ones((4, 4), jnp.float32)
    b = jnp.ones((4, 4), jnp.float32)
    with amp.autocast(jnp.bfloat16):
        out = fn(jnp, a, b)
    assert out.dtype == jnp.bfloat16, entry


_FP32_CASES = {
    ("jax.nn", "softmax"): lambda x: jax.nn.softmax(x),
    ("jax.nn", "log_softmax"): lambda x: jax.nn.log_softmax(x),
    ("jax.nn", "logsumexp"): lambda x: jax.nn.logsumexp(x),
    ("jax.scipy.special", "logsumexp"):
        lambda x: jax.scipy.special.logsumexp(x),
    ("jax.numpy", "exp"): lambda x: jnp.exp(x),
    ("jax.numpy", "expm1"): lambda x: jnp.expm1(x),
    ("jax.numpy", "log"): lambda x: jnp.log(jnp.abs(x) + 1),
    ("jax.numpy", "log10"): lambda x: jnp.log10(jnp.abs(x) + 1),
    ("jax.numpy", "log1p"): lambda x: jnp.log1p(jnp.abs(x)),
    ("jax.numpy", "log2"): lambda x: jnp.log2(jnp.abs(x) + 1),
    ("jax.numpy", "power"): lambda x: jnp.power(jnp.abs(x) + 1, 2.0),
    ("jax.numpy", "float_power"): lambda x: jnp.float_power(
        jnp.abs(x) + 1, 2.0),
    ("jax.numpy", "cosh"): lambda x: jnp.cosh(x),
    ("jax.numpy", "sinh"): lambda x: jnp.sinh(x),
    ("jax.numpy", "tan"): lambda x: jnp.tan(x),
    ("jax.numpy", "reciprocal"): lambda x: jnp.reciprocal(x + 2),
    ("jax.lax", "rsqrt"): lambda x: jax.lax.rsqrt(jnp.abs(x) + 1),
    ("jax.lax", "erf_inv"): lambda x: jax.lax.erf_inv(x * 0.1),
    ("jax.numpy", "sum"): lambda x: jnp.sum(x),
    ("jax.numpy", "prod"): lambda x: jnp.prod(x),
    ("jax.numpy", "cumsum"): lambda x: jnp.cumsum(x),
    ("jax.numpy", "cumprod"): lambda x: jnp.cumprod(x),
    ("jax.numpy", "mean"): lambda x: jnp.mean(x),
    ("jax.numpy", "var"): lambda x: jnp.var(x),
    ("jax.numpy", "std"): lambda x: jnp.std(x),
}


@pytest.mark.parametrize("entry", sorted(_FP32_CASES),
                         ids=lambda e: f"{e[0]}.{e[1]}")
def test_autocast_each_blacklist_entry(entry):
    """Every FP32 (blacklist) entry computes in fp32 under autocast even
    with low-precision inputs — and the table stays in sync with this
    sweep."""
    fn = _FP32_CASES[entry]
    x = jnp.linspace(0.1, 1.0, 16, dtype=jnp.bfloat16)
    with amp.autocast(jnp.bfloat16):
        out = fn(x)
    assert out.dtype == jnp.float32, entry


def test_cast_tables_fully_swept():
    """Every policy-table entry is either in a sweep above or explicitly
    accounted for (the conv/dot_general funnel entries are exercised via
    flax Dense/Conv integration tests)."""
    from apex_tpu.amp import lists
    covered_low = set(_LOW_PREC_CASES)
    funnel = {("jax.lax", "dot_general"),
              ("jax.lax", "conv_general_dilated"),
              ("jax.lax", "conv_with_general_padding"),
              ("jax.lax", "conv")}
    assert set(map(tuple, lists.LOW_PREC_FUNCS)) == covered_low | funnel
    assert set(map(tuple, lists.FP32_FUNCS)) == set(_FP32_CASES)


def test_bn_predicate_from_model_type_keyed():
    """Type-keyed BN detection (VERDICT r2 weak #7): a model whose BN
    params carry unconventional names keeps fp32 BN under O2/O5 via
    bn_predicate_from_model — no warning-and-miss."""
    import flax.linen as nn

    class WeirdNet(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            x = nn.Dense(8, name="proj")(x)
            # BatchNorm under a name the path regex cannot recognize
            x = nn.BatchNorm(use_running_average=not train,
                             name="stats_gadget")(x)
            return nn.Dense(4, name="head")(x)

    x = jnp.ones((2, 8))
    m = WeirdNet()
    variables = m.init(jax.random.PRNGKey(0), x)
    params = variables["params"]

    # the regex path misses it (and warns when explicit)
    with pytest.warns(UserWarning, match="batchnorm-like"):
        missed = amp.cast_model(
            params, amp.resolve("O5", keep_batchnorm_fp32=True))
    assert missed["stats_gadget"]["scale"].dtype == jnp.bfloat16

    # the type-keyed predicate finds it by MODULE TYPE
    pred = amp.bn_predicate_from_model(m, jax.random.PRNGKey(0), x)
    assert pred.bn_module_paths == frozenset({"stats_gadget"})
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        cast = amp.cast_model(
            params, amp.resolve("O5", keep_batchnorm_fp32=True),
            bn_predicate=pred)
    assert cast["stats_gadget"]["scale"].dtype == jnp.float32
    assert cast["stats_gadget"]["bias"].dtype == jnp.float32
    assert cast["proj"]["kernel"].dtype == jnp.bfloat16
    assert cast["head"]["kernel"].dtype == jnp.bfloat16

    # SyncBatchNorm and conventional names still covered
    from apex_tpu.parallel import SyncBatchNorm

    class SyncNet(nn.Module):
        @nn.compact
        def __call__(self, x):
            return SyncBatchNorm(use_running_average=True,
                                 name="tracker")(x)

    m2 = SyncNet()
    pred2 = amp.bn_predicate_from_model(m2, jax.random.PRNGKey(0), x)
    assert pred2.bn_module_paths == frozenset({"tracker"})


def test_cast_model_variables_dict_auto_bn_detection():
    """VERDICT r3 next #8: with the model in hand — the full variables
    dict — oddly-named BN stays fp32 under O2/O5 WITHOUT any user
    action: every module path holding batch_stats is typed as BN
    (amp.bn_predicate_from_batch_stats), no regex, no trace."""
    import flax.linen as nn

    class WeirdNet(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            x = nn.Dense(8, name="proj")(x)
            x = nn.BatchNorm(use_running_average=not train,
                             name="stats_gadget")(x)
            return nn.Dense(4, name="head")(x)

    x = jnp.ones((2, 8))
    variables = WeirdNet().init(jax.random.PRNGKey(0), x)

    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        cast = amp.cast_model(
            variables, amp.resolve("O5", keep_batchnorm_fp32=True))
    # params cast; the oddly-named BN kept fp32 by TYPE (batch_stats)
    assert cast["params"]["stats_gadget"]["scale"].dtype == jnp.float32
    assert cast["params"]["stats_gadget"]["bias"].dtype == jnp.float32
    assert cast["params"]["proj"]["kernel"].dtype == jnp.bfloat16
    assert cast["params"]["head"]["kernel"].dtype == jnp.bfloat16
    # stats returned unconverted (always fp32)
    assert cast["batch_stats"]["stats_gadget"]["mean"].dtype == jnp.float32

    # the standalone predicate is exported and introspectable
    pred = amp.bn_predicate_from_batch_stats(variables["batch_stats"])
    assert pred.bn_module_paths == frozenset({"stats_gadget"})
    assert pred(("stats_gadget", "scale"))
    assert not pred(("proj", "kernel"))

    # a bare params tree (no model in hand) still rides the regex path
    bare = amp.cast_model(
        variables["params"], amp.resolve("O5"))
    assert bare["proj"]["kernel"].dtype == jnp.bfloat16


def test_cast_model_frozen_variables_and_root_bn():
    """Review follow-ups: FrozenDict variables take the auto-BN path
    (Mapping, not dict), and a bare-BatchNorm model's single-segment
    batch_stats mark the ROOT as BN."""
    import flax
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Dense(8, name="proj")(x)
            return nn.BatchNorm(use_running_average=not train,
                                name="odd_stats")(x)

    x = jnp.ones((2, 8))
    frozen = flax.core.freeze(Net().init(jax.random.PRNGKey(0), x))
    cast = amp.cast_model(frozen, amp.resolve("O5"))
    assert isinstance(cast, type(frozen))
    assert cast["params"]["odd_stats"]["scale"].dtype == jnp.float32
    assert cast["params"]["proj"]["kernel"].dtype == jnp.bfloat16
    assert cast["batch_stats"]["odd_stats"]["mean"].dtype == jnp.float32

    # root module IS the batchnorm: batch_stats has single-segment paths
    bn = nn.BatchNorm(use_running_average=True)
    v = bn.init(jax.random.PRNGKey(1), x)
    pred = amp.bn_predicate_from_batch_stats(v["batch_stats"])
    assert pred(("scale",)) and pred(("bias",))
    cast2 = amp.cast_model(v, amp.resolve("O5", keep_batchnorm_fp32=True))
    assert cast2["params"]["scale"].dtype == jnp.float32


def test_zero_fingerprint_catches_leaf_structure_swap():
    """Aggregate counts can coincide while the interleaved layout
    differs: swapping two equal-sized leaves must still fail the guard."""
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    a = {"w1": jnp.ones((4, 4)), "w2": jnp.zeros((16,)),
         "z": jnp.ones((3,))}
    # same sizes, different leaf order/shapes
    b = {"w1": jnp.ones((16,)), "w2": jnp.zeros((4, 4)),
         "z": jnp.ones((3,))}
    opt = DistributedFusedAdam(lr=1e-3, shard_count=1, chunk_elements=8)
    fp = opt.layout_fingerprint(a)
    opt.check_layout(fp, a)
    with pytest.raises(ValueError, match="layout mismatch"):
        opt.check_layout(fp, b)
