"""Elastic membership tests: the deterministic ZeRO re-shard (the
acceptance pin — gather(W-sharded state) == gather(reshard-to-W' state)
BITWISE for real trained state, fp32 masters and Adam moments included),
the snapshot-store re-shard restore path, the resilient_loop elastic
seam, the multiproc rendezvous + supervisor (real node_loss SIGKILL in a
2-process fleet, resumed at world 1), the inspect CLI, and the slow_node
straggler attribution through the PR 8 two-process merge fixture."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import parallel, resilience, telemetry
from apex_tpu.contrib.optimizers import DistributedFusedAdam
from apex_tpu.contrib.optimizers.zero import ZeroState, pack_layout
from apex_tpu.resilience import elastic
from apex_tpu.resilience.faults import FaultInjector

WORKER = os.path.join(os.path.dirname(__file__), "elastic_worker.py")


def tree_params(key=None):
    ks = jax.random.split(key or jax.random.PRNGKey(3), 3)
    # sizes deliberately NOT divisible by any world size in play, so
    # every bucket carries world-dependent padding
    return {"w1": jax.random.normal(ks[0], (37, 11)),
            "w2": jax.random.normal(ks[1], (501,)),
            "b": jax.random.normal(ks[2], (3,))}


def train_zero(world, params, *, steps=3, chunk=256):
    """Real ZeRO training at ``world`` on a device-subset mesh; returns
    (opt, final ZeroState, final params) with genuinely nonzero
    moments."""
    mesh = parallel.reform_mesh(world)
    opt = DistributedFusedAdam(lr=0.05, shard_count=world,
                               chunk_elements=chunk)
    state = opt.init(params)
    specs = opt.state_pspec()
    step = jax.jit(shard_map(
        opt.step, mesh=mesh, in_specs=(P(), P(), specs),
        out_specs=(P(), specs), check_vma=False))
    state = jax.device_put(state, jax.tree_util.tree_map(
        lambda sp: NamedSharding(mesh, sp), specs))
    for i in range(steps):
        ks = jax.random.split(jax.random.PRNGKey(100 + i), len(params))
        grads = {name: jax.random.normal(k, v.shape, jnp.float32)
                 for k, (name, v) in zip(ks, sorted(params.items()))}
        params, state = step(grads, params, state)
    return opt, state, params


# ---------------------------------------------------------------------------
# the acceptance pin: bitwise gather-compare on real trained state
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("src_w,dst_w", [(2, 1), (1, 2), (4, 2)])
def test_reshard_gather_bitwise(src_w, dst_w):
    params = tree_params()
    opt, state, _ = train_zero(src_w, params)
    src_fp = opt.layout_fingerprint(params)
    dst_fp = DistributedFusedAdam(
        shard_count=dst_w, chunk_elements=256).layout_fingerprint(params)
    src_spec = elastic.spec_for(params, src_fp)
    dst_spec = elastic.spec_for(params, dst_fp)
    out = elastic.reshard_state(state, src_spec, dst_spec)
    assert out.master.shape == (dst_fp["padded"],)
    for field in ("master", "exp_avg", "exp_avg_sq"):
        a = elastic.unshard(np.asarray(getattr(state, field)), src_spec)
        b = elastic.unshard(np.asarray(getattr(out, field)), dst_spec)
        np.testing.assert_array_equal(a, b, err_msg=field)
        assert np.any(a != 0), f"{field} trivially zero — test proves " \
            "nothing"
    assert int(np.asarray(out.step)) == int(np.asarray(state.step))


def test_reshard_across_chunk_change_bitwise():
    params = tree_params()
    opt, state, _ = train_zero(2, params, chunk=256)
    src_fp = opt.layout_fingerprint(params)
    src_spec = elastic.spec_for(params, src_fp)
    dst_fp = DistributedFusedAdam(
        shard_count=2, chunk_elements=1000).layout_fingerprint(params)
    # a real bucket-boundary change, not just a relabeled capacity
    assert dst_fp["n_buckets"] != src_fp["n_buckets"]
    dst_spec = elastic.spec_for(params, dst_fp)
    out = elastic.reshard_state(state, src_spec, dst_spec)
    np.testing.assert_array_equal(
        elastic.unshard(np.asarray(state.master), src_spec),
        elastic.unshard(np.asarray(out.master), dst_spec))


def test_resharded_state_continues_training_identically():
    """Continuing at the NEW world from re-sharded state produces the
    same parameters as continuing at the old world — the trajectory half
    of the ROADMAP item 4 acceptance, in-process."""
    params = tree_params()
    opt2, state2, params2 = train_zero(2, params, steps=2)
    fp2 = opt2.layout_fingerprint(params)
    fp1 = DistributedFusedAdam(
        shard_count=1, chunk_elements=256).layout_fingerprint(params)
    state1 = elastic.reshard_state(
        state2, elastic.spec_for(params, fp2),
        elastic.spec_for(params, fp1))

    def one_more(world, st, p):
        mesh = parallel.reform_mesh(world)
        opt = DistributedFusedAdam(lr=0.05, shard_count=world,
                                   chunk_elements=256)
        specs = opt.state_pspec()
        step = jax.jit(shard_map(
            opt.step, mesh=mesh, in_specs=(P(), P(), specs),
            out_specs=(P(), specs), check_vma=False))
        ks = jax.random.split(jax.random.PRNGKey(999), len(p))
        grads = {name: jax.random.normal(k, v.shape, jnp.float32)
                 for k, (name, v) in zip(ks, sorted(p.items()))}
        return step(grads, p, st)[0]

    pa = one_more(2, state2, params2)
    pb = one_more(1, ZeroState(*map(jnp.asarray, state1)), params2)
    for k in pa:
        np.testing.assert_array_equal(np.asarray(pa[k]),
                                      np.asarray(pb[k]), err_msg=k)


# ---------------------------------------------------------------------------
# classification + spec validation
# ---------------------------------------------------------------------------

def test_can_reshard_classification():
    params = tree_params()
    fp2 = DistributedFusedAdam(
        shard_count=2, chunk_elements=256).layout_fingerprint(params)
    fp4 = DistributedFusedAdam(
        shard_count=4, chunk_elements=256).layout_fingerprint(params)
    ok, reason = elastic.can_reshard(fp2, fp4)
    assert ok and "re-shardable" in reason
    ok, reason = elastic.can_reshard(fp2, dict(fp2))
    assert ok and "identical" in reason
    other = DistributedFusedAdam(
        shard_count=2, chunk_elements=256).layout_fingerprint(
        {"different": jnp.ones((8,))})
    ok, reason = elastic.can_reshard(fp2, other)
    assert not ok and "structurally incompatible" in reason
    ok, reason = elastic.can_reshard(None, fp2)
    assert not ok and "missing" in reason
    ok, reason = elastic.can_reshard({"a": 1}, fp2)
    assert not ok
    # the TYPED classification all callers branch on (never the strings)
    assert elastic.classify_reshard(fp2, fp4)[0] == elastic.RESHARDABLE
    assert elastic.classify_reshard(fp2, dict(fp2))[0] == elastic.IDENTICAL
    assert elastic.classify_reshard(fp2, other)[0] == elastic.STRUCTURAL
    assert elastic.classify_reshard({"a": 1}, fp2)[0] \
        == elastic.UNFINGERPRINTED
    assert elastic.classify_reshard(None, fp2)[0] \
        == elastic.UNFINGERPRINTED


def test_check_world_fingerprint_only():
    params = tree_params()
    fp2 = DistributedFusedAdam(
        shard_count=2, chunk_elements=256).layout_fingerprint(params)
    assert elastic.check_world(fp2, 2) == (True, "same world (2): "
                                           "plain restore")
    ok, reason = elastic.check_world(fp2, 4)
    assert ok and "re-shard 2 -> 4" in reason
    assert not elastic.check_world(fp2, 0)[0]
    assert not elastic.check_world(None, 2)[0]
    assert not elastic.check_world({"a": 1}, 2)[0]


def test_spec_for_rejects_wrong_params():
    params = tree_params()
    fp = DistributedFusedAdam(
        shard_count=2, chunk_elements=256).layout_fingerprint(params)
    with pytest.raises(ValueError, match="does not describe"):
        elastic.spec_for({"other": jnp.ones((5, 5))}, fp)


def test_reshard_tree_requires_a_zero_state():
    params = tree_params()
    spec = pack_layout(params, chunk_elements=256, shard_count=2)
    with pytest.raises(ValueError, match="no ZeroState"):
        elastic.reshard_tree({"just": np.ones(3)}, spec, spec)


def test_source_template_keeps_tree_paths():
    from apex_tpu.checkpoint import _structure_key
    params = tree_params()
    opt = DistributedFusedAdam(shard_count=2, chunk_elements=256)
    tmpl = (params, opt.init(params))
    spec = pack_layout(params, chunk_elements=256, shard_count=4)
    resized = elastic.source_template(tmpl, spec)
    assert _structure_key(resized) == _structure_key(tmpl)
    assert resized[1].master.shape == (spec["padded"],)


# ---------------------------------------------------------------------------
# snapshot-store integration
# ---------------------------------------------------------------------------

def test_reshard_restore_roundtrip_and_marker(tmp_path):
    params = tree_params()
    opt2, state2, params2 = train_zero(2, params, steps=2)
    fp2 = opt2.layout_fingerprint(params)
    mgr = resilience.SnapshotManager(str(tmp_path))
    mgr.save((params2, state2), step=2, layout=fp2)

    opt1 = DistributedFusedAdam(lr=0.05, shard_count=1,
                                chunk_elements=256)
    template = (params, opt1.init(params))
    with telemetry.capture() as col:
        found = elastic.reshard_restore(
            mgr, template, params=params, optimizer=opt1)
    assert found is not None and found.step == 2
    _, z1 = found.state
    fp1 = opt1.layout_fingerprint(params)
    np.testing.assert_array_equal(
        elastic.unshard(np.asarray(state2.master),
                        elastic.spec_for(params, fp2)),
        elastic.unshard(z1.master, elastic.spec_for(params, fp1)))
    marks = [e for e in col.snapshot()
             if e.name == "resilience/reshard"]
    assert len(marks) == 1
    assert marks[0].meta["from_world"] == 2
    assert marks[0].meta["to_world"] == 1

    # identical layout: plain restore, no marker
    found2 = elastic.reshard_restore(
        mgr, (params, opt2.init(params)), params=params, optimizer=opt2)
    assert found2 is not None and found2.step == 2


def test_reshard_restore_falls_back_across_layout_boundary(tmp_path):
    """An elastic fleet writes world-W then world-W' generations into
    ONE store. When the newest (same-layout) generation is corrupt, the
    corruption fallback must cross the layout boundary and re-shard the
    older-world generation — not fail fast on it."""
    from apex_tpu.resilience.snapshot import PAYLOAD
    params = tree_params()
    opt2, state2, params2 = train_zero(2, params, steps=2)
    opt1 = DistributedFusedAdam(lr=0.05, shard_count=1,
                                chunk_elements=256)
    mgr = resilience.SnapshotManager(str(tmp_path))
    mgr.save((params2, state2), step=2,
             layout=opt2.layout_fingerprint(params))
    # the re-formed world-1 fleet saved a newer generation...
    mgr.save((params2, elastic.reshard_state(
        state2,
        elastic.spec_for(params, opt2.layout_fingerprint(params)),
        elastic.spec_for(params, opt1.layout_fingerprint(params)))),
        step=4, layout=opt1.layout_fingerprint(params))
    # ...which then got damaged on disk
    gen_dir = tmp_path / "gen_00000001"
    with open(gen_dir / PAYLOAD, "r+b") as f:
        f.truncate(64)
    template = (params, opt1.init(params))
    with pytest.warns(UserWarning, match="skipping corrupt"):
        found = elastic.reshard_restore(
            mgr, template, params=params, optimizer=opt1)
    assert found is not None
    assert found.generation == 0 and found.step == 2
    _, z1 = found.state
    np.testing.assert_array_equal(
        elastic.unshard(np.asarray(state2.master),
                        elastic.spec_for(
                            params, opt2.layout_fingerprint(params))),
        elastic.unshard(z1.master,
                        elastic.spec_for(
                            params, opt1.layout_fingerprint(params))))


def test_restore_latest_message_names_the_reshard_recipe(tmp_path):
    """Satellite bugfix: the fast-fail message must print the re-shard
    recipe for a world mismatch, and say 'structurally incompatible'
    when the tree itself differs."""
    params = tree_params()
    opt2 = DistributedFusedAdam(shard_count=2, chunk_elements=256)
    fp2 = opt2.layout_fingerprint(params)
    mgr = resilience.SnapshotManager(str(tmp_path))
    mgr.save((params, opt2.init(params)), step=2, layout=fp2)

    fp1 = DistributedFusedAdam(
        shard_count=1, chunk_elements=256).layout_fingerprint(params)
    with pytest.raises(ValueError) as ei:
        mgr.restore_latest((params, opt2.init(params)), layout=fp1)
    msg = str(ei.value)
    assert "RE-SHARDABLE world mismatch" in msg
    assert "elastic" in msg and "inspect" in msg

    other_fp = DistributedFusedAdam(
        shard_count=2, chunk_elements=256).layout_fingerprint(
        {"other": jnp.ones((4, 4))})
    with pytest.raises(ValueError) as ei:
        mgr.restore_latest((params, opt2.init(params)), layout=other_fp)
    assert "STRUCTURALLY INCOMPATIBLE" in str(ei.value)


def test_resilient_loop_elastic_resume(tmp_path):
    """The loop seam in-process: a world-2 ZeRO run snapshots, then a
    world-1 loop with elastic= resumes through the re-shard and its
    continued trajectory matches a fresh world-1 run exactly."""
    params = tree_params()

    def build(world):
        mesh = parallel.reform_mesh(world)
        opt = DistributedFusedAdam(lr=0.05, shard_count=world,
                                   chunk_elements=256)
        specs = opt.state_pspec()
        sharded = shard_map(opt.step, mesh=mesh,
                            in_specs=(P(), P(), specs),
                            out_specs=(P(), specs), check_vma=False)

        @jax.jit
        def train(st, x):
            p, z = st
            loss, g = jax.value_and_grad(
                lambda p: sum(jnp.mean((l * x - 0.5) ** 2) for l in
                              jax.tree_util.tree_leaves(p)))(p)
            new_p, new_z = sharded(g, p, z)
            return (new_p, new_z), loss

        return opt, train

    def data(i):
        return jnp.asarray(
            np.random.default_rng([5, i]).uniform(0.5, 1.5), jnp.float32)

    losses = {}

    def run(world, steps, snap, tag, elastic_seam=True):
        opt, train = build(world)
        fp = opt.layout_fingerprint(params)
        seam = resilience.Elastic(opt, params) if elastic_seam else None
        losses[tag] = []
        return resilience.resilient_loop(
            lambda st, x, i: train(st, x),
            (params, opt.init(params)), data, steps=steps,
            snapshot_dir=snap, snapshot_every=2, layout=fp,
            elastic=seam, handle_signals=False,
            on_step=lambda i, st, loss: losses[tag].append(
                (i, float(loss))))

    run(1, 6, str(tmp_path / "fresh"), "fresh")           # baseline
    run(2, 3, str(tmp_path / "snap"), "w2")               # interrupted
    cont = run(1, 6, str(tmp_path / "snap"), "resumed")   # elastic
    assert cont.resumed_from is not None
    la = dict(losses["fresh"])
    for s, v in losses["resumed"]:
        assert la[s] == v, (s, la[s], v)


# ---------------------------------------------------------------------------
# rendezvous + supervisor
# ---------------------------------------------------------------------------

def test_rendezvous_membership(tmp_path):
    from apex_tpu.parallel import multiproc
    a = multiproc.Rendezvous(str(tmp_path / "r"), "0000")
    b = multiproc.Rendezvous(str(tmp_path / "r"), "0001")
    a.announce()
    assert a.world() == (1, 0)
    b.announce()
    assert a.members() == ["0000", "0001"]
    assert b.world() == (2, 1)
    assert b.wait_world(2, timeout_s=1) == (2, 1)
    b.leave()
    assert a.world() == (1, 0)
    # stale heartbeat == departed
    a.ttl_s = 0.05
    old = time.time() - 1.0
    os.utime(a._path("0000"), (old, old))
    assert a.members() == []
    a.heartbeat()   # refresh re-announces
    assert a.members() == ["0000"]
    a.ttl_s = 60.0
    with pytest.raises(TimeoutError, match="1/2 members"):
        a.wait_world(2, timeout_s=0.1)
    # observer mode (no member id): liveness calls are guarded no-ops
    obs = multiproc.Rendezvous(str(tmp_path / "r"))
    obs.heartbeat()
    obs.leave()
    assert obs.members() == ["0000"]


def test_run_elastic_substitution_and_world_env():
    from apex_tpu.parallel import multiproc
    assert multiproc._substitute(
        ["a-{rank}", "b-{world}"], 3, 8) == ["a-3", "b-8"]
    env = dict(os.environ)
    try:
        os.environ["APEX_TPU_WORLD"] = "4"
        os.environ["APEX_TPU_RANK"] = "2"
        assert multiproc.elastic_world() == (4, 2)
        del os.environ["APEX_TPU_WORLD"], os.environ["APEX_TPU_RANK"]
        os.environ.pop("NUM_PROCESSES", None)
        os.environ.pop("PROCESS_ID", None)
        assert multiproc.elastic_world() == (1, 0)
        # a PRESENT but malformed value must raise, not silently
        # degrade to a single-member world
        os.environ["APEX_TPU_WORLD"] = "2x"
        with pytest.raises(ValueError, match="malformed membership"):
            multiproc.elastic_world()
    finally:
        os.environ.clear()
        os.environ.update(env)


def test_node_loss_supervisor_resumes_at_world_1(tmp_path):
    """ROADMAP item 4 acceptance, end to end with REAL processes: a
    2-member fleet loses rank 1 to an injected node_loss SIGKILL
    mid-train, the survivor leaves cooperatively (exit 75 after its
    final snapshot), the supervisor re-forms at world 1, and the resumed
    run's post-resume loss trajectory matches a fresh same-layout
    world-1 run EXACTLY (the re-shard itself is pinned bitwise by
    test_reshard_gather_bitwise)."""
    from apex_tpu.parallel import multiproc
    env = dict(os.environ)
    env.pop("APEX_TPU_FAULT", None)
    env.pop("APEX_TPU_RANK", None)

    # fresh world-1 baseline
    fresh_env = dict(env, APEX_TPU_WORLD="1", APEX_TPU_RANK="0")
    p = subprocess.run(
        [sys.executable, WORKER, "--steps", "6",
         "--snap", str(tmp_path / "fresh"),
         "--out", str(tmp_path / "fresh.npz"), "--resume", "none"],
        env=fresh_env, capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stderr

    env["APEX_TPU_FAULT"] = "step:3:node_loss"   # default target rank 1
    logs = []
    rc = multiproc.run_elastic(
        [sys.executable, WORKER, "--steps", "6",
         "--snap", str(tmp_path / "snap-r{rank}"),
         "--out", str(tmp_path / "out-r{rank}.npz"),
         "--telemetry", str(tmp_path / "tel-r{rank}.jsonl"),
         "--resume", "auto", "--step-ms", "150"],
        world=2, rendezvous_dir=str(tmp_path / "rdzv"),
        grace_s=60.0, env=env, log=logs.append)
    assert rc == 0, "\n".join(logs)
    assert any("LOST" in ln for ln in logs)
    assert any("world 1" in ln for ln in logs)

    fresh = np.load(tmp_path / "fresh.npz")
    out = np.load(tmp_path / "out-r0.npz")
    assert int(out["world"]) == 1 and int(out["resumed_from"]) >= 0
    la = {int(s): v for s, v in fresh["losses"]}
    lb = {int(s): v for s, v in out["losses"]}
    assert lb, "resumed run observed no steps"
    for s, v in lb.items():
        assert la[s] == v, (s, la[s], v)
    for k in ("master", "exp_avg", "exp_avg_sq"):
        np.testing.assert_array_equal(fresh[k], out[k], err_msg=k)

    rows = [json.loads(ln)
            for ln in open(tmp_path / "tel-r0.jsonl")]
    marks = [r for r in rows if r["name"] == "resilience/reshard"]
    assert marks and marks[-1]["meta"]["from_world"] == 2
    assert marks[-1]["meta"]["to_world"] == 1
    assert any(r["name"] == "resilience/resume" for r in rows)


# ---------------------------------------------------------------------------
# inspect CLI
# ---------------------------------------------------------------------------

def test_inspect_cli(tmp_path, capsys):
    from apex_tpu.resilience import cli
    params = tree_params()
    opt = DistributedFusedAdam(shard_count=2, chunk_elements=256)
    mgr = resilience.SnapshotManager(str(tmp_path / "snap"))
    mgr.save((params, opt.init(params)), step=2,
             layout=opt.layout_fingerprint(params))

    assert cli.main(["inspect", str(tmp_path / "snap")]) == 0
    out = capsys.readouterr().out
    assert "step      2" in out and "world   2" in out \
        and "complete" in out

    assert cli.main(["inspect", str(tmp_path / "snap"),
                     "--check", "4"]) == 0
    out = capsys.readouterr().out
    assert "re-shard 2 -> 4 possible" in out

    # a store whose snapshots carry no fingerprint cannot re-shard: 3
    mgr2 = resilience.SnapshotManager(str(tmp_path / "bare"))
    mgr2.save({"w": jnp.ones(3)}, step=1)
    assert cli.main(["inspect", str(tmp_path / "bare"),
                     "--check", "2"]) == 3
    capsys.readouterr()

    # --json parses and carries the check verdict
    assert cli.main(["inspect", str(tmp_path / "snap"), "--check", "1",
                     "--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["rows"][0]["reshard_to_1"][0] is True

    assert cli.main(["inspect", str(tmp_path / "nothing")]) == 2


# ---------------------------------------------------------------------------
# telemetry: reshard section + straggler attribution of slow_node
# ---------------------------------------------------------------------------

def test_summarize_reports_reshard():
    ev = [{"name": "resilience/resume", "value": 1.0, "ts": 1.0,
           "step": 4, "meta": {"generation": 1, "step": 4}},
          {"name": "resilience/reshard", "value": 1.0, "ts": 1.0,
           "step": 4, "meta": {"from_world": 2, "to_world": 1,
                               "generation": 1}}]
    agg = telemetry.summarize(ev)
    assert agg["resilience"]["reshards"] == [
        {"step": 4, "from_world": 2, "to_world": 1, "generation": 1}]
    text = telemetry.format_summary(agg)
    assert "elastic reshard world 2 -> 1 at step 4" in text


def _straggler_stream(path, rank, spec, steps=6):
    """One simulated fleet member: resilient_loop + per-step dispatch
    spans + step/time_s points, with the fault injector from ``spec``
    firing at each step top (the PR 8 merge fixture, slow_node added)."""
    from apex_tpu import trace
    inj = FaultInjector.parse(spec) if spec else None
    with telemetry.capture() as col:
        trace.enable()
        try:
            for i in range(steps):
                t0 = time.perf_counter()
                if inj is not None:
                    inj.fire(i)
                time.sleep(0.003)
                t1 = time.perf_counter()
                trace.emit_span("step/dispatch", t0, t1, step=i)
                telemetry.record("step/time_s", t1 - t0, step=i)
        finally:
            trace.disable()
        events = col.drain()
    from apex_tpu.telemetry.export import write_jsonl
    write_jsonl(path, events)


def test_slow_node_named_by_straggler_attribution(tmp_path,
                                                 monkeypatch):
    """The satellite contract: a slow_node-injected delay on rank 1
    shows up in the trace merge's straggler table NAMING that
    process."""
    from apex_tpu.telemetry import merge
    spec = "step:2:slow_node:60:1"
    monkeypatch.setenv("APEX_TPU_RANK", "0")
    _straggler_stream(str(tmp_path / "run-p0.jsonl"), 0, spec)
    monkeypatch.setenv("APEX_TPU_RANK", "1")
    _straggler_stream(str(tmp_path / "run-p1.jsonl"), 1, spec)

    merged, offsets = merge.merge_files(
        [str(tmp_path / "run-p0.jsonl"), str(tmp_path / "run-p1.jsonl")])
    agg = telemetry.summarize(merged)
    st = agg["stragglers"]
    assert st["worst"]["process"] == "p1"
    # with two processes the median is their mean, so the injected
    # 60 ms surfaces as ~30 ms of max-minus-median skew
    assert st["skew_s"]["max"] >= 0.02
    fams = [a["family"] for a in st.get("attribution", [])]
    assert "step/dispatch" in fams


def test_trainer_notify_resume_world_event():
    from apex_tpu.trainer.builder import Trainer, TrainerConfig
    tr = Trainer(fn=lambda s, b: (s, None),
                 traced_fn=lambda s, b: (s, None),
                 config=TrainerConfig(), donation=None)
    with telemetry.capture() as col:
        tr.notify_resume(7, world=1, from_world=2)
        events = [e for e in col.drain() if e.name == "trainer/resume"]
    assert tr.step_index == 7
    assert len(events) == 1
    assert events[0].meta == {"world": 1, "from_world": 2}
