"""TRUE multi-process distributed test (VERDICT r3 #5): 2 subprocesses x 4
XLA-CPU devices run ``multiproc.initialize_distributed`` -> jax.distributed
-> one DDP+ZeRO step over the GLOBAL 8-device mesh, and must agree with
each other AND with the same program on this process's single-process
8-device virtual mesh — the analog of the reference's launched tier
(tests/distributed/DDP/ddp_race_condition_test.py,
tests/L1/cross_product_distributed/run.sh)."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = os.path.join(os.path.dirname(__file__), "distributed_worker.py")
KEYS = ("grad_norm", "param_sum", "param_norm", "master_psum",
        # hybrid dwu_group_size form: (group=2, data=4) mesh whose
        # cross-group allreduce axis SPANS the two processes
        "hyb_param_sum", "hyb_param_norm", "hyb_master_psum",
        # expert parallelism: the MoE token all_to_all over the global
        # ('expert',) axis crosses the process boundary
        "moe_out_sum", "moe_out_norm", "moe_router_gnorm")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(pid: int, port: int, nproc: int, local_dev: int):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=f"--xla_force_host_platform_device_count={local_dev}",
        COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
        NUM_PROCESSES=str(nproc),
        PROCESS_ID=str(pid),
    )
    return subprocess.Popen(
        [sys.executable, WORKER, "--global-devices",
         str(nproc * local_dev)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _parse(stdout: str):
    for line in stdout.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    return None


def test_two_process_ddp_zero_matches_single_process():
    nproc, local_dev = 2, 4
    port = _free_port()
    try:
        procs = [_spawn(i, port, nproc, local_dev) for i in range(nproc)]
    except OSError as e:  # platform forbids subprocess
        pytest.skip(f"cannot spawn subprocesses: {e}")

    # Drain both workers' pipes CONCURRENTLY: the processes are coupled by
    # collectives, and a sequential communicate() would stop reading the
    # other worker's pipes — if that one fills its ~64 KB stderr buffer it
    # blocks mid-step and deadlocks both until the timeout.
    import concurrent.futures as cf
    with cf.ThreadPoolExecutor(len(procs)) as ex:
        futs = [ex.submit(p.communicate, timeout=600) for p in procs]
        results = []
        for p, f in zip(procs, futs):
            try:
                results.append(f.result())
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("multi-process worker timed out "
                            "(coordination hang?)")

    outs = []
    for p, (stdout, stderr) in zip(procs, results):
        if "Multiprocess computations aren't implemented" in stderr:
            # environment capability, not a code failure: this jaxlib's
            # CPU backend has no cross-process collectives (added in
            # newer releases); the same program IS covered single-process
            # on the 8-device virtual mesh throughout the suite
            pytest.skip("CPU backend lacks multi-process collectives "
                        "in this jaxlib")
        assert p.returncode == 0, (
            f"worker failed (rc={p.returncode}):\n{stderr[-3000:]}")
        out = _parse(stdout)
        assert out is not None, f"no RESULT line in worker stdout:\n{stdout}"
        outs.append(out)

    # both processes see the full global mesh and identical replicated
    # results (cross-process collectives actually ran)
    for out in outs:
        assert out["local_devices"] == local_dev
    for k in KEYS:
        np.testing.assert_allclose(outs[0][k], outs[1][k], rtol=1e-6)

    # ... and the 2x4-process program equals the 8-device single-process
    # program (this pytest process's virtual mesh, set up by conftest)
    import importlib.util
    spec = importlib.util.spec_from_file_location("distributed_worker",
                                                  WORKER)
    w = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(w)
    want = w.run(nproc * local_dev)
    for k in KEYS:
        np.testing.assert_allclose(outs[0][k], want[k], rtol=1e-5,
                                   err_msg=f"{k} differs between 2-process "
                                   "and single-process execution")
    # hybrid step numerically equals the dense FusedAdam step on the
    # mean gradient (sum-of-params anchor; leaf-wise parity is covered
    # single-process) in BOTH processes
    for out in outs:
        assert out["hyb_dense_diff"] < 1e-3, out
    # EP forward across processes equals the single-device dense module
    for out in outs:
        assert out["moe_dense_diff"] < 1e-3, out
