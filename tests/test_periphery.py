"""Periphery tests: fp16_utils legacy API, RNN stacks, weight norm
reparameterization, ASP 2:4 sparsity, pyprof analysis — ports of the
reference's run_fp16util, RNN usage, and the ASP checkpoint-continuity tests
(apex/contrib/sparsity/test/checkpointing_test_part1/2.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import fp16_utils, reparameterization, sparsity, pyprof
from apex_tpu import optimizers
from apex_tpu import rnn as apex_rnn


# ---------------------------------------------------------------------------
# fp16_utils
# ---------------------------------------------------------------------------

def test_convert_network_keeps_bn():
    params = {"Dense_0": {"kernel": jnp.ones((4, 4))},
              "BatchNorm_0": {"scale": jnp.ones((4,))}}
    half = fp16_utils.network_to_half(params)
    assert half["Dense_0"]["kernel"].dtype == jnp.float16
    assert half["BatchNorm_0"]["scale"].dtype == jnp.float32
    b16 = fp16_utils.network_to_bfloat16(params)
    assert b16["Dense_0"]["kernel"].dtype == jnp.bfloat16


def test_prep_and_copy_master_params():
    params = {"w": jnp.ones((8,), jnp.float16)}
    model, master = fp16_utils.prep_param_lists(params)
    assert master["w"].dtype == jnp.float32
    master = {"w": master["w"] * 0.5}
    model = fp16_utils.master_params_to_model_params(model, master)
    assert model["w"].dtype == jnp.float16
    np.testing.assert_allclose(np.asarray(model["w"], np.float32), 0.5)


def test_clip_grad_norm():
    grads = {"a": jnp.full((100,), 3.0), "b": jnp.full((44,), -3.0)}
    clipped, total = fp16_utils.clip_grad_norm(grads, 1.0)
    np.testing.assert_allclose(float(total), 3.0 * np.sqrt(144), rtol=1e-5)
    gnorm_after, _ = __import__("apex_tpu").ops.multi_tensor_l2norm(clipped)
    np.testing.assert_allclose(float(gnorm_after), 1.0, rtol=1e-4)


def test_fp16_optimizer_end_to_end():
    params = {"w": jnp.ones((16,), jnp.float16)}

    def loss_fn(p, x):
        return jnp.mean((p["w"].astype(jnp.float32) * x) ** 2)

    opt = fp16_utils.FP16_Optimizer(
        optimizers.FusedSGD(lr=0.1), params, dynamic_loss_scale=True,
        dynamic_loss_args={"init_scale": 2.0 ** 8})
    x = jnp.ones((16,))
    for _ in range(5):
        opt.backward(loss_fn, x)
        opt.step()
    assert float(jnp.abs(opt.model_params["w"]).max()) < 1.0
    # checkpoint round-trip
    sd = opt.state_dict()
    opt2 = fp16_utils.FP16_Optimizer(
        optimizers.FusedSGD(lr=0.1), params, dynamic_loss_scale=True)
    opt2.load_state_dict(sd)
    np.testing.assert_array_equal(
        np.asarray(opt2.master_params["w"]),
        np.asarray(opt.master_params["w"]))


def test_fp16_optimizer_overflow_skips():
    params = {"w": jnp.ones((4,), jnp.float16)}
    opt = fp16_utils.FP16_Optimizer(
        optimizers.FusedSGD(lr=0.1), params, dynamic_loss_scale=True,
        dynamic_loss_args={"init_scale": 4.0})
    before = np.asarray(opt.master_params["w"]).copy()
    opt.update_master_grads({"w": jnp.full((4,), np.inf, jnp.float16)})
    assert opt.overflow
    opt.step()
    np.testing.assert_array_equal(np.asarray(opt.master_params["w"]), before)
    assert opt.loss_scale == 2.0


# ---------------------------------------------------------------------------
# RNN
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("ctor", [apex_rnn.LSTM, apex_rnn.GRU,
                                  apex_rnn.Tanh, apex_rnn.ReLU,
                                  apex_rnn.mLSTM])
def test_rnn_shapes(ctor):
    m = ctor(input_size=8, hidden_size=16, num_layers=2)
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 10, 8))
    params = m.init(jax.random.PRNGKey(1), x)
    y = m.apply(params, x)
    assert y.shape == (3, 10, 16)


def test_rnn_bidirectional():
    m = apex_rnn.LSTM(input_size=8, hidden_size=16, bidirectional=True)
    x = jax.random.normal(jax.random.PRNGKey(2), (3, 10, 8))
    params = m.init(jax.random.PRNGKey(3), x)
    y = m.apply(params, x)
    assert y.shape == (3, 10, 32)


def test_rnn_grads_flow():
    m = apex_rnn.GRU(input_size=4, hidden_size=8)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 6, 4))
    params = m.init(jax.random.PRNGKey(5), x)
    g = jax.grad(lambda p: jnp.sum(m.apply(p, x) ** 2))(params)
    total = sum(float(jnp.abs(l).sum())
                for l in jax.tree_util.tree_leaves(g))
    assert total > 0


# ---------------------------------------------------------------------------
# reparameterization
# ---------------------------------------------------------------------------

def test_weight_norm_roundtrip():
    params = {"layer": {"kernel": jax.random.normal(jax.random.PRNGKey(6),
                                                    (8, 4)),
                        "bias": jnp.zeros((4,))}}
    wn = reparameterization.apply_weight_norm(params)
    assert "wn_g" in wn["layer"]["kernel"]
    back = reparameterization.remove_weight_norm(wn)
    np.testing.assert_allclose(np.asarray(back["layer"]["kernel"]),
                               np.asarray(params["layer"]["kernel"]),
                               rtol=1e-5, atol=1e-6)
    # bias untouched
    assert back["layer"]["bias"].shape == (4,)


def test_weight_norm_grad_decomposition():
    params = {"kernel": jax.random.normal(jax.random.PRNGKey(7), (6, 3))}
    wn = reparameterization.apply_weight_norm(params)
    assert set(wn["kernel"].keys()) == {"wn_g", "wn_v"}

    def loss(wnp):
        w = reparameterization.reparameterize(wnp)["kernel"]
        return jnp.sum(jnp.sin(w))

    g = jax.grad(loss)(wn)
    assert g["kernel"]["wn_g"].shape == (1, 3)
    assert g["kernel"]["wn_v"].shape == (6, 3)


# ---------------------------------------------------------------------------
# sparsity (ASP)
# ---------------------------------------------------------------------------

def test_m4n2_mask():
    w = jnp.asarray([[0.1, -0.5, 0.3, 0.01, 1.0, 0.2, -0.8, 0.05]])
    m = sparsity.m4n2_mask_1d(w)
    np.testing.assert_array_equal(
        np.asarray(m), [[0, 1, 1, 0, 1, 0, 1, 0]])


def test_asp_workflow_and_checkpoint():
    params = {"dense": {"kernel": jax.random.normal(jax.random.PRNGKey(8),
                                                    (16, 8)),
                        "bias": jnp.ones((8,))},
              "norm": {"scale": jnp.ones((8,))}}
    asp = sparsity.ASP()
    pruned, sopt = asp.init_model_for_pruning(
        params, optimizers.FusedSGD(lr=0.1))
    # kernel 50% sparse, bias/norm untouched
    k = np.asarray(pruned["dense"]["kernel"])
    assert (k == 0).mean() == 0.5
    np.testing.assert_array_equal(np.asarray(pruned["norm"]["scale"]), 1.0)

    # sparsity survives optimizer steps
    st = sopt.init(pruned)
    g = jax.tree.map(jnp.ones_like, pruned)
    p2, st = sopt.step(g, pruned, st)
    k2 = np.asarray(p2["dense"]["kernel"])
    assert ((k2 == 0) == (k == 0)).all()

    # checkpoint continuity (reference checkpointing_test_part1/2)
    sd = asp.state_dict()
    asp2 = sparsity.ASP()
    asp2.load_state_dict(sd)
    np.testing.assert_array_equal(
        np.asarray(asp2.masks["dense"]["kernel"]),
        np.asarray(asp.masks["dense"]["kernel"]))


def test_prune_for_serving_one_shot():
    """The serving entry point: one-shot dense -> 2:4, no optimizer/
    workflow state — masked kernels keep <= 2 of 4 along the last
    axis, non-kernel leaves come back bitwise."""
    params = {"dense": {"kernel": jax.random.normal(jax.random.PRNGKey(9),
                                                    (16, 8)),
                        "bias": jnp.ones((8,))},
              "norm": {"scale": jnp.ones((8,))}}
    pruned = sparsity.prune_for_serving(params)
    k = np.asarray(pruned["dense"]["kernel"])
    assert (k == 0).mean() == 0.5
    groups = (k.reshape(-1) != 0).reshape(-1, 4)
    assert (groups.sum(axis=1) == 2).all()
    # surviving weights are the dense values, not rescaled
    dense = np.asarray(params["dense"]["kernel"])
    assert (k[k != 0] == dense[k != 0]).all()
    np.testing.assert_array_equal(np.asarray(pruned["dense"]["bias"]),
                                  1.0)
    np.testing.assert_array_equal(np.asarray(pruned["norm"]["scale"]),
                                  1.0)


# ---------------------------------------------------------------------------
# pyprof
# ---------------------------------------------------------------------------

def test_pyprof_analyze():
    def f(a, b):
        return (a @ b).sum()

    a = jnp.ones((128, 128))
    stats = pyprof.analyze(f, a, a)
    # 128^3 * 2 flops for the matmul (+ reduce)
    assert stats["flops"] is not None and stats["flops"] >= 2 * 128 ** 3
    report = pyprof.format_report(stats, peak_flops=197e12)
    assert "flops" in report


def test_pyprof_annotate():
    @pyprof.annotate("my_op")
    def f(x):
        return x * 2

    y = jax.jit(f)(jnp.ones((4,)))
    np.testing.assert_array_equal(np.asarray(y), 2.0)


def test_pyprof_parse_synthetic(tmp_path):
    """Chrome-trace parsing: metadata joins, device-lane detection,
    per-op and per-category aggregation (reference parse/ + prof/)."""
    import gzip
    import json

    trace = {"traceEvents": [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "tid": 7, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "python"}},
        {"ph": "X", "pid": 1, "tid": 7, "name": "fusion.1",
         "ts": 0, "dur": 50, "args": {"long_name": "jit(f)/dot_general"}},
        {"ph": "X", "pid": 1, "tid": 7, "name": "convolution.2",
         "ts": 60, "dur": 100},
        {"ph": "X", "pid": 1, "tid": 7, "name": "convolution.2",
         "ts": 170, "dur": 100},
        {"ph": "X", "pid": 2, "tid": 1, "name": "host_python_call",
         "ts": 0, "dur": 1000},
    ]}
    p = tmp_path / "t.trace.json.gz"
    with gzip.open(p, "wt") as f:
        json.dump(trace, f)

    tr = pyprof.load_trace(str(tmp_path))
    assert len(tr.events) == 4
    dev = tr.device_events()
    assert len(dev) == 3  # host python event excluded
    assert tr.total_device_time_us() == 250
    ops = tr.by_op()
    assert ops[0]["op"] == "convolution.2" and ops[0]["count"] == 2
    assert abs(ops[0]["pct"] - 80.0) < 1e-6
    cats = tr.by_category()
    assert cats[0]["category"] == "conv"
    assert {"conv", "fusion"} == {c["category"] for c in cats}
    assert dev[0].long_name == "jit(f)/dot_general"

    report = pyprof.summarize_trace(str(tmp_path))
    assert "convolution.2" in report and "conv" in report


def test_pyprof_categorize():
    assert pyprof.categorize("fusion.dot.3") == "matmul"
    assert pyprof.categorize("all-reduce.1") == "collective"
    assert pyprof.categorize("copy.4") == "data-movement"
    assert pyprof.categorize("wat") == "other"


def test_pyprof_capture_roundtrip(tmp_path):
    """End-to-end: capture a real jax.profiler trace and parse it back."""
    logdir = str(tmp_path / "trace")
    with pyprof.trace(logdir):
        jax.block_until_ready(jax.jit(lambda x: x @ x)(jnp.ones((64, 64))))
    from apex_tpu.pyprof.parse import find_trace_files
    files = find_trace_files(logdir)
    assert files, "profiler produced no trace file"
    tr = pyprof.load_trace(logdir)
    assert len(tr.events) > 0


def test_trace_leaf_filtering(tmp_path):
    """Container events (jit_ wrappers, while bodies) nesting leaf kernels
    on the same lane must not double-count device time (r2 fix: the r1
    ResNet-50 summary showed the jit_/while containers as 50% 'other')."""
    import gzip
    import json
    trace = {"traceEvents": [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        # container: whole-step wrapper enclosing both kernels
        {"ph": "X", "pid": 1, "tid": 7, "name": "jit_train_step",
         "ts": 0, "dur": 300},
        {"ph": "X", "pid": 1, "tid": 7, "name": "while.4",
         "ts": 0, "dur": 300},
        {"ph": "X", "pid": 1, "tid": 7, "name": "convolution.1",
         "ts": 10, "dur": 100},
        {"ph": "X", "pid": 1, "tid": 7, "name": "fusion.9",
         "ts": 120, "dur": 80},
    ]}
    p = tmp_path / "t.trace.json.gz"
    with gzip.open(p, "wt") as f:
        json.dump(trace, f)

    tr = pyprof.load_trace(str(tmp_path))
    leaves = tr.leaf_device_events()
    assert sorted(e.name for e in leaves) == ["convolution.1", "fusion.9"]
    assert tr.total_device_time_us() == 180
    cats = {c["category"]: c for c in tr.by_category()}
    assert "other" not in cats          # no container leakage
    assert abs(cats["conv"]["pct"] - 100 * 100 / 180) < 1e-6


def test_sparsity_2d_patterns():
    """2d m:n masks (sparse_masklib mn_2d_best / mn_2d_greedy parity):
    every 4x4 block keeps exactly 2 per row AND 2 per column — so the
    TRANSPOSE is also 2:4 sparse (the DGRAD property) — and 'best' keeps
    at least as much magnitude as greedy."""
    from apex_tpu import sparsity

    w = jax.random.normal(jax.random.PRNGKey(80), (16, 32))

    best = np.asarray(sparsity.m4n2_mask_2d_best(w))
    bb = best.reshape(4, 4, 8, 4).transpose(0, 2, 1, 3)
    # exhaustive: exactly 2 per row AND per column in every 4x4 block
    assert (bb.sum(axis=-1) == 2).all()
    assert (bb.sum(axis=-2) == 2).all()

    greedy = np.asarray(sparsity.m4n2_mask_2d_greedy(w))
    gb = greedy.reshape(4, 4, 8, 4).transpose(0, 2, 1, 3)
    # greedy never exceeds the quotas but (like the reference, which does
    # not backtrack) may under-fill a row/column when magnitudes collide
    assert (gb.sum(axis=-1) <= 2).all()
    assert (gb.sum(axis=-2) <= 2).all()
    assert (gb.sum(axis=-1) >= 1).all()

    aw = np.abs(np.asarray(w))
    assert (aw * best).sum() >= (aw * greedy).sum() - 1e-5


def test_sparsity_create_mask_ranks():
    """create_mask dispatches rank 1-4 like the reference and yields 50%
    density with valid 2:4 groups along the PRUNED axis (last for rank
    1-3; input-channel — axis 2 in flax conv layout — for rank 4)."""
    from apex_tpu import sparsity

    for shape in [(16,), (8, 16), (2, 4, 16)]:
        w = jax.random.normal(jax.random.PRNGKey(81), shape)
        m = np.asarray(sparsity.create_mask(w, "m4n2_1d"))
        assert m.shape == shape
        assert abs(m.mean() - 0.5) < 1e-6
        groups = m.reshape(-1, 4)
        assert (groups.sum(axis=1) == 2).all()

    # 4d conv kernel (h, w, in, out): 2:4 groups run along `in`
    w = jax.random.normal(jax.random.PRNGKey(82), (3, 3, 8, 16))
    m = np.asarray(sparsity.create_mask(w, "m4n2_1d"))
    assert m.shape == (3, 3, 8, 16)
    groups = m.transpose(0, 1, 3, 2).reshape(-1, 4)
    assert (groups.sum(axis=1) == 2).all()

    with pytest.raises(ValueError):
        sparsity.create_mask(jnp.ones((8, 8)), "bogus")


def test_asp_2d_pattern_on_conv_model():
    """ASP with a 2d block calculator handles 4d conv kernels via the rank
    dispatcher (r2 review: the calculators must not dead-end on non-2d
    leaves)."""
    from apex_tpu import sparsity

    params = {"conv": {"kernel": jax.random.normal(
        jax.random.PRNGKey(83), (3, 3, 8, 16))},
        "dense": {"kernel": jax.random.normal(
            jax.random.PRNGKey(84), (16, 8))}}
    asp = sparsity.ASP(mask_calculator=sparsity.m4n2_mask_2d_best)
    pruned = asp.init_model_for_pruning(params)
    for key in ("conv", "dense"):
        k = np.asarray(pruned[key]["kernel"])
        assert (k == 0).mean() == 0.5, key


def test_sparsity_1d_best_keeps_top_magnitude():
    from apex_tpu import sparsity

    w = jnp.asarray([[0.1, -5.0, 3.0, 0.2, 7.0, 0.0, -0.5, 2.0]])
    m = np.asarray(sparsity.mn_mask_1d(w, 4, 2))
    np.testing.assert_array_equal(m, [[0, 1, 1, 0, 1, 0, 0, 1]])
