"""API-surface parity tests: the public names a reference (apex) user reaches
for must exist and behave (SURVEY.md §2 component inventory)."""

import jax
import jax.numpy as jnp
import pytest


def test_multi_tensor_applier_funnel():
    """multi_tensor_applier(op, noop, tensor_lists, *args) dispatches to the
    functional ops and folds overflow into the noop flag
    (reference multi_tensor_apply.py:3-30)."""
    from apex_tpu.multi_tensor_apply import multi_tensor_applier
    from apex_tpu.ops.multi_tensor import multi_tensor_scale

    tree = [jnp.ones((4,)), jnp.full((3,), 2.0)]
    noop = jnp.asarray(False)
    out, flag = multi_tensor_applier(multi_tensor_scale, noop, [tree], 0.5)
    assert float(out[0][0]) == 0.5 and float(out[1][0]) == 1.0
    assert not bool(flag)

    bad = [jnp.array([jnp.inf])]
    _, flag = multi_tensor_applier(multi_tensor_scale, noop, [bad], 1.0)
    assert bool(flag)

    # pre-set noop flag stays set (accumulation contract)
    _, flag = multi_tensor_applier(multi_tensor_scale, jnp.asarray(True),
                                   [tree], 1.0)
    assert bool(flag)


def test_multi_tensor_applier_adam():
    from apex_tpu.multi_tensor_apply import multi_tensor_applier
    from apex_tpu.ops.multi_tensor import multi_tensor_adam

    g = [jnp.ones((8,))]
    p = [jnp.zeros((8,))]
    m = [jnp.zeros((8,))]
    v = [jnp.zeros((8,))]
    new_p, new_m, new_v = multi_tensor_applier(
        multi_tensor_adam, None, [g, p, m, v],
        lr=0.1, beta1=0.9, beta2=0.999, eps=1e-8, step=1)
    assert float(new_p[0][0]) != 0.0


def test_amp_scale_loss_context_manager():
    """with amp.scale_loss(loss, opt, state) as scaled: (handle.py:16-158)."""
    from apex_tpu import amp, optimizers

    opt = optimizers.FusedAdam(lr=0.1)
    aopt = amp.AmpOptimizer(opt, amp.resolve("O5"))
    params = {"w": jnp.ones((4,), jnp.bfloat16)}
    state = aopt.init(params)
    loss = jnp.asarray(2.0)

    with amp.scale_loss(loss, aopt, state) as scaled:
        expected = float(loss) * float(state.scaler.loss_scale[0])
        assert float(scaled) == expected

    # plain-call form also usable (idiomatic JAX), incl. loss composition
    sl = amp.scale_loss(loss, aopt, state)
    assert float(sl.value) == expected
    assert float(2.0 * sl) == 2.0 * expected
    assert float(sl + 1.0) == expected + 1.0
    assert float(1.0 + sl) == expected + 1.0
    assert float(sl - 1.0) == expected - 1.0
    assert float(-sl) == -expected
    assert float(sl / 2.0) == expected / 2.0
    assert float(sl) == expected

    # missing state errors with migration guidance
    with pytest.raises(TypeError):
        amp.scale_loss(loss, aopt)
    # reference-style positional loss_id as 3rd arg also gets the guidance
    with pytest.raises(TypeError):
        amp.scale_loss(loss, aopt, 0)


def test_amp_promote_function_identity():
    from apex_tpu import amp

    @amp.promote_function
    def f(a, b):
        return a + b

    out = f(jnp.ones((2,), jnp.bfloat16), jnp.ones((2,), jnp.float32))
    assert out.dtype == jnp.float32  # jnp widest-wins promotion
    amp.register_promote_function("jax.numpy", "add")  # no-op, must not raise


def test_contrib_deprecated_optimizers_exported():
    from apex_tpu.contrib import optimizers as co

    opt = co.FusedAdam({"w": jnp.zeros((4,))}, lr=0.1)
    grads = {"w": jnp.ones((4,))}
    new_params = opt.step(grads=grads)
    assert float(new_params["w"][0]) != 0.0


def test_fast_mask_softmax_dropout_reference_signature():
    """Positional call parity with the reference
    (mask_softmax_dropout_func.py:8)."""
    from apex_tpu.contrib import multihead_attn as mha

    scores = jnp.zeros((2, 4, 4))
    # (is_training, heads, inputs, pad_mask, mask_additive, dropout_prob)
    p = mha.fast_mask_softmax_dropout_func(False, 4, scores, None, False, 0.1)
    assert jnp.allclose(p.sum(-1), 1.0, atol=1e-6)

    # boolean padding mask: masked columns get zero probability
    pad = jnp.zeros((2, 4, 4), bool).at[:, :, -1].set(True)
    p = mha.fast_mask_softmax_dropout_func(False, 4, scores, pad, False, 0.0)
    assert jnp.all(p[:, :, -1] == 0.0)
    assert jnp.allclose(p.sum(-1), 1.0, atol=1e-6)

    # additive mask path
    add = jnp.where(pad, -1e9, 0.0)
    p2 = mha.fast_mask_softmax_dropout_func(False, 4, scores, add, True, 0.0)
    assert jnp.allclose(p, p2, atol=1e-6)

    # training dropout requires an rng and zeroes some probs
    rng = jax.random.PRNGKey(0)
    p3 = mha.fast_mask_softmax_dropout_func(True, 4, scores, None, False,
                                            0.5, rng=rng)
    assert bool((p3 == 0.0).any())
