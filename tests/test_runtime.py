"""Native host-runtime tests: C++ flatten/unflatten and augmentation vs
numpy references; prefetch loader ordering/termination."""

import numpy as np
import pytest

from apex_tpu import runtime


def test_native_builds():
    assert runtime.native_available(), (
        f"host runtime failed to build: {runtime._build_err}")
    assert runtime._load().apex_host_runtime_version() == 1


def test_flatten_unflatten_roundtrip():
    rng = np.random.default_rng(0)
    arrays = [rng.standard_normal((17, 5)).astype(np.float32),
              rng.integers(0, 255, (33,), dtype=np.uint8),
              rng.standard_normal((2, 3, 4)).astype(np.float64)]
    flat = runtime.flatten_arrays(arrays)
    assert flat.nbytes == sum(a.nbytes for a in arrays)
    back = runtime.unflatten_array(flat, arrays)
    for a, b in zip(arrays, back):
        np.testing.assert_array_equal(a, b)


def test_flatten_matches_numpy_concat():
    rng = np.random.default_rng(1)
    arrays = [rng.standard_normal((100,)).astype(np.float32)
              for _ in range(7)]
    flat = runtime.flatten_arrays(arrays)
    want = np.concatenate([a.view(np.uint8) for a in arrays])
    np.testing.assert_array_equal(flat, want)


def test_augment_batch_matches_numpy():
    rng = np.random.default_rng(2)
    imgs = rng.integers(0, 256, (4, 40, 40, 3), dtype=np.uint8)
    crop = np.stack([rng.integers(0, 8, 4), rng.integers(0, 8, 4)], 1)
    flip = np.asarray([0, 1, 0, 1], np.uint8)
    got = runtime.augment_batch(imgs, (32, 32), crop, flip)

    mean, std = runtime.IMAGENET_MEAN, runtime.IMAGENET_STD
    for i in range(4):
        y0, x0 = crop[i]
        ref = imgs[i, y0:y0 + 32, x0:x0 + 32].astype(np.float32) / 255.0
        if flip[i]:
            ref = ref[:, ::-1]
        ref = (ref - mean) / std
        np.testing.assert_allclose(got[i], ref, rtol=1e-5, atol=1e-6)


def test_prefetch_loader():
    src = iter(range(20))
    loader = runtime.PrefetchLoader(src, transform=lambda x: x * 2,
                                    depth=4, workers=1)
    out = list(loader)
    assert out == [x * 2 for x in range(20)]


def test_prefetch_loader_multiworker_complete():
    src = iter(range(50))
    loader = runtime.PrefetchLoader(src, depth=8, workers=3)
    out = sorted(loader)
    # multi-worker may reorder but must deliver everything exactly once
    assert out == list(range(50))
