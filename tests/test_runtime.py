"""Native host-runtime tests: C++ flatten/unflatten and augmentation vs
numpy references; prefetch loader ordering/termination."""

import numpy as np
import pytest

from apex_tpu import runtime


def test_native_builds():
    assert runtime.native_available(), (
        f"host runtime failed to build: {runtime._build_err}")
    assert runtime._load().apex_host_runtime_version() == 1


def test_flatten_unflatten_roundtrip():
    rng = np.random.default_rng(0)
    arrays = [rng.standard_normal((17, 5)).astype(np.float32),
              rng.integers(0, 255, (33,), dtype=np.uint8),
              rng.standard_normal((2, 3, 4)).astype(np.float64)]
    flat = runtime.flatten_arrays(arrays)
    assert flat.nbytes == sum(a.nbytes for a in arrays)
    back = runtime.unflatten_array(flat, arrays)
    for a, b in zip(arrays, back):
        np.testing.assert_array_equal(a, b)


def test_flatten_matches_numpy_concat():
    rng = np.random.default_rng(1)
    arrays = [rng.standard_normal((100,)).astype(np.float32)
              for _ in range(7)]
    flat = runtime.flatten_arrays(arrays)
    want = np.concatenate([a.view(np.uint8) for a in arrays])
    np.testing.assert_array_equal(flat, want)


def test_augment_batch_matches_numpy():
    rng = np.random.default_rng(2)
    imgs = rng.integers(0, 256, (4, 40, 40, 3), dtype=np.uint8)
    crop = np.stack([rng.integers(0, 8, 4), rng.integers(0, 8, 4)], 1)
    flip = np.asarray([0, 1, 0, 1], np.uint8)
    got = runtime.augment_batch(imgs, (32, 32), crop, flip)

    mean, std = runtime.IMAGENET_MEAN, runtime.IMAGENET_STD
    for i in range(4):
        y0, x0 = crop[i]
        ref = imgs[i, y0:y0 + 32, x0:x0 + 32].astype(np.float32) / 255.0
        if flip[i]:
            ref = ref[:, ::-1]
        ref = (ref - mean) / std
        np.testing.assert_allclose(got[i], ref, rtol=1e-5, atol=1e-6)


def test_prefetch_loader():
    src = iter(range(20))
    loader = runtime.PrefetchLoader(src, transform=lambda x: x * 2,
                                    depth=4, workers=1)
    out = list(loader)
    assert out == [x * 2 for x in range(20)]


def test_prefetch_loader_stats_counters():
    loader = runtime.PrefetchLoader(iter(range(12)), depth=3, workers=1)
    assert list(loader) == list(range(12))
    st = loader.stats()
    assert st["produced"] == 12 and st["consumed"] == 12
    assert st["queue_depth"] == 0 and st["depth"] == 3
    # fast source, fast consumer: starvation bounded by total fetches
    assert 0 <= st["starvations"] <= 12


def test_prefetch_loader_device_put_staging():
    import jax
    loader = runtime.PrefetchLoader(
        iter([np.ones((4,), np.float32) * i for i in range(6)]),
        depth=2, device_put=True)
    out = list(loader)
    assert len(out) == 6
    # staged batches are device-resident jax arrays, values intact
    assert all(isinstance(b, jax.Array) for b in out)
    np.testing.assert_array_equal(np.asarray(out[3]),
                                  np.ones((4,), np.float32) * 3)
    st = loader.stats()
    assert st["put_s"] > 0.0


def test_prefetch_loader_device_put_callable_and_span():
    import jax
    from apex_tpu import telemetry, trace
    telemetry.enable()
    trace.enable()
    try:
        telemetry.get_collector().clear()
        loader = runtime.PrefetchLoader(
            iter(range(4)), depth=2,
            device_put=lambda x: jax.device_put(np.float32(x)))
        assert [float(b) for b in loader] == [0.0, 1.0, 2.0, 3.0]
        rows = trace.span_rows(telemetry.get_collector().snapshot())
        assert sum(r["name"] == "span/data/put" for r in rows) == 4
    finally:
        trace.disable()
        telemetry.disable()


def test_prefetch_loader_multiworker_complete():
    src = iter(range(50))
    loader = runtime.PrefetchLoader(src, depth=8, workers=3)
    out = sorted(loader)
    # multi-worker may reorder but must deliver everything exactly once
    assert out == list(range(50))


def test_prefetch_loader_propagates_transform_error():
    def boom(x):
        if x == 5:
            raise RuntimeError("corrupt batch")
        return x

    loader = runtime.PrefetchLoader(iter(range(20)), transform=boom,
                                    depth=2, workers=1)
    with pytest.raises(RuntimeError, match="corrupt batch"):
        list(loader)


def test_prefetch_loader_stopiteration_is_sticky():
    loader = runtime.PrefetchLoader(iter(range(3)), depth=2, workers=1)
    assert list(loader) == [0, 1, 2]
    # a second next() must raise again, not hang
    with pytest.raises(StopIteration):
        next(loader)


def test_prefetch_loader_early_close_unblocks_workers():
    loader = runtime.PrefetchLoader(iter(range(1000)), depth=1, workers=3)
    assert next(loader) is not None
    loader.close()  # workers blocked in put() must exit
    for t in loader._threads:
        t.join(timeout=5.0)
        assert not t.is_alive()
    with pytest.raises(StopIteration):
        next(loader)


def test_augment_batch_rejects_out_of_range_crop():
    imgs = np.zeros((2, 40, 40, 3), np.uint8)
    bad = np.asarray([[0, 0], [9, 9]], np.int32)  # 9+32 > 40
    with pytest.raises(ValueError):
        runtime.augment_batch(imgs, (32, 32), bad, np.zeros(2, np.uint8))
    with pytest.raises(ValueError):
        runtime.augment_batch(imgs, (32, 32),
                              np.asarray([[0, 0], [-1, 0]], np.int32),
                              np.zeros(2, np.uint8))


def test_unflatten_rejects_short_buffer():
    t = np.zeros((10,), np.float32)
    with pytest.raises(ValueError):
        runtime.unflatten_array(np.zeros(10, np.uint8), [t])


def test_unflatten_accepts_non_u8_view():
    arrays = [np.arange(6, dtype=np.float32).reshape(2, 3)]
    flat_f32 = np.arange(6, dtype=np.float32)  # same bytes, f32 view
    back = runtime.unflatten_array(flat_f32, arrays)
    np.testing.assert_array_equal(back[0], arrays[0])


def test_normalize_u8_to_f32_matches_numpy():
    rng = np.random.default_rng(3)
    imgs = rng.integers(0, 256, (2, 8, 8, 3), dtype=np.uint8)
    got = runtime.normalize_u8_to_f32(imgs)
    want = ((imgs.astype(np.float32) / 255.0 - runtime.IMAGENET_MEAN)
            / runtime.IMAGENET_STD)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
