"""Overlap-engine tests (apex_tpu.parallel.overlap) on the 8-device CPU
mesh: staged-backward reduction parity with the post-hoc path, wire
compression within tolerance, Adasum's defining identities, the
jaxpr-equality guarantee that the engine at its defaults is inert, ZeRO
reduce-scatter compression, and the overlap-efficiency telemetry."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel, telemetry
from apex_tpu.parallel import overlap

NDEV = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == NDEV, "conftest must set 8 CPU devices"
    return parallel.make_mesh(axis_names=("data",))


def _params():
    k = jax.random.split(jax.random.PRNGKey(0), 3)
    return {"w1": jax.random.normal(k[0], (64, 64)),
            "w2": jax.random.normal(k[1], (64, 32)),
            "b": jax.random.normal(k[2], (32,)) * 0.1}


def _batch():
    return jax.random.normal(jax.random.PRNGKey(9), (16, 64))


def _loss(p, x):
    h = jnp.tanh(x @ p["w1"])
    return jnp.mean((h @ p["w2"] + p["b"]) ** 2)


def _grads_posthoc(mesh, **kw):
    def body(p, x):
        g = jax.grad(_loss)(p, x)
        return parallel.allreduce_gradients(g, "data", **kw)
    return jax.jit(shard_map(body, mesh=mesh,
                             in_specs=(P(), P("data")), out_specs=P(),
                             check_vma=False))(_params(), _batch())


def _grads_staged(mesh, **kw):
    def body(p, x):
        return jax.grad(lambda p: _loss(
            overlap.sync_in_backward(p, "data", **kw), x))(p)
    return jax.jit(shard_map(body, mesh=mesh,
                             in_specs=(P(), P("data")), out_specs=P(),
                             check_vma=False))(_params(), _batch())


# ---------------------------------------------------------------------------
# staged backward == post-hoc sync
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kw", [
    dict(),
    dict(message_size=1024),
    dict(allreduce_always_fp32=True),
    dict(gradient_average=False),
    dict(gradient_predivide_factor=4.0),
])
def test_staged_matches_posthoc(mesh, kw):
    gs = _grads_staged(mesh, **kw)
    gp = _grads_posthoc(mesh, **kw)
    for k in gs:
        np.testing.assert_allclose(np.asarray(gs[k]), np.asarray(gp[k]),
                                   rtol=1e-6, atol=1e-7)


def test_staged_matches_posthoc_compressed(mesh):
    gs = _grads_staged(mesh, reduce_dtype="bf16")
    gp = _grads_posthoc(mesh, reduce_dtype="bf16")
    for k in gs:
        # same pre-scaling, same bucket concat, same wire cast -> the two
        # paths round identically
        np.testing.assert_allclose(np.asarray(gs[k]), np.asarray(gp[k]),
                                   rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# wire compression
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rd,tol", [("bf16", 2e-2), ("fp16", 5e-3)])
def test_wire_compression_close_to_fp32(mesh, rd, tol):
    ref = _grads_posthoc(mesh)
    got = _grads_posthoc(mesh, reduce_dtype=rd)
    for k in ref:
        a, b = np.asarray(got[k]), np.asarray(ref[k])
        scale = np.abs(b).max() + 1e-12
        assert np.abs(a - b).max() / scale < tol, k


def test_wire_compression_loss_scale_safe(mesh):
    # bf16 shares fp32's exponent range: a 2^16 loss scale must survive
    # the wire and unscale to the same mean (the amp O2/O5 contract)
    scale = 2.0 ** 16

    def body():
        r = jax.lax.axis_index("data").astype(jnp.float32)
        g = {"w": jnp.full((4096,), (r + 1.0) * 1e-3 * scale)}
        return parallel.allreduce_gradients(g, "data",
                                            reduce_dtype="bf16")
    out = jax.jit(shard_map(body, mesh=mesh, in_specs=(),
                            out_specs={"w": P()}, check_vma=False))()
    got = np.asarray(out["w"]) / scale
    np.testing.assert_allclose(got, 4.5e-3, rtol=2e-2)


def test_reduce_dtype_rejects_non_wire_formats():
    # fp32 on the wire is not compression; int4 is not implemented.
    # int8 IS a wire format since the lowp tier (tests/test_lowp.py).
    with pytest.raises(ValueError, match="wire format"):
        overlap.resolve_reduce_dtype("float32")
    with pytest.raises(ValueError, match="wire format"):
        overlap.resolve_reduce_dtype("int4")
    assert overlap.resolve_reduce_dtype("int8") == jnp.int8


def test_reduce_dtype_conflicts_with_always_fp32():
    with pytest.raises(ValueError, match="contradictory"):
        parallel.DistributedDataParallel(
            "data", reduce_dtype="bf16", allreduce_always_fp32=True)


# ---------------------------------------------------------------------------
# adasum
# ---------------------------------------------------------------------------

def test_adasum_parallel_gradients_reduce_to_mean(mesh):
    # identical gradients on every device: pairwise combination yields
    # the common value at every level == the plain mean
    def body():
        g = {"w": jnp.full((1000,), 3.0), "b": jnp.full((7,), -2.0)}
        return parallel.allreduce_gradients(g, "data", adasum=True)
    out = jax.jit(shard_map(body, mesh=mesh, in_specs=(),
                            out_specs={"w": P(), "b": P()},
                            check_vma=False))()
    np.testing.assert_allclose(np.asarray(out["w"]), 3.0, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["b"]), -2.0, rtol=1e-5)


def test_adasum_orthogonal_gradients_sum(mesh):
    # one-hot per device: orthogonal at every recursion level -> the sum
    def body():
        r = jax.lax.axis_index("data")
        g = jnp.where(jnp.arange(NDEV) == r, 1.0 + r.astype(jnp.float32),
                      0.0)
        return parallel.allreduce_gradients([g], "data", adasum=True)
    out = jax.jit(shard_map(body, mesh=mesh, in_specs=(),
                            out_specs=[P()], check_vma=False))()
    np.testing.assert_allclose(np.asarray(out[0]),
                               np.arange(1.0, NDEV + 1.0), rtol=1e-5)


def test_adasum_scale_invariance(mesh):
    # adasum(S*g) == S*adasum(g): the property that makes amp loss
    # scaling compose exactly (unscale after reduction is exact)
    def body(scale):
        r = jax.lax.axis_index("data").astype(jnp.float32)
        g = jnp.sin(jnp.arange(512.0) + r)  # distinct, partially aligned
        return parallel.allreduce_gradients([g * scale], "data",
                                            adasum=True)[0]
    run = jax.jit(shard_map(body, mesh=mesh, in_specs=(P(),),
                            out_specs=P(), check_vma=False))
    base = np.asarray(run(jnp.float32(1.0)))
    scaled = np.asarray(run(jnp.float32(1024.0)))
    np.testing.assert_allclose(scaled, base * 1024.0, rtol=1e-5)


def test_adasum_rejects_axis_index_groups():
    with pytest.raises(ValueError, match="adasum"):
        parallel.DistributedDataParallel(
            "data", adasum=True, axis_index_groups=[[0, 1], [2, 3]])


def test_adasum_rejects_sum_semantics():
    # adasum replaces the combiner: gradient_average=False (shard
    # contributions summed, the seq-parallel shape) cannot be honored
    # and must fail loudly at construction, not silently under-scale
    with pytest.raises(ValueError, match="gradient_average"):
        parallel.DistributedDataParallel(
            "data", adasum=True, gradient_average=False)


def test_adasum_fp16_wire_prescaled_in_range(mesh):
    # identical near-fp16-max gradients: a raw level-0 pair psum would
    # overflow (40k + 40k > 65504); the per-level x0.5 pre-scale keeps
    # the wire in range and the x2 restore is power-of-two exact
    def body():
        g = {"w": jnp.full((512,), 40000.0)}
        return parallel.allreduce_gradients(g, "data", adasum=True,
                                            reduce_dtype="fp16")
    out = jax.jit(shard_map(body, mesh=mesh, in_specs=(),
                            out_specs={"w": P()}, check_vma=False))()
    got = np.asarray(out["w"])
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, 40000.0, rtol=1e-3)


# ---------------------------------------------------------------------------
# jaxpr equality: the engine at its defaults is inert
# ---------------------------------------------------------------------------

def _jaxpr(mesh, fn):
    smapped = shard_map(fn, mesh=mesh, in_specs=(P(), P("data")),
                        out_specs=P(), check_vma=False)
    return str(jax.make_jaxpr(smapped)(_params(), _batch()))


def test_defaults_trace_bit_identical(mesh):
    # reduce_dtype=None, adasum=False (explicit) vs the bare pre-overlap
    # call signature: byte-identical programs — the engine's presence
    # costs nothing until a knob is turned
    def legacy(p, x):
        g = jax.grad(_loss)(p, x)
        return parallel.allreduce_gradients(g, "data")

    def explicit(p, x):
        g = jax.grad(_loss)(p, x)
        return parallel.allreduce_gradients(g, "data", reduce_dtype=None,
                                            adasum=False)

    j_legacy = _jaxpr(mesh, legacy)
    assert j_legacy == _jaxpr(mesh, explicit)
    # and no compression artifact leaks into the default program
    assert "bf16" not in j_legacy and "f16" not in j_legacy


def test_ddp_class_defaults_trace_bit_identical(mesh):
    ddp_default = parallel.DistributedDataParallel("data")
    ddp_explicit = parallel.DistributedDataParallel(
        "data", overlap=False, reduce_dtype=None, adasum=False)

    def mk(ddp):
        def body(p, x):
            g = jax.grad(_loss)(p, x)
            return ddp.sync(g)
        return body

    assert _jaxpr(mesh, mk(ddp_default)) == _jaxpr(mesh, mk(ddp_explicit))


def test_prepare_is_passthrough_without_overlap(mesh):
    ddp = parallel.DistributedDataParallel("data")
    p = _params()
    assert ddp.prepare(p) is p


# ---------------------------------------------------------------------------
# tune resolution for the staged path
# ---------------------------------------------------------------------------

def test_staged_bucket_capacity_resolves_via_tune(mesh):
    from apex_tpu import tune
    # off policy: the tune-resolved capacity IS the frozen heuristic, so
    # message_size=None and the explicit constant trace identically
    assert tune.policy() == "off"
    assert tune.ddp_overlap_message_size(total=10_000, world=NDEV) \
        == tune.heuristics.DDP_MESSAGE_SIZE

    def resolved(p, x):
        return jax.grad(lambda p: _loss(
            overlap.sync_in_backward(p, "data"), x))(p)

    def frozen(p, x):
        return jax.grad(lambda p: _loss(overlap.sync_in_backward(
            p, "data",
            message_size=tune.heuristics.DDP_MESSAGE_SIZE), x))(p)

    assert _jaxpr(mesh, resolved) == _jaxpr(mesh, frozen)


def test_sweeps_registry_has_ddp_overlap():
    from apex_tpu.tune import sweeps
    spec = sweeps.registry()["ddp_overlap"]
    key = {"total": 2 ** 20, "world": NDEV}
    cands = spec.candidates(key)
    assert cands[0] == spec.heuristic(key)   # heuristic always first
    assert len(cands) > 1


# ---------------------------------------------------------------------------
# ZeRO reduce-scatter compression
# ---------------------------------------------------------------------------

def _zero_step(mesh, **kw):
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    opt = DistributedFusedAdam(lr=0.1, axis_name="data", **kw)
    p = _params()
    g = jax.tree_util.tree_map(lambda a: a * 0.01, p)
    st = opt.init(p)

    def per_device(g, p, s):
        return opt.step(g, p, s)

    f = jax.jit(shard_map(per_device, mesh=mesh,
                          in_specs=(P(), P(), opt.state_pspec()),
                          out_specs=(P(), opt.state_pspec()),
                          check_vma=False))
    return f(g, p, st), opt


def test_zero_reduce_dtype_close_to_fp32(mesh):
    (p32, _), _ = _zero_step(mesh)
    (p16, _), _ = _zero_step(mesh, reduce_dtype="bf16")
    for k in p32:
        np.testing.assert_allclose(np.asarray(p16[k]), np.asarray(p32[k]),
                                   atol=5e-3)


def test_zero_reduce_dtype_layout_compatible(mesh):
    # compression is wire-only: the flat state layout (and therefore the
    # snapshot fingerprint) is identical, so checkpoints restore across
    # a reduce_dtype change
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    p = _params()
    f32 = DistributedFusedAdam(lr=0.1, axis_name="data")
    f16 = DistributedFusedAdam(lr=0.1, axis_name="data",
                               reduce_dtype="bf16")
    assert f32.layout_fingerprint(p) == f16.layout_fingerprint(p)
    assert f16.layout_mismatch(f32.layout_fingerprint(p), p) == {}


def test_zero_defaults_trace_bit_identical(mesh):
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    p = _params()
    g = jax.tree_util.tree_map(lambda a: a * 0.01, p)

    def jx(opt):
        st = opt.init(p)
        smapped = shard_map(lambda g, p, s: opt.step(g, p, s), mesh=mesh,
                            in_specs=(P(), P(), opt.state_pspec()),
                            out_specs=(P(), opt.state_pspec()),
                            check_vma=False)
        return str(jax.make_jaxpr(smapped)(g, p, st))

    assert jx(DistributedFusedAdam(lr=0.1, axis_name="data")) \
        == jx(DistributedFusedAdam(lr=0.1, axis_name="data",
                                   reduce_dtype=None))


# ---------------------------------------------------------------------------
# telemetry: wire accounting + overlap efficiency
# ---------------------------------------------------------------------------

def test_static_comm_bill_reflects_wire_dtype(mesh):
    def run(**kw):
        with telemetry.capture() as col:
            def body(p, x):
                g = jax.grad(_loss)(p, x)
                return parallel.allreduce_gradients(g, "data", **kw)
            jax.jit(shard_map(body, mesh=mesh,
                              in_specs=(P(), P("data")), out_specs=P(),
                              check_vma=False))(_params(), _batch())
            jax.effects_barrier()
            evs = [e for e in col.drain()
                   if e.name == "ddp/data/allreduce_bytes"]
        assert evs, "no ddp comm event"
        return evs[0]

    e32 = run()
    e16 = run(reduce_dtype="bf16")
    assert e16.value == pytest.approx(e32.value / 2)
    assert e16.meta["bytes_wire"] == pytest.approx(
        e32.meta["bytes_wire"] / 2, rel=1e-3)
    assert e16.meta["reduce_dtype"] == "bfloat16"
    assert "reduce_dtype" not in (e32.meta or {})

    eada = run(adasum=True)
    # adasum wire bill: log2(8) = 3 levels of pair-allreduce (1x bytes
    # each) vs the ring's 2*(8-1)/8
    assert eada.meta["bytes_wire"] == pytest.approx(
        e32.value * 3, rel=1e-3)
    assert eada.meta["adasum"] is True

    # grouped collective: the producer bill must use the GROUP world
    # (pair ring multiplier 1.0, not the 8-member 1.75) — matching the
    # jaxpr walker's grouped accounting
    egrp = run(axis_index_groups=[[2 * i, 2 * i + 1] for i in range(4)])
    assert egrp.meta["world"] == 2
    assert egrp.meta["bytes_wire"] == pytest.approx(e32.value, rel=1e-3)


def test_comm_walker_respects_axis_index_groups(mesh):
    # adasum's pairwise levels are grouped psums: the walker must bill
    # them as 2-member all-reduces, not full-axis ones
    from apex_tpu.telemetry import comm as tcomm

    def body(x):
        return overlap.adasum_flat(x, "data")

    smapped = shard_map(body, mesh=mesh, in_specs=(P(),), out_specs=P(),
                        check_vma=False)
    x = jnp.ones((1024,))
    recs = tcomm.comm_stats(smapped, x)
    psums = [r for r in recs if r.primitive == "psum" and r.axis == "data"]
    assert len(psums) == 1
    # 3 levels x 4096 bytes payload x 2*(2-1)/2 (pair ring) each
    assert psums[0].count == 3
    assert psums[0].bytes_wire == pytest.approx(3 * 4096.0, rel=1e-6)


def test_overlap_efficiency_metric():
    # pipelined: later buckets' issues land inside earlier windows
    # (backward demonstrably running while the collective is in flight)
    issues = {0: 0.0, 1: 8.0, 2: 16.0, 3: 24.0}
    dones = {0: 10.0, 1: 18.0, 2: 26.0, 3: 34.0}
    eff = overlap.overlap_efficiency(issues, dones)
    assert eff == pytest.approx((8.0 * 3) / 40.0)
    # serialized interleaved: compute blocked on each collective, no
    # issue ever lands inside another's window -> nothing was hidden
    issues_s = {b: 20.0 * b for b in range(4)}
    dones_s = {b: 20.0 * b + 10.0 for b in range(4)}
    assert overlap.overlap_efficiency(issues_s, dones_s) == 0.0
    # all-comm-after-backward barrier: issues cluster at the tail with
    # nothing left to compute -> (near) nothing hidden either
    issues_b = {b: 100.0 + 0.01 * b for b in range(4)}
    dones_b = {b: 110.0 + 0.01 * b for b in range(4)}
    assert overlap.overlap_efficiency(issues_b, dones_b) < 0.01
    # degenerate: no positive window
    assert overlap.overlap_efficiency({0: 1.0}, {0: 1.0}) is None


def test_overlap_efficiency_event(mesh):
    overlap._tracker.reset()
    with telemetry.capture() as col:
        def body(p, x, step):
            return jax.grad(lambda p: _loss(overlap.sync_in_backward(
                p, "data", message_size=2000, telemetry_step=step),
                x))(p)
        run = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P(), P("data"), P()),
            out_specs=P(), check_vma=False))
        for i in range(2):
            jax.block_until_ready(run(_params(), _batch(), jnp.int32(i)))
        jax.effects_barrier()
        evs = [e for e in col.drain()
               if e.name == "ddp/overlap_efficiency"]
    # one emission per step (per-shard replicas dedup'd at the tracker)
    assert {e.step for e in evs} == {0, 1}
    assert all(0.0 <= e.value <= 1.0 for e in evs)
    assert all(e.meta["buckets"] >= 2 for e in evs)


def test_summarize_renders_overlap_efficiency():
    from apex_tpu.telemetry.export import format_summary, summarize
    events = [{"name": "ddp/overlap_efficiency", "value": 0.75,
               "ts": float(i), "step": i, "kind": "point"}
              for i in range(3)]
    s = summarize(events)
    assert s["overlap_efficiency"]["mean"] == pytest.approx(0.75)
    assert "overlap eff" in format_summary(s)


# ---------------------------------------------------------------------------
# the staged identity itself
# ---------------------------------------------------------------------------

def test_staged_vjp_identity_and_transform():
    from apex_tpu.ops import staged_vjp

    def double(cts):
        return [2.0 * c for c in cts]

    a = jnp.arange(4.0)
    b = jnp.ones((2, 2))
    out = staged_vjp.cotangent_transform(double)(a, b)
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(a))

    def loss(a, b):
        xa, xb = staged_vjp.cotangent_transform(double)(a, b)
        return jnp.sum(xa) + jnp.sum(xb * xb)

    ga, gb = jax.grad(loss, argnums=(0, 1))(a, b)
    np.testing.assert_allclose(np.asarray(ga), 2.0)       # 2 * 1
    np.testing.assert_allclose(np.asarray(gb), 4.0 * np.asarray(b))


def test_ddp_train_step_overlap_end_to_end(mesh):
    # the packaged ddp_train_step with overlap + compression trains and
    # matches the non-overlap step within wire tolerance
    from apex_tpu import optimizers

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((jnp.tanh(x @ p["w1"]) @ p["w2"] + p["b"] - y)
                        ** 2)

    x = jax.random.normal(jax.random.PRNGKey(3), (16, 64))
    y = jax.random.normal(jax.random.PRNGKey(4), (16, 32))

    def run(ddp):
        opt = optimizers.FusedSGD(lr=0.1)
        p = _params()
        st = opt.init(p)
        step = parallel.ddp_train_step(loss_fn, opt, mesh, "data",
                                       ddp=ddp, donate=False)
        for _ in range(2):
            p, st, loss = step(p, st, (x, y))
        return p, float(loss)

    p_ref, l_ref = run(parallel.DistributedDataParallel("data"))
    p_ovl, l_ovl = run(parallel.DistributedDataParallel(
        "data", overlap=True, reduce_dtype="bf16"))
    assert abs(l_ref - l_ovl) < 1e-2
    for k in p_ref:
        np.testing.assert_allclose(np.asarray(p_ovl[k]),
                                   np.asarray(p_ref[k]), atol=5e-3)
