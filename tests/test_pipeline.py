"""GPipe-style pipeline parallelism (parallel/pipeline.py): stages over
a mesh axis, microbatch tick loop, ppermute activation shifts — forward
and EVERY parameter gradient must match the dense model on the virtual
mesh. Additive capability: with data (DDP/ZeRO), tensor, and sequence
parallelism this completes the four classic axes."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.models import TransformerLM
from apex_tpu.models.gpt import next_token_loss
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.parallel.pipeline import (lm_stack_blocks,
                                        lm_unstack_blocks,
                                        pipeline_apply, psum_input_grads,
                                        stacked_block_pspecs)

# Integration tier (PR 1): this whole module rides `-m slow` — GPipe dense-parity integration.
# Tier-1 (-m 'not slow') must fit the 870 s gate budget; the fast cross-
# sections of this stack stay in tier-1 via test_zero/test_parallel/
# test_param_groups/test_attention and the ci/gate.sh dryrun parts.
pytestmark = pytest.mark.slow

V, L, E, H, S, B = 64, 8, 32, 4, 16, 4
STAGES = 4
M = 4  # microbatches (batch B splits into M of B//M)


def _model():
    return TransformerLM(vocab_size=V, num_layers=L, embed_dim=E,
                         num_heads=H, max_seq=S)


def test_stack_roundtrip():
    model = _model()
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, V)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    stacked, rest = lm_stack_blocks(params)
    assert jax.tree_util.tree_leaves(stacked)[0].shape[0] == L
    back = lm_unstack_blocks(stacked, rest)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(params)[0],
            jax.tree_util.tree_flatten_with_path(back)[0]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(pa))


def _pipe_loss_fn(model, toks_mb):
    """Replicated-per-rank pipeline loss: embeddings -> pipeline over
    the block stack -> final norm + head + next-token loss. ``toks_mb``
    is (M, B/M, S)."""
    def loss(stacked, rest, toks_mb):
        emb_tok = rest["tok_emb"]["embedding"]
        emb_pos = rest["pos_emb"]["embedding"]
        x = emb_tok[toks_mb] + emb_pos[jnp.arange(S)][None, None]

        def one_block(p, h):
            from apex_tpu.models.gpt import Block
            return Block(E, H, name="b").apply({"params": p}, h)

        def stage(stage_params, h):
            def step(h, p):
                return one_block(p, h), ()
            h, _ = jax.lax.scan(step, h, stage_params)
            return h

        outs = pipeline_apply(stage, stacked, x, "pipe")
        # final norm + head, replicated (outs are psum-broadcast)
        g, b_ = rest["ln_f"]["weight"], rest["ln_f"]["bias"]
        from apex_tpu.normalization import layer_norm
        h = layer_norm(outs.reshape(-1, E), g, b_).reshape(outs.shape)
        logits = h @ rest["head"]["kernel"] + rest["head"]["bias"]
        flat_logits = logits.reshape(M * (B // M), S, V)
        flat_toks = toks_mb.reshape(M * (B // M), S)
        return next_token_loss(flat_logits.astype(jnp.float32), flat_toks)

    return loss


@pytest.fixture(scope="module")
def pipe_mesh():
    return parallel.make_mesh((STAGES,), ("pipe",),
                              devices=jax.devices()[:STAGES])


def test_pipeline_forward_and_grads_match_dense(pipe_mesh):
    model = _model()
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, V)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]

    def dense_loss(p):
        return next_token_loss(model.apply({"params": p}, toks), toks)

    want_loss, want_grads = jax.value_and_grad(dense_loss)(params)
    want_stacked, want_rest = lm_stack_blocks(want_grads)

    stacked, rest = lm_stack_blocks(params)
    sspecs = stacked_block_pspecs(stacked)
    stacked = jax.device_put(stacked, jax.tree_util.tree_map(
        lambda sp: NamedSharding(pipe_mesh, sp), sspecs))
    toks_mb = toks.reshape(M, B // M, S)
    loss = _pipe_loss_fn(model, toks_mb)

    def per_device(stk, rst, t):
        l, (g_stk, g_rst) = jax.value_and_grad(loss, argnums=(0, 1))(
            stk, rst, t)
        # embeddings: input-side (rank-0-only) grads -> psum; head/ln_f
        # grads are replicated already
        g_rst = dict(g_rst)
        for k in ("tok_emb", "pos_emb"):
            g_rst[k] = psum_input_grads(g_rst[k], "pipe")
        return l, g_stk, g_rst

    fn = jax.jit(shard_map(
        per_device, mesh=pipe_mesh, in_specs=(sspecs, P(), P()),
        out_specs=(P(), sspecs, P()), check_vma=False))
    got_loss, got_stacked, got_rest = fn(stacked, rest, toks_mb)

    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=2e-5)
    for (pa, g), (_, w) in zip(
            jax.tree_util.tree_flatten_with_path(got_stacked)[0],
            jax.tree_util.tree_flatten_with_path(want_stacked)[0]):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=3e-4, atol=3e-5, err_msg=str(pa))
    for (pa, g), (_, w) in zip(
            jax.tree_util.tree_flatten_with_path(got_rest)[0],
            jax.tree_util.tree_flatten_with_path(want_rest)[0]):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=3e-4, atol=3e-5, err_msg=str(pa))


# ---------------------------------------------------------------------------
# 3-D composition: data x tensor x pipeline parallelism in ONE train step
# ---------------------------------------------------------------------------

def test_3d_dp_tp_pp_grads_match_dense():
    """(data=2, model=2, pipe=2) mesh: batch shards over data, heads and
    MLP shard over model (Megatron f/g inside each block), the block
    stack shards into stages over pipe (GPipe microbatch ticks). Forward
    loss and EVERY param grad must match the dense single-device model:
    stacked block grads pmean over data only (local-complete over
    model/pipe), embedding grads additionally psum over pipe (inject
    zeroing), head/ln_f grads replicated off the psum-broadcast
    outputs."""
    import numpy as np
    from apex_tpu.models import TransformerLM
    from apex_tpu.models.gpt import Block, next_token_loss
    from apex_tpu.parallel import (lm_stack_blocks, lm_tp_pspecs,
                                   lm_unstack_blocks, pipeline_apply,
                                   psum_input_grads, tp_shard_lm_params,
                                   tp_unshard_lm_params,
                                   stacked_block_pspecs)

    d_dp = d_tp = d_pp = 2
    e, heads, s, vocab, layers = 32, 4, 16, 64, 4
    m_micro, mb = 2, 1                    # 2 microbatches of 1 per device
    b_loc = m_micro * mb
    b_glob = b_loc * d_dp
    dense = TransformerLM(vocab_size=vocab, num_layers=layers,
                          embed_dim=e, num_heads=heads, max_seq=s)
    toks = jax.random.randint(jax.random.PRNGKey(20), (b_glob, s), 0,
                              vocab)
    params = dense.init(jax.random.PRNGKey(21), toks)["params"]

    def dense_loss(p):
        return next_token_loss(dense.apply({"params": p}, toks), toks)

    want_loss, want_grads = jax.value_and_grad(dense_loss)(params)

    # ---- shard: qkv permute for TP, stack blocks for PP
    params_tp = tp_shard_lm_params(params, d_tp)
    stacked, rest = lm_stack_blocks(params_tp)
    tp_specs = lm_tp_pspecs(params_tp, axis="model")
    sspecs = stacked_block_pspecs(stacked, axis="pipe",
                                  inner_specs=tp_specs["block_0"])
    rest_specs = jax.tree_util.tree_map(lambda _: P(), rest)

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(d_dp, d_tp, d_pp),
                ("data", "model", "pipe"))

    def per_device(stk, rst, t):
        x = rst["tok_emb"]["embedding"][t] \
            + rst["pos_emb"]["embedding"][jnp.arange(s)][None]
        micro = x.reshape(m_micro, mb, s, e)

        def stage(sp, hbuf):
            def body(hh, pp):
                out = Block(e, heads // d_tp, name="b",
                            tensor_parallel_axis="model",
                            tensor_parallel_size=d_tp).apply(
                    {"params": pp}, hh)
                return out, ()
            return jax.lax.scan(body, hbuf, sp)[0]

        y = pipeline_apply(stage, stk, micro, "pipe")
        hid = y.reshape(b_loc, s, e)
        from apex_tpu.normalization import FusedLayerNorm
        hid = FusedLayerNorm(normalized_shape=e, name="ln_f").apply(
            {"params": rst["ln_f"]}, hid)
        logits = (hid @ rst["head"]["kernel"]
                  + rst["head"]["bias"]).astype(jnp.float32)
        return next_token_loss(logits, t)

    def grad_step(stk, rst, t):
        loss, (g_stk, g_rst) = jax.value_and_grad(
            per_device, argnums=(0, 1))(stk, rst, t)
        loss = jax.lax.pmean(loss, "data")
        # data axis: every param saw only this shard's batch
        g_stk = jax.lax.pmean(g_stk, "data")
        g_rst = jax.lax.pmean(g_rst, "data")
        # pipe axis: embeddings fed stage 0 only
        emb_g = psum_input_grads(
            {"tok_emb": g_rst["tok_emb"], "pos_emb": g_rst["pos_emb"]},
            "pipe")
        g_rst = {**g_rst, **emb_g}
        return loss, g_stk, g_rst

    f = jax.jit(shard_map(
        grad_step, mesh=mesh,
        in_specs=(sspecs, rest_specs, P("data")),
        out_specs=(P(), sspecs, rest_specs), check_vma=False))
    stacked = jax.device_put(stacked, jax.tree_util.tree_map(
        lambda sp: NamedSharding(mesh, sp), sspecs))
    loss, g_stk, g_rst = f(
        stacked, rest,
        jax.device_put(toks, NamedSharding(mesh, P("data"))))

    np.testing.assert_allclose(float(loss), float(want_loss),
                               rtol=2e-5, atol=1e-6)
    got = tp_unshard_lm_params(
        lm_unstack_blocks(jax.device_get(g_stk), jax.device_get(g_rst)),
        d_tp)
    flat_got, _ = jax.tree_util.tree_flatten_with_path(got)
    flat_want, _ = jax.tree_util.tree_flatten_with_path(want_grads)
    assert len(flat_got) == len(flat_want)
    for (pg, gg), (_, gw) in zip(flat_got, flat_want):
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(gw), rtol=2e-4, atol=2e-5,
            err_msg=str(pg))
