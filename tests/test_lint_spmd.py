"""apex_tpu.lint SPMD verifier (APX201-APX209) — per-rule firing
fixtures, corrected twins, and per-line suppressions; the read-only
(jaxpr-equality) contract; the static donation re-derivation pinned
against the trainer's runtime DonationReport; baseline + SARIF output;
and the trainer's check_spmd seam.

The bad/suppressed fixtures live in THIS file on purpose: the verifier
attributes findings to real source lines via jaxpr source_info, so the
suppression tests exercise the same file-line mechanics users rely on.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import trainer
from apex_tpu.lint import (StaticDonation, builtin_entries,
                           check_entry_spmd, static_donation)
from apex_tpu.lint import main as lint_main
from apex_tpu.lint.report import (Finding, apply_suppressions,
                                  load_baseline, render_sarif,
                                  split_baseline, write_baseline)
from apex_tpu.lint.rules import RULES, SPMD_RULE_IDS
from apex_tpu.lint.spmd_checks import (replication_threshold_bytes,
                                       run_entries_spmd)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh(n=1):
    return Mesh(np.asarray(jax.devices()[:n]), ("data",))


def _smap(fn, n_in=1, mesh=None, sharded=True):
    spec = P("data") if sharded else P()
    return jax.shard_map(fn, mesh=mesh or _mesh(),
                         in_specs=(spec,) * n_in, out_specs=P(),
                         check_vma=False)


def spmd_ids(fn, args, **kw):
    return sorted({f.rule_id for f in check_entry_spmd(fn, args, **kw)})


def run_suppressions(fn, args, **kw):
    """check_entry_spmd + the real file/line suppression machinery."""
    findings = check_entry_spmd(fn, args, **kw)
    sources = {}
    for f in findings:
        if f.path not in sources and os.path.exists(f.path):
            with open(f.path, encoding="utf-8") as fh:
                sources[f.path] = fh.read().splitlines()
    return apply_suppressions(findings, sources)


def assert_suppressed(rule, fn, args, **kw):
    active, suppressed = run_suppressions(fn, args, **kw)
    assert [f.rule_id for f in active] == []
    assert [f.rule_id for f in suppressed] == [rule]


# ---------------------------------------------------------------------------
# APX201: collective under rank-dependent control flow
# ---------------------------------------------------------------------------

def _bad201(x):
    i = jax.lax.axis_index("data")
    return jax.lax.cond(
        i == 0, lambda v: jax.lax.psum(v, "data"), lambda v: v, x)


def _good201(x):
    total = jax.lax.psum(x, "data")
    i = jax.lax.axis_index("data")
    return jnp.where(i == 0, total, x)


def _sup201(x):
    i = jax.lax.axis_index("data")
    return jax.lax.cond(
        i == 0,
        lambda v: jax.lax.psum(v, "data"),  # apexlint: disable=APX201 -- test fixture
        lambda v: v, x)


def test_apx201_rank_gated_cond_fires():
    x = jnp.ones((4, 4))
    assert spmd_ids(_smap(_bad201), (x,), mesh_axes=("data",)) == ["APX201"]
    assert check_entry_spmd(_smap(_good201), (x,),
                            mesh_axes=("data",)) == []


def test_apx201_rank_gated_while_fires():
    def bad(x):
        i = jax.lax.axis_index("data")

        def cond(c):
            return c[1] < i

        def body(c):
            return (jax.lax.psum(c[0], "data"), c[1] + 1)
        return jax.lax.while_loop(cond, body, (x, 0))[0]

    def good(x):
        def cond(c):
            return c[1] < 3

        def body(c):
            return (jax.lax.psum(c[0], "data"), c[1] + 1)
        return jax.lax.while_loop(cond, body, (x, 0))[0]

    x = jnp.ones((4,))
    assert spmd_ids(_smap(bad), (x,), mesh_axes=("data",)) == ["APX201"]
    assert check_entry_spmd(_smap(good), (x,), mesh_axes=("data",)) == []


def test_apx201_while_carry_becomes_rank_dependent():
    # the predicate reads a carry that only becomes rank-tainted INSIDE
    # the body: requires the fixpoint, not a single pass
    def bad(x):
        def cond(c):
            return c[1] < 3

        def body(c):
            i = jax.lax.axis_index("data")
            return (jax.lax.psum(c[0], "data"), c[1] + i)
        return jax.lax.while_loop(cond, body, (x, 0))[0]

    x = jnp.ones((4,))
    assert spmd_ids(_smap(bad), (x,), mesh_axes=("data",)) == ["APX201"]


def test_apx201_suppression():
    assert_suppressed("APX201", _smap(_sup201), (jnp.ones((4, 4)),),
                      mesh_axes=("data",))


def test_apx201_taint_erasure_is_axis_scoped():
    # a psum over "data" does NOT launder model-rank divergence: on a
    # 2-D mesh, psum(axis_index("model"), "data") is still divergent
    # along "model", and gating a collective on it still deadlocks
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1),
                ("data", "model"))

    def bad(x):
        i = jax.lax.axis_index("model")
        s = jax.lax.psum(i, "data")          # erases "data" taint only
        return jax.lax.cond(
            s > 0, lambda v: jax.lax.psum(v, "data"), lambda v: v, x)

    def good(x):
        # reduced over BOTH axes: genuinely replica-uniform predicate
        s = jax.lax.psum(jax.lax.axis_index("model"), ("data", "model"))
        return jax.lax.cond(
            s > 0, lambda v: jax.lax.psum(v, "data"), lambda v: v, x)

    def smap(fn):
        return jax.shard_map(fn, mesh=mesh, in_specs=(P(),),
                             out_specs=P(), check_vma=False)

    x = jnp.ones((4,))
    assert spmd_ids(smap(bad), (x,),
                    mesh_axes=("data", "model")) == ["APX201"]
    assert check_entry_spmd(smap(good), (x,),
                            mesh_axes=("data", "model")) == []


def test_apx201_committed_deadlock_fixture():
    # the fixture ci/gate.sh pins: bad flagged, corrected twin clean
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "spmd_deadlock",
        os.path.join(REPO, "tests", "fixtures", "spmd_deadlock.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.bad_entry()
    assert "APX201" in {f.rule_id for f in check_entry_spmd(
        fn, args, mesh_axes=("data",))}
    fn, args = mod.good_entry()
    assert check_entry_spmd(fn, args, mesh_axes=("data",)) == []


# ---------------------------------------------------------------------------
# APX202: replica-divergent RNG
# ---------------------------------------------------------------------------

def _bad202(x):
    seed = jnp.sum(x).astype(jnp.int32)      # sharded data -> divergent
    key = jax.random.PRNGKey(seed)
    return x * jax.random.uniform(key, x.shape)


def _good202_uniform_seed(x):
    seed = jnp.sum(jax.lax.psum(x, "data")).astype(jnp.int32)
    key = jax.random.PRNGKey(seed)
    return x * jax.random.uniform(key, x.shape)


def _good202_folded(x):
    seed = jnp.sum(x).astype(jnp.int32)
    key = jax.random.fold_in(jax.random.PRNGKey(seed),
                             jax.lax.axis_index("data"))
    return x * jax.random.uniform(key, x.shape)


def _sup202(x):
    seed = jnp.sum(x).astype(jnp.int32)
    key = jax.random.PRNGKey(seed)
    return x * jax.random.uniform(key, x.shape)  # apexlint: disable=APX202 -- test fixture


def test_apx202_sharded_seed_fires_and_twins_pass():
    x = jnp.ones((4, 4))
    assert spmd_ids(_smap(_bad202), (x,), mesh_axes=("data",)) == ["APX202"]
    assert check_entry_spmd(_smap(_good202_uniform_seed), (x,),
                            mesh_axes=("data",)) == []
    assert check_entry_spmd(_smap(_good202_folded), (x,),
                            mesh_axes=("data",)) == []


def test_apx202_replicated_key_is_uniform():
    # a key passed in REPLICATED is provably replica-uniform: silent
    def f(x, key):
        return x * jax.random.uniform(key, x.shape)

    g = jax.shard_map(f, mesh=_mesh(), in_specs=(P("data"), P()),
                      out_specs=P(), check_vma=False)
    assert check_entry_spmd(
        g, (jnp.ones((4, 4)), jax.random.PRNGKey(0)),
        mesh_axes=("data",)) == []


def test_apx202_outside_mesh_is_silent():
    # replica semantics only exist inside a mesh region
    def f(x):
        key = jax.random.PRNGKey(jnp.sum(x).astype(jnp.int32))
        return x * jax.random.uniform(key, x.shape)

    assert check_entry_spmd(f, (jnp.ones((4, 4)),)) == []


def test_apx202_suppression():
    # NB distinct shape from _bad202: jax caches the traced _uniform
    # sub-jaxpr per aval, and a cache hit would carry the FIRST call
    # site's source lines into this fixture's finding
    assert_suppressed("APX202", _smap(_sup202), (jnp.ones((4, 12)),),
                      mesh_axes=("data",))


# ---------------------------------------------------------------------------
# APX203: use-after-donation
# ---------------------------------------------------------------------------

def _bad203(state, batch):
    new = jax.tree_util.tree_map(lambda a: a + jnp.mean(batch), state)
    aux = jnp.sum(state["w"] * 3.0)          # reads donated w after new
    return new, aux


def _good203(state, batch):
    aux = jnp.sum(state["w"] * 3.0)          # old value read first
    new = jax.tree_util.tree_map(lambda a: a + jnp.mean(batch), state)
    return new, aux


def _sup203(state, batch):
    new = jax.tree_util.tree_map(lambda a: a + jnp.mean(batch), state)
    aux = jnp.sum(state["w"] * 3.0)  # apexlint: disable=APX203 -- test fixture
    return new, aux


_S203 = {"w": jnp.ones((4,)), "v": jnp.zeros((2,))}
_B203 = jnp.ones((3,))


def test_apx203_read_after_aliased_output_fires():
    assert spmd_ids(_bad203, (_S203, _B203),
                    donate_argnums=(0,)) == ["APX203"]
    assert check_entry_spmd(_good203, (_S203, _B203),
                            donate_argnums=(0,)) == []
    # donation not declared: rule disarmed on the same program
    assert check_entry_spmd(_bad203, (_S203, _B203)) == []


def test_apx203_suppression():
    assert_suppressed("APX203", _sup203, (_S203, _B203),
                      donate_argnums=(0,))


# ---------------------------------------------------------------------------
# APX204: implicit full replication
# ---------------------------------------------------------------------------

def _bad204(x):
    g = jax.lax.all_gather(x, "data")
    return jnp.sum(g)


def _good204(x):
    return jnp.sum(x)                        # stays sharded


def _sup204(x):
    g = jax.lax.all_gather(x, "data")  # apexlint: disable=APX204 -- test fixture
    return jnp.sum(g)


def test_apx204_large_all_gather_fires_small_passes():
    x = jnp.ones((8, 128))                   # gathered: 4 KiB
    assert spmd_ids(_smap(_bad204), (x,), mesh_axes=("data",),
                    threshold_bytes=2048) == ["APX204"]
    assert check_entry_spmd(_smap(_good204), (x,), mesh_axes=("data",),
                            threshold_bytes=2048) == []
    # under the threshold: the gather is small enough to be deliberate
    assert check_entry_spmd(_smap(_bad204), (x,), mesh_axes=("data",),
                            threshold_bytes=1 << 20) == []


def test_apx204_default_threshold_is_env_overridable(monkeypatch):
    assert replication_threshold_bytes() == 1 << 20
    monkeypatch.setenv("APEX_TPU_LINT_REPLICATION_BYTES", "4096")
    assert replication_threshold_bytes() == 4096
    monkeypatch.setenv("APEX_TPU_LINT_REPLICATION_BYTES", "bogus")
    assert replication_threshold_bytes() == 1 << 20


def test_apx204_default_threshold_fires_on_megabyte_gather():
    # ShapeDtypeStruct args: the verifier traces, never executes
    x = jax.ShapeDtypeStruct((2048, 128), jnp.float32)   # 1 MiB
    assert spmd_ids(_smap(_bad204), (x,),
                    mesh_axes=("data",)) == ["APX204"]


def test_apx204_suppression():
    assert_suppressed("APX204", _smap(_sup204), (jnp.ones((8, 128)),),
                      mesh_axes=("data",), threshold_bytes=2048)


# ---------------------------------------------------------------------------
# APX205: reshard thrash
# ---------------------------------------------------------------------------

def _bad205(x):
    g = jax.lax.all_gather(x, "data")
    return jax.lax.psum(g, "data")


def _good205(x):
    return jax.lax.psum(x, "data")           # reduce first, no gather


def _sup205(x):
    g = jax.lax.all_gather(x, "data")  # apexlint: disable=APX205 -- test fixture
    return jax.lax.psum(g, "data")


def test_apx205_gather_feeding_only_reduce_fires():
    x = jnp.ones((8, 8))
    assert spmd_ids(_smap(_bad205), (x,), mesh_axes=("data",)) == ["APX205"]
    assert check_entry_spmd(_smap(_good205), (x,),
                            mesh_axes=("data",)) == []


def test_apx205_gather_with_real_consumer_is_silent():
    def f(x):
        g = jax.lax.all_gather(x, "data")
        return jax.lax.psum(g, "data") + jnp.sum(g)   # g used for real

    assert check_entry_spmd(_smap(f), (jnp.ones((8, 8)),),
                            mesh_axes=("data",)) == []


def test_apx205_suppression():
    assert_suppressed("APX205", _smap(_sup205), (jnp.ones((8, 8)),),
                      mesh_axes=("data",))


# ---------------------------------------------------------------------------
# APX206: collective bypassing the overlap bucket seam
# ---------------------------------------------------------------------------

def _seam_loss(p, x):
    from apex_tpu.parallel import overlap
    p = overlap.sync_in_backward(p, "data")
    return jnp.mean((x @ p["w"]) ** 2)


def _bad206(p, x):
    g = jax.grad(_seam_loss)(p, x)
    return jax.lax.psum(g["w"], "data")      # gradient psum off the seam


def _good206(p, x):
    return jax.grad(_seam_loss)(p, x)["w"]   # every collective staged


def _sup206(p, x):
    g = jax.grad(_seam_loss)(p, x)
    return jax.lax.psum(g["w"], "data")  # apexlint: disable=APX206 -- test fixture


_P206 = {"w": jnp.ones((64, 64))}
_X206 = jnp.ones((4, 64))


def _smap206(fn):
    return jax.shard_map(fn, mesh=_mesh(), in_specs=(P(), P("data")),
                         out_specs=P(), check_vma=False)


def test_apx206_raw_psum_next_to_seam_fires():
    assert spmd_ids(_smap206(_bad206), (_P206, _X206),
                    mesh_axes=("data",)) == ["APX206"]
    assert check_entry_spmd(_smap206(_good206), (_P206, _X206),
                            mesh_axes=("data",)) == []


def test_apx206_no_seam_no_finding():
    # without the staged seam present, a raw gradient psum is the plain
    # DDP pattern — not a bypass
    def f(p, x):
        def loss(p):
            return jnp.mean((x @ p["w"]) ** 2)
        return jax.lax.psum(jax.grad(loss)(p)["w"], "data")

    assert check_entry_spmd(_smap206(f), (_P206, _X206),
                            mesh_axes=("data",)) == []


def test_apx206_scalar_psum_next_to_seam_is_exempt():
    def f(p, x):
        g = jax.grad(_seam_loss)(p, x)
        return jax.lax.psum(jnp.sum(g["w"] ** 2), "data")   # norm scalar

    assert check_entry_spmd(_smap206(f), (_P206, _X206),
                            mesh_axes=("data",)) == []


def test_apx206_suppression():
    assert_suppressed("APX206", _smap206(_sup206), (_P206, _X206),
                      mesh_axes=("data",))


# ---------------------------------------------------------------------------
# APX207: host callback re-entering the graph
# ---------------------------------------------------------------------------

def _bad207(x):
    y = jax.pure_callback(
        lambda a: np.asarray(a) * 2,
        jax.ShapeDtypeStruct(x.shape, x.dtype), x)
    return y + x


def _good207(x):
    jax.debug.callback(lambda a: None, x)    # effect-only: fine
    return x * 2


def _sup207(x):
    y = jax.pure_callback(  # apexlint: disable=APX207 -- test fixture
        lambda a: np.asarray(a) * 2,
        jax.ShapeDtypeStruct(x.shape, x.dtype), x)
    return y + x


def test_apx207_callback_result_reenters_fires():
    x = jnp.ones((4,))
    assert spmd_ids(_bad207, (x,)) == ["APX207"]
    assert check_entry_spmd(_good207, (x,)) == []


def test_apx207_suppression():
    assert_suppressed("APX207", _sup207, (jnp.ones((4,)),))


# ---------------------------------------------------------------------------
# APX208: scan-carry widening
# ---------------------------------------------------------------------------

def _bad208(x):
    def body(c, _):
        y = (c.astype(jnp.bfloat16) * x).astype(jnp.float32)
        return y, jnp.float32(0)
    out, _ = jax.lax.scan(body, jnp.zeros(x.shape, jnp.float32), None, length=4)
    return out


def _good208(x):
    def body(c, _):
        return c * x, jnp.float32(0)
    out, _ = jax.lax.scan(body, jnp.zeros(x.shape, jnp.bfloat16),
                          None, length=4)
    return out


def _accum208(x):
    # a TRUE fp32 accumulator of bf16 addends: intended, must not fire
    def body(c, _):
        return c + jnp.sum(x.astype(jnp.float32)), jnp.float32(0)
    out, _ = jax.lax.scan(body, jnp.float32(0), None, length=4)
    return out


def _sup208(x):
    def body(c, _):
        y = (c.astype(jnp.bfloat16) * x).astype(jnp.float32)
        return y, jnp.float32(0)
    out, _ = jax.lax.scan(body, jnp.zeros(x.shape, jnp.float32), None, length=4)  # apexlint: disable=APX208 -- test fixture
    return out


def test_apx208_widened_carry_fires_twins_pass():
    x = jnp.ones((8, 8), jnp.bfloat16)
    assert spmd_ids(_bad208, (x,)) == ["APX208"]
    assert check_entry_spmd(_good208, (x,)) == []
    assert check_entry_spmd(_accum208, (x,)) == []


def test_apx208_suppression():
    assert_suppressed("APX208", _sup208, (jnp.ones((8, 8), jnp.bfloat16),))


# ---------------------------------------------------------------------------
# APX209: pipeline-schedule divergence (self-axis-gated ppermute)
# ---------------------------------------------------------------------------

_RING = [(0, 1), (1, 0)]


def _pipe_mesh(extra=()):
    axes = ("pipe",) + tuple(extra)
    n = 2 * max(1, len(extra) * 4)
    shape = (2,) + ((4,) if extra else ())
    return Mesh(np.asarray(jax.devices()[:n]).reshape(shape), axes)


def _smap_pipe(fn, extra=()):
    mesh = _pipe_mesh(extra)
    return jax.shard_map(fn, mesh=mesh, in_specs=(P("pipe"),),
                         out_specs=P("pipe"), check_vma=False)


def _bad209(x):
    r = jax.lax.axis_index("pipe")
    return jax.lax.cond(
        r < 1,
        lambda v: jax.lax.ppermute(v, "pipe", _RING),
        lambda v: v, x)


def _good209(x):
    # the timetable-executor idiom: every rank runs the SAME ppermute
    # every tick; activity is masked in the payload, not the schedule
    r = jax.lax.axis_index("pipe")
    v = jnp.where(r < 1, x, jnp.zeros_like(x))
    return jax.lax.ppermute(v, "pipe", _RING)


def _cross209(x):
    # gated on the DATA rank, permuting over PIPE: still a schedule
    # divergence (APX201), but not the pipeline self-gating pattern
    r = jax.lax.axis_index("data")
    return jax.lax.cond(
        r < 1,
        lambda v: jax.lax.ppermute(v, "pipe", _RING),
        lambda v: v, x)


def _sup209(x):
    r = jax.lax.axis_index("pipe")
    return jax.lax.cond(
        r < 1,
        lambda v: jax.lax.ppermute(v, "pipe", _RING),  # apexlint: disable=APX209 -- test fixture
        lambda v: v, x)


def test_apx209_self_gated_ppermute_fires_masked_twin_passes():
    x = jnp.ones((8, 4))
    assert spmd_ids(_smap_pipe(_bad209), (x,),
                    mesh_axes=("pipe",)) == ["APX209"]
    assert check_entry_spmd(_smap_pipe(_good209), (x,),
                            mesh_axes=("pipe",)) == []


def test_apx209_cross_axis_gating_stays_apx201():
    x = jnp.ones((8, 4))
    assert spmd_ids(_smap_pipe(_cross209, extra=("data",)), (x,),
                    mesh_axes=("pipe", "data")) == ["APX201"]


def test_apx209_registered_and_suppressible():
    assert "APX209" in SPMD_RULE_IDS
    assert RULES["APX209"].name == "pipeline-schedule-divergence"
    assert_suppressed("APX209", _smap_pipe(_sup209), (jnp.ones((8, 4)),),
                      mesh_axes=("pipe",))


# ---------------------------------------------------------------------------
# read-only contract: analysis leaves the traced program bit-identical
# ---------------------------------------------------------------------------

def test_spmd_analysis_is_read_only_on_builtin_entries():
    specs = {s.name: s for s in builtin_entries()}
    for name in ("ddp_syncbn_grads", "overlap_staged_grads",
                 "trainer_per_step"):
        spec = specs[name]
        fn, args = spec.make()
        before = str(jax.make_jaxpr(fn)(*args))
        check_entry_spmd(fn, args, name=name, mesh_axes=spec.mesh_axes,
                         donate_argnums=spec.donate_argnums)
        after = str(jax.make_jaxpr(fn)(*args))
        assert before == after, f"entry {name} was altered by analysis"


def test_spmd_analysis_is_read_only_on_fixtures():
    x = jnp.ones((4, 4))
    fn = _smap(_bad201)
    before = str(jax.make_jaxpr(fn)(x))
    check_entry_spmd(fn, (x,), mesh_axes=("data",))
    assert str(jax.make_jaxpr(fn)(x)) == before


# ---------------------------------------------------------------------------
# static donation: re-derives the trainer's runtime DonationReport
# ---------------------------------------------------------------------------

def _tstep(state, batch):
    params, opt = state

    def loss_fn(p):
        return jnp.mean((batch @ p["w"]) ** 2)
    loss, g = jax.value_and_grad(loss_fn)(params)
    new_p = jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, params, g)
    return (new_p, opt + 1.0), loss


def _tstate():
    return ({"w": jnp.ones((64, 8))}, jnp.zeros((3,)))


def test_static_donation_matches_runtime_all_aliased():
    tr = trainer.build(_tstep, _tstate(), jnp.ones((4, 64)))
    rep, sd = tr.donation, tr.static_donation()
    assert isinstance(sd, StaticDonation)
    assert (sd.declared, sd.aliased, sd.dropped) == (
        rep.declared, rep.aliased, rep.dropped)
    assert sd.refused == () and len(rep.refused) == 0
    assert sd.ok and sd.to_json()["ok"] is True


def test_static_donation_matches_runtime_refusal():
    import warnings

    def bad(state, batch):
        return {"w": (state["w"] + jnp.mean(batch)).astype(jnp.bfloat16),
                "v": state["v"] * 2.0}, jnp.mean(batch)

    s = {"w": jnp.ones((4,)), "v": jnp.zeros((2,))}
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        tr = trainer.build(bad, s, jnp.ones((3,)))
    rep, sd = tr.donation, tr.static_donation()
    assert (sd.declared, sd.aliased, len(sd.refused), sd.dropped) == (
        rep.declared, rep.aliased, len(rep.refused), rep.dropped)
    assert not sd.ok and "float32[4]" in sd.refused[0]


def test_static_donation_matches_runtime_dead_code_drop():
    def dropper(state, batch):
        return {"w": state["w"] + jnp.mean(batch),
                "unused": jnp.zeros((7,))}, jnp.mean(batch)

    s = {"w": jnp.ones((4,)), "unused": jnp.zeros((7,))}
    tr = trainer.build(dropper, s, jnp.ones((3,)))
    rep, sd = tr.donation, tr.static_donation()
    assert (sd.declared, sd.aliased, sd.dropped) == (
        rep.declared, rep.aliased, rep.dropped)
    assert sd.dropped == 1 and sd.refused == ()


def test_static_donation_on_mesh_wrapped_bench_shape():
    # the bench form: shard_map-wrapped step built through the trainer —
    # the analyzer must read ordering through the wrapper eqn
    tr = trainer.build(_tstep, _tstate(), jnp.ones((4, 64)),
                       mesh=_mesh(), batch_spec=P("data"))
    rep, sd = tr.donation, tr.static_donation()
    assert (sd.declared, sd.aliased, len(sd.refused)) == (
        rep.declared, rep.aliased, len(rep.refused))
    assert sd.declared == sd.aliased == 2


def test_trainer_check_spmd_seam():
    tr = trainer.build(_tstep, _tstate(), jnp.ones((4, 64)),
                       mesh=_mesh(), batch_spec=P("data"))
    assert tr.check_spmd() == []
    assert tr.donate_argnums == (0,)
    assert tr.mesh_axes == ("data",)

    def late_read(state, batch):
        new = jax.tree_util.tree_map(lambda a: a + jnp.mean(batch), state)
        return new, jnp.sum(state["w"])      # use after donation

    tr2 = trainer.build(late_read, {"w": jnp.ones((4,))}, jnp.ones((3,)),
                        config=trainer.TrainerConfig(audit_donation=False))
    assert [f.rule_id for f in tr2.check_spmd()] == ["APX203"]


def test_trainer_constructed_directly_raises_on_seam():
    tr = trainer.Trainer(fn=lambda s, b: (s, 0.0),
                         traced_fn=lambda s, b: (s, 0.0),
                         config=trainer.TrainerConfig(), donation=None)
    with pytest.raises(ValueError, match="example_args"):
        tr.check_spmd()
    with pytest.raises(ValueError, match="example_args"):
        tr.static_donation()


# ---------------------------------------------------------------------------
# rules / catalog / entry sweep
# ---------------------------------------------------------------------------

def test_spmd_rule_ids_registered():
    assert SPMD_RULE_IDS == tuple(f"APX20{i}" for i in range(1, 10))
    for rid in SPMD_RULE_IDS:
        assert RULES[rid].severity in ("error", "warning")
    assert RULES["APX201"].severity == "error"
    assert RULES["APX202"].severity == "error"
    assert RULES["APX209"].severity == "error"


def test_cli_list_rules_includes_spmd(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in SPMD_RULE_IDS:
        assert rid in out


@pytest.mark.apexlint
def test_builtin_entry_sweep_spmd_clean():
    assert run_entries_spmd() == []


# ---------------------------------------------------------------------------
# SARIF output
# ---------------------------------------------------------------------------

def test_sarif_document_shape():
    import json
    err = Finding("APX201", "a.py", 3, "deadlock")
    warn = Finding("APX204", "b.py", 0, "replicated")
    sup = Finding("APX205", "a.py", 9, "thrash")
    doc = json.loads(render_sarif([err, warn], [sup]))
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "apexlint"
    rule_ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert rule_ids == ["APX201", "APX204", "APX205"]
    results = run["results"]
    assert [r["ruleId"] for r in results] == ["APX201", "APX204", "APX205"]
    assert results[0]["level"] == "error"
    assert results[0]["locations"][0]["physicalLocation"]["region"][
        "startLine"] == 3
    assert results[1]["locations"][0]["physicalLocation"]["region"][
        "startLine"] == 1          # line 0 clamps to 1 (SARIF minimum)
    assert results[2]["suppressions"] == [{"kind": "inSource"}]
    assert "suppressions" not in results[0]


def test_sarif_carries_baselined_as_external_suppressions():
    import json
    new = Finding("APX201", "a.py", 3, "deadlock")
    known = Finding("APX204", "b.py", 5, "replicated")
    doc = json.loads(render_sarif([new], [], [known]))
    results = doc["runs"][0]["results"]
    # baselined findings are carried (code scanning would otherwise
    # auto-close and later flap their alerts), marked external
    assert [r["ruleId"] for r in results] == ["APX201", "APX204"]
    assert results[1]["suppressions"] == [{"kind": "external"}]
    assert "APX204" in [r["id"] for r in
                        doc["runs"][0]["tool"]["driver"]["rules"]]


def test_cli_sarif_format(tmp_path, capsys):
    import json
    bad = "import jax.numpy as jnp\ny = jnp.zeros((4,), jnp.bfloat16)\n"
    (tmp_path / "bad.py").write_text(bad)
    rc = lint_main([str(tmp_path / "bad.py"), "--no-jaxpr",
                    "--format=sarif"])
    assert rc == 0                  # APX005 is a warning; not strict
    doc = json.loads(capsys.readouterr().out)
    assert [r["ruleId"] for r in doc["runs"][0]["results"]] == ["APX005"]


# ---------------------------------------------------------------------------
# baselines
# ---------------------------------------------------------------------------

def test_baseline_roundtrip_and_count_semantics(tmp_path):
    f1 = Finding("APX005", "m.py", 2, "msg")
    f2 = Finding("APX005", "m.py", 9, "msg")     # same key, second hit
    f3 = Finding("APX007", "m.py", 4, "other")
    path = str(tmp_path / "base.json")
    write_baseline(path, [f1, f3])
    known = load_baseline(path)
    new, old = split_baseline([f1, f2, f3], known)
    # one APX005 instance is known; the SECOND identical one is NEW
    assert [f.line for f in old] == [2, 4]
    assert [f.line for f in new] == [9]


def test_baseline_version_guard(tmp_path):
    p = tmp_path / "base.json"
    p.write_text('{"version": 99, "findings": []}')
    with pytest.raises(ValueError, match="version"):
        load_baseline(str(p))


def test_cli_baseline_gate(tmp_path):
    bad = "import jax.numpy as jnp\ny = jnp.zeros((4,), jnp.bfloat16)\n"
    src = tmp_path / "bad.py"
    src.write_text(bad)
    base = str(tmp_path / "base.json")

    # no baseline file yet: usage error, with the remedy named
    assert lint_main([str(src), "--no-jaxpr", "--strict",
                      "--baseline", base]) == 2
    # record the current findings
    assert lint_main([str(src), "--no-jaxpr", "--strict",
                      "--baseline", base, "--update-baseline"]) == 0
    # known finding: gate passes
    assert lint_main([str(src), "--no-jaxpr", "--strict",
                      "--baseline", base]) == 0
    # a NEW finding still fails the gate
    src.write_text(bad + "z = jnp.ones((2,), jnp.float16)\n")
    assert lint_main([str(src), "--no-jaxpr", "--strict",
                      "--baseline", base]) == 1
    # --update-baseline without --baseline: usage error
    assert lint_main([str(src), "--no-jaxpr",
                      "--update-baseline"]) == 2
