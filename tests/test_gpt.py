"""Decoder LM + sequence-parallel integration: the TransformerLM on
sequence shards (ring / Ulysses over a mesh axis) must match the same
model run dense on one device — the end-to-end check of the long-context
stack (flash kernels + SP attention + LN/MLP locality + global position
embeddings)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.models import GPTTiny
from apex_tpu.models.gpt import next_token_loss

# Integration tier (PR 1): this whole module rides `-m slow` — end-to-end LM numerics (decode/seq-parallel parity).
# Tier-1 (-m 'not slow') must fit the 870 s gate budget; the fast cross-
# sections of this stack stay in tier-1 via test_zero/test_parallel/
# test_param_groups/test_attention and the ci/gate.sh dryrun parts.
pytestmark = pytest.mark.slow

NDEV = 8


@pytest.fixture(scope="module")
def mesh():
    return parallel.make_mesh(axis_names=("seq",))


def _make(seq_parallel=None, num_heads=4):
    # params are identical across seq_parallel settings (it only changes
    # runtime ops), so init a dense twin and apply the SP model
    return GPTTiny(vocab_size=256, max_seq=NDEV * 16, num_heads=num_heads,
                   seq_parallel=seq_parallel,
                   axis_name="seq" if seq_parallel else None)


@pytest.mark.parametrize("scheme", ["ring", "ulysses"])
def test_lm_seq_parallel_matches_dense(mesh, scheme):
    s = NDEV * 16
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, s), 0, 256)

    heads = 8 if scheme == "ulysses" else 4   # ulysses: heads % devices
    dense = _make(None, heads)
    variables = dense.init(jax.random.PRNGKey(1), tokens)
    want = dense.apply(variables, tokens)

    sp = _make(scheme, heads)

    def per_device(tokens_):
        s_loc = tokens_.shape[1]
        off = jax.lax.axis_index("seq") * s_loc
        return sp.apply(variables, tokens_, pos_offset=off)

    got = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(P(None, "seq"),),
        out_specs=P(None, "seq"), check_vma=False))(tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("scheme,kind", [
    ("ring", "relative_bias"),     # row-varying bias, global q offsets
    ("ring", "alibi"),             # column form around the ring
    ("ulysses", "alibi"),          # column form through the all-to-all
])
def test_lm_seq_parallel_position_bias_matches_dense(mesh, scheme, kind):
    """r5: learned position biases compose with sequence parallelism —
    the SP model (bias built per-shard with GLOBAL positions) matches
    the dense twin's outputs, and the bias params' grads, psum'd over
    the axis per the replicated-param convention, match the dense
    grads."""
    s = NDEV * 16
    heads = 8
    tokens = jax.random.randint(jax.random.PRNGKey(40), (2, s), 0, 256)
    kw = ({"relative_bias": True} if kind == "relative_bias"
          else {"alibi": True, "alibi_learned": True})

    def make(sp):
        return GPTTiny(vocab_size=256, max_seq=s, num_heads=heads,
                       seq_parallel=sp,
                       axis_name="seq" if sp else None, **kw)

    dense = make(None)
    variables = dense.init(jax.random.PRNGKey(41), tokens)
    want = dense.apply(variables, tokens)

    def dense_loss(p):
        return next_token_loss(dense.apply({"params": p}, tokens),
                               tokens)

    want_g = jax.grad(dense_loss)(variables["params"])

    sp = make(scheme)

    def per_device(tokens_):
        s_loc = tokens_.shape[1]
        off = jax.lax.axis_index("seq") * s_loc
        out = sp.apply(variables, tokens_, pos_offset=off)

        def loss(p):
            return next_token_loss(
                sp.apply({"params": p}, tokens_, pos_offset=off),
                tokens_, "seq")

        g = jax.lax.psum(jax.grad(loss)(variables["params"]), "seq")
        return out, g

    got, got_g = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(P(None, "seq"),),
        out_specs=(P(None, "seq"), P()), check_vma=False))(tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    name = "rel_bias" if kind == "relative_bias" else "alibi_slopes"
    flat_w, _ = jax.tree_util.tree_flatten_with_path(want_g)
    flat_g, _ = jax.tree_util.tree_flatten_with_path(got_g)
    checked_bias = False
    for (pw, gw), (_, gg) in zip(flat_w, flat_g):
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(gw), rtol=5e-3, atol=5e-4,
            err_msg=str(pw))
        if name in str(pw):
            checked_bias = True
            assert float(jnp.max(jnp.abs(gw))) > 0
    assert checked_bias


def test_lm_seq_parallel_train_step(mesh):
    """One full sequence-parallel LM train step: grads via the collective
    transposes + fused optimizer update."""
    from apex_tpu import amp, optimizers
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss

    s = NDEV * 16
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, s), 0, 256)
    sp = _make("ring")
    variables = _make(None).init(jax.random.PRNGKey(3), tokens)
    params32 = variables["params"]

    inner = optimizers.FusedAdam(lr=1e-3)
    _, aopt = amp.initialize(None, inner, opt_level="O5", verbosity=0)
    params = amp.cast_model(params32, amp.resolve("O5"))
    opt_state = aopt.init(params)

    def per_device(params, opt_state, tokens_):
        s_loc = tokens_.shape[1]
        off = jax.lax.axis_index("seq") * s_loc

        def scaled(p):
            logits = sp.apply({"params": p}, tokens_, pos_offset=off)
            # globally-normalized next-token loss (boundary targets
            # ppermuted in); cross-shard grad flow rides the attention
            # collectives' transposes
            loss = next_token_loss(logits, tokens_, "seq")
            return aopt.scale_loss(loss, opt_state), loss

        grads, loss = jax.grad(scaled, has_aux=True)(params)
        # global loss -> each device holds its shard's contribution: sum
        grads = jax.lax.psum(grads, "seq")
        new_params, new_opt, _ = aopt.step(grads, params, opt_state)
        return new_params, new_opt, jax.lax.pmean(loss, "seq")

    rep = P()
    step = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(rep, rep, P(None, "seq")),
        out_specs=(rep, rep, rep), check_vma=False))
    p1, o1, loss1 = step(params, opt_state, tokens)
    p2, o2, loss2 = step(p1, o1, tokens)
    assert np.isfinite(float(loss1)) and np.isfinite(float(loss2))
    assert float(loss2) < float(loss1)  # memorizing one batch


def test_lm_dropout_path():
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 64), 0, 256)
    m = GPTTiny(vocab_size=256, max_seq=64, dropout=0.2)
    v = m.init(jax.random.PRNGKey(5), tokens)
    y1 = m.apply(v, tokens, deterministic=False,
                 dropout_rng=jax.random.PRNGKey(6))
    y2 = m.apply(v, tokens, deterministic=False,
                 dropout_rng=jax.random.PRNGKey(7))
    assert np.isfinite(np.asarray(y1)).all()
    assert not np.allclose(np.asarray(y1), np.asarray(y2))


def test_lm_2d_mesh_zero_plus_ring():
    """2-D composition: 4-way data parallel x 2-way sequence parallel in
    ONE train step — ZeRO-sharded Adam state over the data axis, ring
    attention over the seq axis. The full multi-dimensional story of
    SURVEY.md §2.4 on a 2-D mesh."""
    from jax.sharding import NamedSharding
    from apex_tpu import amp
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss

    d_data, d_seq = 4, 2
    mesh2 = parallel.make_mesh([d_data, d_seq], ("data", "seq"))
    s = d_seq * 32
    batch = d_data * 2
    tokens = jax.random.randint(jax.random.PRNGKey(10), (batch, s), 0, 256)

    sp = GPTTiny(vocab_size=256, max_seq=s, seq_parallel="ring",
                 axis_name="seq")
    variables = GPTTiny(vocab_size=256, max_seq=s).init(
        jax.random.PRNGKey(11), tokens[:1])
    params32 = variables["params"]

    zopt = DistributedFusedAdam(lr=1e-3, axis_name="data",
                                shard_count=d_data)
    props = amp.resolve("O5")
    params = amp.cast_model(params32, props)
    zstate = zopt.init(params32)
    zspecs = zopt.state_pspec()

    def per_device(params, zstate, tokens_):
        off = jax.lax.axis_index("seq") * tokens_.shape[1]

        def loss_fn(p):
            logits = sp.apply({"params": p}, tokens_, pos_offset=off)
            return next_token_loss(logits, tokens_, "seq")

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # seq-axis grads: the globally-normalized loss leaves each device
        # holding only its shard's contribution — sum over the seq axis;
        # data-axis reduction happens inside the ZeRO psum_scatter
        grads = jax.lax.psum(grads, "seq")
        new_params, new_zstate = zopt.step(grads, params, zstate)
        return new_params, new_zstate, jax.lax.pmean(
            jax.lax.pmean(loss, "seq"), "data")

    rep = P()
    step = jax.jit(shard_map(
        per_device, mesh=mesh2,
        in_specs=(rep, zspecs, P("data", "seq")),
        out_specs=(rep, zspecs, rep), check_vma=False))

    zstate = jax.device_put(
        zstate, jax.tree_util.tree_map(
            lambda spc: NamedSharding(mesh2, spc), zspecs))
    p1, z1, loss1 = step(params, zstate, tokens)
    p2, z2, loss2 = step(p1, z1, tokens)
    assert np.isfinite(float(loss1)) and np.isfinite(float(loss2))
    assert float(loss2) < float(loss1)


def test_next_token_loss_seq_parallel_matches_dense(mesh):
    """The seq-parallel objective must EQUAL the dense objective — shard
    boundary targets are ppermuted in, the last global position is masked,
    and normalization is global (ADVICE r1: a per-shard logits[:, :-1] vs
    tokens[:, 1:] loss silently drops one target per boundary)."""
    b, s = 2, NDEV * 16
    vocab = 64
    tokens = jax.random.randint(jax.random.PRNGKey(30), (b, s), 0, vocab)
    logits = jax.random.normal(jax.random.PRNGKey(31), (b, s, vocab))

    dense_val, dense_grad = jax.value_and_grad(
        lambda lg: next_token_loss(lg, tokens))(logits)

    def per_device(lg, tk):
        val, grad = jax.value_and_grad(
            lambda l: next_token_loss(l, tk, "seq"))(lg)
        return val, grad

    sp_val, sp_grad = jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(P(None, "seq", None), P(None, "seq")),
        out_specs=(P(), P(None, "seq", None)), check_vma=False))(
        logits, tokens)

    np.testing.assert_allclose(float(sp_val), float(dense_val), rtol=1e-6)
    # each shard's grad slice equals the dense grad slice (grads w.r.t.
    # logits are local — no cross-shard terms for the loss itself)
    np.testing.assert_allclose(np.asarray(sp_grad), np.asarray(dense_grad),
                               rtol=1e-5, atol=1e-7)


def test_lm_remat_matches_no_remat():
    """remat=True (jax.checkpoint per block) must not change values or
    grads — only the backward's memory/recompute schedule."""
    tokens = jax.random.randint(jax.random.PRNGKey(40), (2, 64), 0, 256)
    m = GPTTiny(vocab_size=256, max_seq=64)
    mr = GPTTiny(vocab_size=256, max_seq=64, remat=True)
    v = m.init(jax.random.PRNGKey(41), tokens)

    def loss(mod, p):
        lg = mod.apply({"params": p}, tokens)
        return next_token_loss(lg, tokens)

    l1, g1 = jax.value_and_grad(lambda p: loss(m, p))(v["params"])
    l2, g2 = jax.value_and_grad(lambda p: loss(mr, p))(v["params"])
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7), g1, g2)


def test_lm_remat_composes_with_dropout():
    """remat + dropout>0 must train (ADVICE r2: nn.remat turned the
    ``deterministic`` kwarg into a tracer and the dropout branch crashed).
    With the same dropout rng, remat and no-remat draw identical masks, so
    values and grads must match exactly."""
    tokens = jax.random.randint(jax.random.PRNGKey(60), (2, 64), 0, 256)
    m = GPTTiny(vocab_size=256, max_seq=64, dropout=0.1)
    mr = GPTTiny(vocab_size=256, max_seq=64, dropout=0.1, remat=True)
    v = m.init(jax.random.PRNGKey(61), tokens)
    rng = jax.random.PRNGKey(62)

    def loss(mod, p):
        lg = mod.apply({"params": p}, tokens, deterministic=False,
                       dropout_rng=rng)
        return next_token_loss(lg, tokens)

    l1, g1 = jax.value_and_grad(lambda p: loss(m, p))(v["params"])
    l2, g2 = jax.value_and_grad(lambda p: loss(mr, p))(v["params"])
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7), g1, g2)


def test_chunked_loss_ragged_seq_pads():
    """S not divisible by chunk pads the tail instead of shrinking the
    chunk (ADVICE r2: the gcd fallback degraded to chunk=1 for prime S).
    Value and grads must still match the dense loss."""
    from apex_tpu.models.gpt import chunked_next_token_loss

    b, s, d, vocab = 2, 61, 32, 64   # s prime: old gcd fallback -> chunk=1
    tokens = jax.random.randint(jax.random.PRNGKey(70), (b, s), 0, vocab)
    hidden = jax.random.normal(jax.random.PRNGKey(71), (b, s, d))
    head = {"kernel": jax.random.normal(jax.random.PRNGKey(72), (d, vocab))
            * 0.1, "bias": jnp.zeros((vocab,))}

    def dense(h_):
        return next_token_loss(h_ @ head["kernel"] + head["bias"], tokens)

    def chunked(h_):
        return chunked_next_token_loss(h_, head, tokens, chunk=16)

    l1, g1 = jax.value_and_grad(dense)(hidden)
    l2, g2 = jax.value_and_grad(chunked)(hidden)
    np.testing.assert_allclose(float(l2), float(l1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(g1),
                               rtol=2e-5, atol=1e-6)
    # scan length is ceil(s/chunk), not s (the degraded-chunk failure mode)
    jaxpr = jax.make_jaxpr(chunked)(hidden)
    scans = [e for e in jaxpr.jaxpr.eqns if e.primitive.name == "scan"]
    assert scans and scans[0].params["length"] == 4


def test_chunked_next_token_loss_matches_dense():
    """chunked_next_token_loss (per-chunk head + xent under
    jax.checkpoint) must equal next_token_loss on full logits — value and
    grads — in both dense and seq-parallel layouts."""
    from apex_tpu.models.gpt import chunked_next_token_loss

    tokens = jax.random.randint(jax.random.PRNGKey(50), (2, 64), 0, 256)
    m = GPTTiny(vocab_size=256, max_seq=64)
    v = m.init(jax.random.PRNGKey(51), tokens)

    def full(p):
        return next_token_loss(m.apply({"params": p}, tokens), tokens)

    def chunked(p):
        hid = m.apply({"params": p}, tokens, return_hidden=True)
        return chunked_next_token_loss(hid, p["head"], tokens, chunk=16)

    l1, g1 = jax.value_and_grad(full)(v["params"])
    l2, g2 = jax.value_and_grad(chunked)(v["params"])
    np.testing.assert_allclose(float(l2), float(l1), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6), g2, g1)


def test_chunked_loss_seq_parallel(mesh):
    from apex_tpu.models.gpt import chunked_next_token_loss

    b, s, d, vocab = 2, NDEV * 16, 32, 64
    tokens = jax.random.randint(jax.random.PRNGKey(52), (b, s), 0, vocab)
    hidden = jax.random.normal(jax.random.PRNGKey(53), (b, s, d))
    head = {"kernel": jax.random.normal(jax.random.PRNGKey(54), (d, vocab))
            * 0.1, "bias": jnp.zeros((vocab,))}

    want = float(next_token_loss(
        hidden @ head["kernel"] + head["bias"], tokens))

    def per_device(h_, t_):
        return chunked_next_token_loss(h_, head, t_, chunk=8,
                                       axis_name="seq")

    got = jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(P(None, "seq", None), P(None, "seq")),
        out_specs=P(), check_vma=False))(hidden, tokens)
    np.testing.assert_allclose(float(got), want, rtol=1e-5)


# ---------------------------------------------------------------------------
# KV-cache autoregressive decoding
# ---------------------------------------------------------------------------

def test_decode_logits_match_full_forward():
    """Prefill (chunked cache write) + 1-token steps reproduce the full
    forward's logits at every position."""
    import numpy as np
    from apex_tpu.models import TransformerLM

    lm = TransformerLM(vocab_size=97, num_layers=2, embed_dim=32,
                       num_heads=4, max_seq=24)
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 12), 0, 97)
    params = lm.init(jax.random.PRNGKey(1), toks)["params"]
    want = lm.apply({"params": params}, toks)          # (B, 12, V)

    dec = lm.clone(decode=True, decode_max_len=24)
    # prefill on the first 8 tokens
    lg_pre, vs = dec.apply({"params": params}, toks[:, :8],
                           mutable=["cache"])
    np.testing.assert_allclose(np.asarray(lg_pre), np.asarray(want[:, :8]),
                               rtol=2e-4, atol=2e-4)
    # then 1-token steps for positions 8..11
    cache = vs["cache"]
    for i in range(8, 12):
        lg, vs = dec.apply({"params": params, "cache": cache},
                           toks[:, i:i + 1], pos_offset=i,
                           mutable=["cache"])
        cache = vs["cache"]
        np.testing.assert_allclose(
            np.asarray(lg[:, 0]), np.asarray(want[:, i]),
            rtol=2e-4, atol=2e-4, err_msg=f"position {i}")


def test_generate_greedy_matches_reforward_reference():
    """generate() greedy output == the naive loop that re-runs the full
    forward on the growing sequence and argmaxes the last position."""
    import numpy as np
    from apex_tpu.models import TransformerLM
    from apex_tpu.models.gpt import generate

    lm = TransformerLM(vocab_size=61, num_layers=2, embed_dim=32,
                       num_heads=4, max_seq=20)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0, 61)
    params = lm.init(jax.random.PRNGKey(3), prompt)["params"]

    seq = prompt
    for _ in range(8):
        lg = lm.apply({"params": params}, seq)
        seq = jnp.concatenate(
            [seq, jnp.argmax(lg[:, -1], axis=-1)[:, None].astype(
                seq.dtype)], axis=1)

    got = generate(lm, params, prompt, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(seq))


def test_generate_sampling_shapes_and_determinism():
    from apex_tpu.models import TransformerLM
    from apex_tpu.models.gpt import generate

    lm = TransformerLM(vocab_size=31, num_layers=1, embed_dim=16,
                       num_heads=2, max_seq=16)
    prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 4), 0, 31)
    params = lm.init(jax.random.PRNGKey(5), prompt)["params"]
    a = generate(lm, params, prompt, 6, temperature=0.8,
                 rng=jax.random.PRNGKey(6))
    b = generate(lm, params, prompt, 6, temperature=0.8,
                 rng=jax.random.PRNGKey(6))
    assert a.shape == (1, 10)
    import numpy as np
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    import pytest
    with pytest.raises(ValueError, match="exceeds the cache"):
        generate(lm, params, prompt, 100)


@pytest.mark.parametrize("decode_impl", ["einsum", "fused"])
def test_tp_decode_matches_dense_decode(decode_impl):
    """Tensor-parallel decode: head-sharded KV caches on a 2-way model
    axis reproduce the dense decode logits (prefill + 1-token step) on
    BOTH step backends — 'fused' feeds the elision kernel per-device
    (local head count, local cache); the cache-shape assertion proves
    the fused path actually resolved (its cache rounds to the 128-row
    block grid), so a silent demotion to einsum fails loudly."""
    import numpy as np
    from jax import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from apex_tpu.models import TransformerLM
    from apex_tpu.parallel import lm_tp_pspecs, tp_shard_lm_params

    tp, heads, e = 2, 4, 32
    lm = TransformerLM(vocab_size=53, num_layers=2, embed_dim=e,
                       num_heads=heads, max_seq=16)
    toks = jax.random.randint(jax.random.PRNGKey(6), (2, 8), 0, 53)
    params = lm.init(jax.random.PRNGKey(7), toks)["params"]

    dec = lm.clone(decode=True, decode_max_len=16)
    want_pre, vs = dec.apply({"params": params}, toks,
                             mutable=["cache"])
    want_step, _ = dec.apply(
        {"params": params, "cache": vs["cache"]},
        jnp.full((2, 1), 5, toks.dtype), pos_offset=8,
        mutable=["cache"])

    params_tp = tp_shard_lm_params(params, tp)
    specs = lm_tp_pspecs(params_tp, axis="model")
    local = dec.clone(num_heads=heads // tp, decode_impl=decode_impl,
                      tensor_parallel_axis="model",
                      tensor_parallel_size=tp)
    mesh = Mesh(np.asarray(jax.devices()[:tp]), ("model",))

    def run(p, t):
        lg1, vs_ = local.apply({"params": p}, t, mutable=["cache"])
        cache_rows = vs_["cache"]["block_0"]["attn"][
            "cached_key"].shape[2]
        lg2, _ = local.apply(
            {"params": p, "cache": vs_["cache"]},
            jnp.full((2, 1), 5, t.dtype), pos_offset=8,
            mutable=["cache"])
        return lg1, lg2, cache_rows

    lg1, lg2, cache_rows = jax.jit(shard_map(
        run, mesh=mesh, in_specs=(specs, P()),
        out_specs=(P(), P(), P()), check_vma=False))(
        jax.device_put(params_tp, jax.tree_util.tree_map(
            lambda sp: NamedSharding(mesh, sp), specs)), toks)
    # einsum keeps decode_max_len; fused rounds to the block grid —
    # the observable proof of which backend resolved
    assert cache_rows == (16 if decode_impl == "einsum" else 128)
    np.testing.assert_allclose(np.asarray(lg1), np.asarray(want_pre),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(lg2), np.asarray(want_step),
                               rtol=2e-4, atol=2e-4)


def test_generate_top_k_and_top_p():
    """top_k=1 must reduce to greedy regardless of temperature; top_p
    truncation keeps outputs inside the nucleus (valid tokens only)."""
    import numpy as np
    from apex_tpu.models import TransformerLM
    from apex_tpu.models.gpt import generate

    lm = TransformerLM(vocab_size=41, num_layers=1, embed_dim=16,
                       num_heads=2, max_seq=16)
    prompt = jax.random.randint(jax.random.PRNGKey(8), (2, 4), 0, 41)
    params = lm.init(jax.random.PRNGKey(9), prompt)["params"]

    greedy = generate(lm, params, prompt, 6)
    topk1 = generate(lm, params, prompt, 6, temperature=1.5,
                     rng=jax.random.PRNGKey(10), top_k=1)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(topk1))

    out = generate(lm, params, prompt, 6, temperature=1.0,
                   rng=jax.random.PRNGKey(11), top_p=0.9)
    arr = np.asarray(out)
    assert arr.shape == (2, 10)
    assert (0 <= arr).all() and (arr < 41).all()
    # tiny top_p -> only the argmax survives the nucleus -> greedy
    tp_small = generate(lm, params, prompt, 6, temperature=1.0,
                        rng=jax.random.PRNGKey(12), top_p=1e-6)
    np.testing.assert_array_equal(np.asarray(greedy),
                                  np.asarray(tp_small))
    # COMBINED top_k + top_p (r5 single-sort path): must match the
    # sequential two-sort reference — top-k truncation first, nucleus
    # computed on the truncated distribution
    logits = lm.apply({"params": params}, prompt)[:, -1].astype(
        jnp.float32) / 1.3
    kth = jnp.sort(logits, axis=-1)[..., -5][..., None]
    ref = jnp.where(logits < kth, -jnp.inf, logits)
    srt = jnp.sort(ref, axis=-1)[..., ::-1]
    cum = jnp.cumsum(jax.nn.softmax(srt, axis=-1), axis=-1)
    keep = jnp.concatenate(
        [jnp.ones_like(cum[..., :1], bool), cum[..., :-1] < 0.7],
        axis=-1)
    cutoff = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                     keepdims=True)
    ref = jnp.where(ref < cutoff, -jnp.inf, ref)
    # same rng -> same categorical draw iff the truncated logits match
    a = generate(lm, params, prompt, 1, temperature=1.3,
                 rng=jax.random.PRNGKey(13), top_k=5, top_p=0.7)
    want_tok = jax.random.categorical(
        jax.random.split(jax.random.PRNGKey(13), 1)[0], ref, axis=-1)
    np.testing.assert_array_equal(np.asarray(a[:, -1]),
                                  np.asarray(want_tok))


def test_tie_embeddings():
    """Tied LM head: no separate head params, logits = h @ E^T, the
    shared table receives grads from BOTH uses, and training/decoding
    paths all work."""
    import numpy as np
    from apex_tpu.models import TransformerLM
    from apex_tpu.models.gpt import (chunked_next_token_loss, generate,
                                     next_token_loss)

    lm = TransformerLM(vocab_size=37, num_layers=1, embed_dim=16,
                       num_heads=2, max_seq=12, tie_embeddings=True)
    toks = jax.random.randint(jax.random.PRNGKey(13), (2, 8), 0, 37)
    params = lm.init(jax.random.PRNGKey(14), toks)["params"]
    assert "head" not in params

    logits = lm.apply({"params": params}, toks)
    hid = lm.apply({"params": params}, toks, return_hidden=True)
    want = hid.astype(jnp.float32) @ np.asarray(
        params["tok_emb"]["embedding"]).T.astype(np.float32)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(want),
                               rtol=2e-4, atol=2e-4)

    g = jax.grad(lambda p: next_token_loss(
        lm.apply({"params": p}, toks), toks))(params)
    assert float(jnp.max(jnp.abs(g["tok_emb"]["embedding"]))) > 0

    # chunked loss with the transposed shared table
    loss_full = next_token_loss(logits, toks)
    loss_chunk = chunked_next_token_loss(
        hid, {"kernel": params["tok_emb"]["embedding"].T}, toks,
        chunk=4)
    np.testing.assert_allclose(float(loss_chunk), float(loss_full),
                               rtol=1e-5)

    out = generate(lm, params, toks[:, :4], 4)
    assert out.shape == (2, 8)


def test_generate_rejects_overflowing_position_table():
    from apex_tpu.models import TransformerLM
    from apex_tpu.models.gpt import generate

    lm = TransformerLM(vocab_size=17, num_layers=1, embed_dim=16,
                       num_heads=2, max_seq=8)
    prompt = jnp.zeros((1, 4), jnp.int32)
    params = lm.init(jax.random.PRNGKey(0), prompt)["params"]
    with pytest.raises(ValueError, match="position table"):
        generate(lm, params, prompt, 100, decode_max_len=200)


def test_decode_rejects_noncausal_and_active_dropout():
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

    x = jnp.zeros((1, 1, 16))
    m = SelfMultiheadAttn(embed_dim=16, num_heads=2, decode=True,
                          decode_max_len=8, causal=False)
    with pytest.raises(NotImplementedError):
        m.init(jax.random.PRNGKey(0), x)
    m2 = SelfMultiheadAttn(embed_dim=16, num_heads=2, decode=True,
                           decode_max_len=8, causal=True, dropout=0.3)
    with pytest.raises(NotImplementedError):
        m2.init(jax.random.PRNGKey(0), x, deterministic=False,
                dropout_rng=jax.random.PRNGKey(1))


def test_generate_eos_pads_finished_sequences():
    """Once a sequence emits eos_token_id, all its later positions are
    pad_token_id (static-shape early stop)."""
    import numpy as np
    from apex_tpu.models import TransformerLM
    from apex_tpu.models.gpt import generate

    lm = TransformerLM(vocab_size=23, num_layers=1, embed_dim=16,
                       num_heads=2, max_seq=20)
    prompt = jax.random.randint(jax.random.PRNGKey(15), (3, 4), 0, 23)
    params = lm.init(jax.random.PRNGKey(16), prompt)["params"]
    greedy = np.asarray(generate(lm, params, prompt, 12))
    # pick the token the first sequence greedily emits at step 2 as EOS
    eos = int(greedy[0, 4 + 2])
    out = np.asarray(generate(lm, params, prompt, 12, eos_token_id=eos,
                              pad_token_id=22))
    for row in out:
        gen = row[4:]
        hits = np.where(gen == eos)[0]
        if len(hits):
            assert (gen[hits[0] + 1:] == 22).all()
    # the first sequence definitely hit EOS at step 2
    assert (out[0, 4 + 3:] == 22).all()


def test_generate_validates_sampling_args():
    """top_k/top_p with temperature<=0 raise (the greedy branch would
    silently ignore them), and max_new_tokens must be >= 1 (ADVICE r4:
    0 died with an opaque IndexError)."""
    from apex_tpu.models import TransformerLM
    from apex_tpu.models.gpt import generate

    lm = TransformerLM(vocab_size=17, num_layers=1, embed_dim=16,
                       num_heads=2, max_seq=12)
    prompt = jnp.zeros((1, 4), jnp.int32)
    params = lm.init(jax.random.PRNGKey(0), prompt)["params"]
    with pytest.raises(ValueError, match="temperature"):
        generate(lm, params, prompt, 4, top_k=5)
    with pytest.raises(ValueError, match="temperature"):
        generate(lm, params, prompt, 4, top_p=0.9)
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(lm, params, prompt, 0)


@pytest.mark.parametrize("kind", ["relative_bias", "alibi",
                                  "alibi_learned"])
def test_decode_logits_match_full_forward_with_position_bias(kind):
    """VERDICT r4 missing #1: a model built with the trainable-bias
    feature (T5 rel-bias / ALiBi) decodes through the KV cache —
    prefill + 1-token steps reproduce the full forward's logits at
    every position (the bias columns are sliced at the cache index)."""
    import numpy as np
    from apex_tpu.models import TransformerLM

    kw = {"relative_bias": True} if kind == "relative_bias" else \
        {"alibi": True, "alibi_learned": kind == "alibi_learned"}
    lm = TransformerLM(vocab_size=97, num_layers=2, embed_dim=32,
                       num_heads=4, max_seq=24, **kw)
    toks = jax.random.randint(jax.random.PRNGKey(20), (2, 12), 0, 97)
    params = lm.init(jax.random.PRNGKey(21), toks)["params"]
    # position info lives in the attention bias: no absolute table
    assert "pos_emb" not in params
    if kind == "relative_bias":
        assert "rel_bias" in params["block_0"]["attn"]
    if kind == "alibi_learned":
        assert "alibi_slopes" in params["block_0"]["attn"]
    want = lm.apply({"params": params}, toks)

    dec = lm.clone(decode=True, decode_max_len=24)
    lg_pre, vs = dec.apply({"params": params}, toks[:, :8],
                           mutable=["cache"])
    np.testing.assert_allclose(np.asarray(lg_pre),
                               np.asarray(want[:, :8]),
                               rtol=2e-4, atol=2e-4)
    cache = vs["cache"]
    for i in range(8, 12):
        lg, vs = dec.apply({"params": params, "cache": cache},
                           toks[:, i:i + 1], pos_offset=i,
                           mutable=["cache"])
        cache = vs["cache"]
        np.testing.assert_allclose(
            np.asarray(lg[:, 0]), np.asarray(want[:, i]),
            rtol=2e-4, atol=2e-4, err_msg=f"{kind} position {i}")


def test_generate_extrapolates_past_max_seq_without_pos_table():
    """Bias-positioned models (ALiBi/rel-bias, no absolute table) may
    generate past max_seq — length extrapolation is their advertised
    capability; only decode_max_len caps them. Models WITH the table
    still get the loud error."""
    from apex_tpu.models import TransformerLM
    from apex_tpu.models.gpt import generate

    lm = TransformerLM(vocab_size=19, num_layers=1, embed_dim=16,
                       num_heads=2, max_seq=8, alibi=True)
    prompt = jnp.zeros((1, 4), jnp.int32)
    params = lm.init(jax.random.PRNGKey(0), prompt)["params"]
    out = generate(lm, params, prompt, 12, decode_max_len=16)
    assert out.shape == (1, 16)

    lm_abs = TransformerLM(vocab_size=19, num_layers=1, embed_dim=16,
                           num_heads=2, max_seq=8)
    params_abs = lm_abs.init(jax.random.PRNGKey(1), prompt)["params"]
    with pytest.raises(ValueError, match="position table"):
        generate(lm_abs, params_abs, prompt, 12, decode_max_len=16)


def test_alibi_learned_requires_alibi():
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

    m = SelfMultiheadAttn(embed_dim=16, num_heads=2, causal=True,
                          alibi_learned=True)
    with pytest.raises(ValueError, match="alibi_learned"):
        m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 16)))


def test_fused_decode_impl_matches_einsum():
    """decode_impl='fused' (single Pallas step-attention call, 128-row
    rounded cache) reproduces the einsum path's generate() output
    exactly at the logits level — fresh prefill rides flash in both."""
    import numpy as np
    from apex_tpu.models import TransformerLM
    from apex_tpu.models.gpt import generate

    lm = TransformerLM(vocab_size=71, num_layers=2, embed_dim=32,
                       num_heads=4, max_seq=20)
    prompt = jax.random.randint(jax.random.PRNGKey(40), (2, 6), 0, 71)
    params = lm.init(jax.random.PRNGKey(41), prompt)["params"]

    want = generate(lm, params, prompt, 8)
    got = generate(lm.clone(decode_impl="fused"), params, prompt, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    # logits-level parity too (argmax agreement can mask drift)
    dec_e = lm.clone(decode=True, decode_max_len=20)
    dec_f = lm.clone(decode=True, decode_max_len=20,
                     decode_impl="fused")
    lg_e, vs_e = dec_e.apply({"params": params}, prompt,
                             mutable=["cache"])
    lg_f, vs_f = dec_f.apply({"params": params}, prompt,
                             mutable=["cache"])
    np.testing.assert_allclose(np.asarray(lg_f), np.asarray(lg_e),
                               rtol=2e-4, atol=2e-4)
    step = jnp.full((2, 1), 3, prompt.dtype)
    se, _ = dec_e.apply({"params": params, "cache": vs_e["cache"]},
                        step, pos_offset=6, mutable=["cache"])
    sf, _ = dec_f.apply({"params": params, "cache": vs_f["cache"]},
                        step, pos_offset=6, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(sf), np.asarray(se),
                               rtol=2e-4, atol=2e-4)


def test_decode_impl_auto_picks_by_cache_length():
    """'auto' resolves to einsum below 2048 cache rows (cache stays at
    decode_max_len) and fused at >= 2048 (cache rounds up to the
    128-row block grid) — pinned via the cache shapes it allocates."""
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

    x = jnp.zeros((1, 1, 16))
    short = SelfMultiheadAttn(embed_dim=16, num_heads=2, causal=True,
                              decode=True, decode_max_len=641)
    vs = short.init(jax.random.PRNGKey(0), x)
    assert vs["cache"]["cached_key"].shape[2] == 641   # einsum: as-is
    # fused: rounds to a 512-multiple (divisor-friendly block grid —
    # a bare 128-multiple like 2176=128*17 would force the kernel onto
    # the measured-worst 128-row blocks)
    long = SelfMultiheadAttn(embed_dim=16, num_heads=2, causal=True,
                             decode=True, decode_max_len=2050)
    vs = long.init(jax.random.PRNGKey(0), x)
    assert vs["cache"]["cached_key"].shape[2] == 2560
    # non-native head dim (48): fused would re-pay the pad copy every
    # step, so auto/fused demote to einsum (cache stays as-is)
    odd = SelfMultiheadAttn(embed_dim=96, num_heads=2, causal=True,
                            decode=True, decode_max_len=2050,
                            decode_impl="fused")
    vs = odd.init(jax.random.PRNGKey(0), jnp.zeros((1, 1, 96)))
    assert vs["cache"]["cached_key"].shape[2] == 2050
    with pytest.raises(ValueError, match="decode_impl"):
        SelfMultiheadAttn(embed_dim=16, num_heads=2, causal=True,
                          decode=True, decode_max_len=8,
                          decode_impl="nope").init(
            jax.random.PRNGKey(0), x)


def test_moe_decode_logits_match_full_forward():
    """VERDICT r4 weak #5: generate()'s decode path on an MoE model.
    Prefill + 1-token steps must reproduce the full forward's logits —
    the capacity computation runs per CALL (b·s tokens at prefill, b at
    a step), so capacity_factor is set high enough that neither path
    drops tokens (cf >= experts/selected guarantees worst-case room;
    with drops the two paths would legitimately diverge)."""
    import numpy as np
    from apex_tpu.models import TransformerLM

    lm = TransformerLM(vocab_size=89, num_layers=2, embed_dim=32,
                       num_heads=4, max_seq=24, moe_num_experts=4,
                       moe_every=1, moe_capacity_factor=2.0)
    toks = jax.random.randint(jax.random.PRNGKey(30), (2, 12), 0, 89)
    params = lm.init(jax.random.PRNGKey(31), toks)["params"]
    want = lm.apply({"params": params}, toks)

    dec = lm.clone(decode=True, decode_max_len=24)
    lg_pre, vs = dec.apply({"params": params}, toks[:, :8],
                           mutable=["cache"])
    np.testing.assert_allclose(np.asarray(lg_pre),
                               np.asarray(want[:, :8]),
                               rtol=2e-4, atol=2e-4)
    cache = vs["cache"]
    for i in range(8, 12):
        lg, vs = dec.apply({"params": params, "cache": cache},
                           toks[:, i:i + 1], pos_offset=i,
                           mutable=["cache"])
        cache = vs["cache"]
        np.testing.assert_allclose(
            np.asarray(lg[:, 0]), np.asarray(want[:, i]),
            rtol=2e-4, atol=2e-4, err_msg=f"position {i}")


def test_moe_generate_end_to_end():
    """generate() drives an MoE model through prefill + scanned steps
    (greedy and sampled): shapes, determinism, and agreement with the
    naive re-forward loop."""
    import numpy as np
    from apex_tpu.models import TransformerLM
    from apex_tpu.models.gpt import generate

    lm = TransformerLM(vocab_size=53, num_layers=2, embed_dim=32,
                       num_heads=4, max_seq=20, moe_num_experts=4,
                       moe_capacity_factor=2.0)
    prompt = jax.random.randint(jax.random.PRNGKey(32), (2, 6), 0, 53)
    params = lm.init(jax.random.PRNGKey(33), prompt)["params"]

    seq = prompt
    for _ in range(6):
        lg = lm.apply({"params": params}, seq)
        seq = jnp.concatenate(
            [seq, jnp.argmax(lg[:, -1], axis=-1)[:, None].astype(
                seq.dtype)], axis=1)
    got = generate(lm, params, prompt, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(seq))

    a = generate(lm, params, prompt, 6, temperature=0.9,
                 rng=jax.random.PRNGKey(34), top_p=0.9)
    b = generate(lm, params, prompt, 6, temperature=0.9,
                 rng=jax.random.PRNGKey(34), top_p=0.9)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (2, 12)


def test_generate_greedy_matches_reforward_relative_bias():
    """generate() on a rel-bias model == the naive re-forward loop."""
    import numpy as np
    from apex_tpu.models import TransformerLM
    from apex_tpu.models.gpt import generate

    lm = TransformerLM(vocab_size=61, num_layers=2, embed_dim=32,
                       num_heads=4, max_seq=20, relative_bias=True)
    prompt = jax.random.randint(jax.random.PRNGKey(22), (2, 6), 0, 61)
    params = lm.init(jax.random.PRNGKey(23), prompt)["params"]

    seq = prompt
    for _ in range(8):
        lg = lm.apply({"params": params}, seq)
        seq = jnp.concatenate(
            [seq, jnp.argmax(lg[:, -1], axis=-1)[:, None].astype(
                seq.dtype)], axis=1)

    got = generate(lm, params, prompt, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(seq))


def test_position_bias_lm_trains():
    """One FusedAdam step on a rel-bias/ALiBi LM moves the bias params
    (the end-to-end trainability the module tests can't prove)."""
    from apex_tpu import amp, optimizers
    from apex_tpu.models import TransformerLM
    from apex_tpu.models.gpt import next_token_loss

    lm = TransformerLM(vocab_size=67, num_layers=2, embed_dim=32,
                       num_heads=4, max_seq=32, relative_bias=True,
                       alibi=True, alibi_learned=True)
    toks = jax.random.randint(jax.random.PRNGKey(24), (2, 16), 0, 67)
    params = lm.init(jax.random.PRNGKey(25), toks)["params"]
    _, aopt = amp.initialize(None, optimizers.FusedAdam(lr=1e-2),
                             opt_level="O0", verbosity=0)
    st = aopt.init(params)

    def loss(p):
        return next_token_loss(lm.apply({"params": p}, toks), toks)

    grads = jax.grad(loss)(params)
    table_g = grads["block_0"]["attn"]["rel_bias"]["rel_bias"]
    slopes_g = grads["block_0"]["attn"]["alibi_slopes"]
    assert float(jnp.max(jnp.abs(table_g))) > 0
    assert float(jnp.max(jnp.abs(slopes_g))) > 0
    new_params, _, _ = aopt.step(grads, params, st)
    moved = new_params["block_0"]["attn"]["rel_bias"]["rel_bias"] \
        - params["block_0"]["attn"]["rel_bias"]["rel_bias"]
    assert float(jnp.max(jnp.abs(moved))) > 0
