"""Fused optimizer parity tests vs torch.optim references — port of the
reference's tests/L0/run_optimizers/ (test_adam.py:181, test_lamb.py:263,
test_adagrad.py:131): random param sets, several steps, assert trajectories
match the framework-independent reference implementation."""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from apex_tpu import optimizers as opt


def rand_tree(key, shapes):
    ks = jax.random.split(key, len(shapes))
    return {f"p{i}": jax.random.normal(k, s, jnp.float32)
            for i, (k, s) in enumerate(zip(ks, shapes))}


SHAPES = [(73,), (13, 64), (4, 3, 9)]
NSTEPS = 5


def run_jax(optimizer, params, grads_per_step):
    state = optimizer.init(params)
    for g in grads_per_step:
        params, state = optimizer.step(g, params, state)
    return params


def run_torch(torch_opt_ctor, params, grads_per_step):
    tparams = [torch.nn.Parameter(torch.tensor(np.asarray(v)))
               for v in params.values()]
    topt = torch_opt_ctor(tparams)
    for g in grads_per_step:
        for tp, gv in zip(tparams, g.values()):
            tp.grad = torch.tensor(np.asarray(gv))
        topt.step()
    return {k: tp.detach().numpy() for k, tp in zip(params, tparams)}


def make_grads(key, params, n):
    out = []
    for i in range(n):
        key, k = jax.random.split(key)
        ks = jax.random.split(k, len(params))
        out.append({name: jax.random.normal(kk, v.shape, jnp.float32)
                    for kk, (name, v) in zip(ks, params.items())})
    return out


@pytest.mark.parametrize("adam_w,wd", [(True, 0.0), (True, 0.1),
                                       (False, 0.0), (False, 0.1)])
def test_fused_adam_vs_torch(adam_w, wd):
    params = rand_tree(jax.random.PRNGKey(0), SHAPES)
    grads = make_grads(jax.random.PRNGKey(1), params, NSTEPS)
    got = run_jax(opt.FusedAdam(lr=1e-2, weight_decay=wd, adam_w_mode=adam_w),
                  params, grads)
    ctor = ((lambda ps: torch.optim.AdamW(ps, lr=1e-2, weight_decay=wd))
            if adam_w else
            (lambda ps: torch.optim.Adam(ps, lr=1e-2, weight_decay=wd)))
    want = run_torch(ctor, params, grads)
    for k in params:
        np.testing.assert_allclose(np.asarray(got[k]), want[k],
                                   rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("momentum,nesterov,wd",
                         [(0.0, False, 0.0), (0.9, False, 0.0),
                          (0.9, True, 0.0), (0.9, False, 0.05)])
def test_fused_sgd_vs_torch(momentum, nesterov, wd):
    params = rand_tree(jax.random.PRNGKey(2), SHAPES)
    grads = make_grads(jax.random.PRNGKey(3), params, NSTEPS)
    got = run_jax(opt.FusedSGD(lr=0.05, momentum=momentum, nesterov=nesterov,
                               weight_decay=wd), params, grads)
    want = run_torch(
        lambda ps: torch.optim.SGD(ps, lr=0.05, momentum=momentum,
                                   nesterov=nesterov, weight_decay=wd),
        params, grads)
    for k in params:
        np.testing.assert_allclose(np.asarray(got[k]), want[k],
                                   rtol=2e-5, atol=2e-6)


def test_fused_sgd_dampening_first_step():
    # torch lazy momentum init: buf_1 = g_1 exactly (not (1-dampening)*g_1).
    params = rand_tree(jax.random.PRNGKey(4), [(32,)])
    grads = make_grads(jax.random.PRNGKey(5), params, 3)
    got = run_jax(opt.FusedSGD(lr=0.1, momentum=0.9, dampening=0.5),
                  params, grads)
    want = run_torch(
        lambda ps: torch.optim.SGD(ps, lr=0.1, momentum=0.9, dampening=0.5),
        params, grads)
    np.testing.assert_allclose(np.asarray(got["p0"]), want["p0"],
                               rtol=2e-5, atol=2e-6)


def test_fused_adagrad_vs_torch():
    params = rand_tree(jax.random.PRNGKey(6), SHAPES)
    grads = make_grads(jax.random.PRNGKey(7), params, NSTEPS)
    got = run_jax(opt.FusedAdagrad(lr=0.05, eps=1e-10, weight_decay=0.1),
                  params, grads)
    want = run_torch(
        lambda ps: torch.optim.Adagrad(ps, lr=0.05, eps=1e-10,
                                       weight_decay=0.1),
        params, grads)
    for k in params:
        np.testing.assert_allclose(np.asarray(got[k]), want[k],
                                   rtol=2e-5, atol=2e-6)


def _reference_lamb_step(params, grads, m, v, step, *, lr, beta1, beta2, eps,
                         wd, max_grad_norm, grad_averaging=True,
                         use_nvlamb=False):
    """Pure-numpy LAMB (the reference test ships its own python LAMB,
    tests/L0/run_optimizers/test_lamb.py)."""
    gnorm = np.sqrt(sum(float(np.sum(g * g)) for g in grads.values()))
    clip = gnorm / max_grad_norm if (max_grad_norm > 0 and
                                     gnorm > max_grad_norm) else 1.0
    bc1 = 1 - beta1 ** step
    bc2 = 1 - beta2 ** step
    beta3 = (1 - beta1) if grad_averaging else 1.0
    out = {}
    for k in params:
        g = grads[k] / clip
        p = params[k]
        m[k] = beta1 * m[k] + beta3 * g
        v[k] = beta2 * v[k] + (1 - beta2) * g * g
        upd = (m[k] / bc1) / (np.sqrt(v[k] / bc2) + eps) + wd * p
        pn = np.linalg.norm(p)
        un = np.linalg.norm(upd)
        ratio = pn / un if (wd != 0 or use_nvlamb) and pn > 0 and un > 0 \
            else 1.0
        out[k] = p - lr * ratio * upd
    return out


def test_fused_lamb_vs_python_reference():
    params = rand_tree(jax.random.PRNGKey(8), SHAPES)
    grads = make_grads(jax.random.PRNGKey(9), params, NSTEPS)
    lamb = opt.FusedLAMB(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
    state = lamb.init(params)
    p_jax = params
    for g in grads:
        p_jax, state = lamb.step(g, p_jax, state)

    p_np = {k: np.asarray(v).copy() for k, v in params.items()}
    m = {k: np.zeros_like(v) for k, v in p_np.items()}
    v2 = {k: np.zeros_like(vv) for k, vv in p_np.items()}
    for i, g in enumerate(grads):
        gn = {k: np.asarray(vv) for k, vv in g.items()}
        p_np = _reference_lamb_step(p_np, gn, m, v2, i + 1, lr=1e-2,
                                    beta1=0.9, beta2=0.999, eps=1e-6,
                                    wd=0.01, max_grad_norm=1.0)
    for k in params:
        np.testing.assert_allclose(np.asarray(p_jax[k]), p_np[k],
                                   rtol=3e-5, atol=3e-6)


def test_fused_novograd_runs_and_converges():
    # quadratic bowl: params should shrink toward 0
    params = {"w": jnp.full((64,), 5.0)}
    ng = opt.FusedNovoGrad(lr=0.5, weight_decay=0.0)
    state = ng.init(params)
    for _ in range(200):
        grads = {"w": params["w"]}  # d/dw 0.5 w^2
        params, state = ng.step(grads, params, state)
    assert float(jnp.abs(params["w"]).max()) < 1.0


def test_optimizer_step_is_jittable():
    params = rand_tree(jax.random.PRNGKey(10), [(128,), (16, 8)])
    adam = opt.FusedAdam(lr=1e-3)
    state = adam.init(params)
    grads = make_grads(jax.random.PRNGKey(11), params, 1)[0]

    @jax.jit
    def f(g, p, s):
        return adam.step(g, p, s)

    p1, s1 = f(grads, params, state)
    p2, s2 = adam.step(grads, params, state)
    for k in params:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   rtol=1e-6)


def test_lr_schedule_callable():
    params = {"w": jnp.ones((8,))}
    sched = lambda step: 0.1 / step.astype(jnp.float32)
    sgd = opt.FusedSGD(lr=sched)
    state = sgd.init(params)
    g = {"w": jnp.ones((8,))}
    p1, state = sgd.step(g, params, state)     # lr = 0.1
    np.testing.assert_allclose(np.asarray(p1["w"]), 0.9, rtol=1e-6)
    p2, state = sgd.step(g, p1, state)         # lr = 0.05
    np.testing.assert_allclose(np.asarray(p2["w"]), 0.85, rtol=1e-6)


def test_as_optax():
    import optax
    params = {"w": jnp.ones((16,))}
    tx = opt.FusedAdam(lr=1e-2).as_optax()
    state = tx.init(params)
    g = {"w": jnp.full((16,), 0.5)}
    updates, state = tx.update(g, state, params)
    new_params = optax.apply_updates(params, updates)
    assert float(new_params["w"][0]) < 1.0


# --- r3: persistent-bucket mode ------------------------------------------


class TestBucketedOptimizer:
    def _params(self):
        k = jax.random.split(jax.random.PRNGKey(30), 4)
        return {"w1": jax.random.normal(k[0], (37, 11)),
                "w2": jax.random.normal(k[1], (501,)),
                "b": jax.random.normal(k[2], (3,)),
                "h": jax.random.normal(k[3], (64, 8), jnp.bfloat16)}

    @pytest.mark.parametrize("mk", [
        lambda: opt.FusedAdam(lr=1e-2, weight_decay=0.01),
        lambda: opt.FusedSGD(lr=0.1, momentum=0.9,
                                    weight_decay=1e-4),
        lambda: opt.FusedAdagrad(lr=1e-2, weight_decay=1e-4),
    ])
    def test_matches_tree_mode(self, mk):
        """Bucketed trajectory == tree trajectory exactly: elementwise
        updates commute with concatenation (VERDICT r3 #4)."""
        from apex_tpu.optimizers import BucketedOptimizer  # noqa
        params = self._params()
        tree_opt, bopt = mk(), BucketedOptimizer(mk())
        ts = tree_opt.init(params)
        pb, bs = bopt.init(params)
        p_tree = params
        for i in range(4):
            g = jax.tree_util.tree_map(
                lambda p: (jnp.sin(p.astype(jnp.float32) * (i + 1))
                           .astype(p.dtype)), p_tree)
            p_tree, ts = tree_opt.step(g, p_tree, ts)
            pb, bs = bopt.step(bopt.flatten(g), pb, bs)
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_array_equal(
                    np.asarray(a, np.float32), np.asarray(b, np.float32)),
                p_tree, bopt.unflatten(pb))

    def test_rejects_per_tensor_optimizers_and_groups(self):
        from apex_tpu.optimizers import BucketedOptimizer  # noqa
        with pytest.raises(ValueError, match="per-tensor"):
            BucketedOptimizer(opt.FusedLAMB(lr=1e-3))
        with pytest.raises(ValueError, match="per-tensor"):
            BucketedOptimizer(opt.FusedNovoGrad(lr=1e-3))
        with pytest.raises(ValueError, match="param groups"):
            BucketedOptimizer(opt.FusedAdam(
                lr=1e-3, param_groups=[{"filter": "b", "lr": 1.0}]))

    def test_layout_change_rejected(self):
        from apex_tpu.optimizers import BucketedOptimizer  # noqa
        bopt = BucketedOptimizer(opt.FusedAdam(lr=1e-3))
        bopt.init(self._params())
        with pytest.raises(ValueError, match="layout is static"):
            bopt.flatten({"other": jnp.ones((4,))})
