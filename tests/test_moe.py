"""Expert parallelism (MoE) tests — parallel/expert_parallel.py.

Tiers (mirrors test_tensor_parallel.py):
  1. routing unit behavior (capacity, priorities, gate weights)
  2. single-device MoE semantics (identical-experts == dense MLP)
  3. EP-sharded vs single-device parity under shard_map + all_to_all
  4. gradient sync contract (expert grads complete, replicated psum'd)
  5. TransformerLM integration (aux losses, remat, training step)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.parallel.expert_parallel import (
    MoEMLP, lm_moe_pspecs, moe_aux_total, moe_sync_grads, top_k_routing)

# Integration tier (PR 1): this whole module rides `-m slow` — expert-parallel integration numerics.
# Tier-1 (-m 'not slow') must fit the 870 s gate budget; the fast cross-
# sections of this stack stay in tier-1 via test_zero/test_parallel/
# test_param_groups/test_attention and the ci/gate.sh dryrun parts.
pytestmark = pytest.mark.slow


# ---------------------------------------------------------------------------
# 1. routing
# ---------------------------------------------------------------------------

def test_top1_routing_dispatches_to_argmax():
    probs = jnp.asarray([[0.7, 0.2, 0.1],
                         [0.1, 0.8, 0.1],
                         [0.6, 0.3, 0.1]], jnp.float32)
    dispatch, combine, frac = top_k_routing(probs, k=1, capacity=4)
    # token 0 -> expert 0 slot 0, token 1 -> expert 1 slot 0,
    # token 2 -> expert 0 slot 1
    assert dispatch[0, 0, 0] == 1 and dispatch[1, 1, 0] == 1
    assert dispatch[2, 0, 1] == 1
    assert float(jnp.sum(dispatch)) == 3
    # Switch: top-1 combine weight is the raw probability
    np.testing.assert_allclose(np.asarray(combine[0, 0, 0]), 0.7, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(frac), [2 / 3, 1 / 3, 0],
                               rtol=1e-6)


def test_capacity_drops_overflow_tokens():
    # all four tokens pick expert 0; capacity 8 min -> force via tiny k
    probs = jnp.tile(jnp.asarray([[0.9, 0.1]], jnp.float32), (4, 1))
    dispatch, combine, _ = top_k_routing(probs, k=1, capacity=2)
    kept = jnp.sum(dispatch, axis=(1, 2))
    np.testing.assert_array_equal(np.asarray(kept), [1, 1, 0, 0])
    assert float(jnp.sum(combine[2:])) == 0.0


def test_top2_gates_renormalize():
    probs = jnp.asarray([[0.5, 0.3, 0.2]], jnp.float32)
    dispatch, combine, _ = top_k_routing(probs, k=2, capacity=4)
    assert float(jnp.sum(dispatch)) == 2
    w0 = float(combine[0, 0, 0])
    w1 = float(combine[0, 1, 0])
    np.testing.assert_allclose(w0, 0.5 / 0.8, rtol=1e-5)
    np.testing.assert_allclose(w1, 0.3 / 0.8, rtol=1e-5)


def test_second_choices_fill_after_first_choices():
    # token 0 first-choice expert 0; token 1 second-choice expert 0:
    # token 1's slot comes after ALL first choices (GShard priority)
    probs = jnp.asarray([[0.9, 0.1, 0.0],
                         [0.2, 0.75, 0.05]], jnp.float32)
    dispatch, _, _ = top_k_routing(probs, k=2, capacity=4)
    assert dispatch[0, 0, 0] == 1          # first choice, slot 0
    assert dispatch[1, 0, 1] == 1          # second choice, after it


# ---------------------------------------------------------------------------
# 2. single-device semantics
# ---------------------------------------------------------------------------

def _identical_experts(params):
    """Copy expert 0's weights into every expert slot."""
    p = jax.tree_util.tree_map(lambda x: x, params)
    for k in ("wi", "bi", "wo", "bo"):
        arr = p[k]
        p[k] = jnp.broadcast_to(arr[:1], arr.shape)
    return p


def test_identical_experts_match_dense_mlp():
    """With every expert holding the same weights and no capacity drops,
    top-2 combine weights sum to 1 per token, so MoE(x) == MLP(x)."""
    key = jax.random.PRNGKey(0)
    m, e = 16, 4
    x = jax.random.normal(key, (2, 8, m), jnp.float32)
    moe = MoEMLP(embed_dim=m, num_experts=e, mlp_ratio=2,
                 num_selected=2, capacity_factor=float(e))
    params = moe.init(key, x)["params"]
    params = _identical_experts(params)
    y, _ = moe.apply({"params": params}, x, mutable=["intermediates"])

    wi, bi = params["wi"][0], params["bi"][0]
    wo, bo = params["wo"][0], params["bo"][0]
    ref = jax.nn.gelu(x @ wi + bi) @ wo + bo
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_aux_loss_balanced_router_is_one():
    """A uniform router (zero weights -> uniform probs) with evenly
    spread argmax ties... instead: hand-build probs where each expert
    gets exactly 1/E of the tokens with uniform mean prob -> aux == 1."""
    e = 4
    probs = jnp.eye(e, dtype=jnp.float32) * 0.6 + 0.1  # rows sum to 1
    dispatch, _, frac = top_k_routing(probs, k=1, capacity=8)
    aux = e * jnp.sum(frac * jnp.mean(probs, axis=0))
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)


def test_router_gradient_flows():
    key = jax.random.PRNGKey(1)
    m, e = 8, 4
    x = jax.random.normal(key, (1, 16, m), jnp.float32)
    moe = MoEMLP(embed_dim=m, num_experts=e, mlp_ratio=2,
                 num_selected=2, capacity_factor=2.0)
    params = moe.init(key, x)["params"]

    def loss(p):
        y, _ = moe.apply({"params": p}, x, mutable=["intermediates"])
        return jnp.sum(y * y)

    g = jax.grad(loss)(params)
    assert float(jnp.max(jnp.abs(g["router"]))) > 0
    assert float(jnp.max(jnp.abs(g["wi"]))) > 0
    assert float(jnp.max(jnp.abs(g["wo"]))) > 0


# ---------------------------------------------------------------------------
# 3. EP-sharded parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("ep", [2, 4])
def test_expert_parallel_matches_single_device(ep):
    """shard_map over an 'expert' axis (tokens batch-sharded, experts
    leading-dim-sharded, two all_to_alls) reproduces the single-device
    forward exactly when capacity admits every token."""
    key = jax.random.PRNGKey(2)
    m, e, b, s = 16, 4, ep * 2, 8
    x = jax.random.normal(key, (b, s, m), jnp.float32)
    dense = MoEMLP(embed_dim=m, num_experts=e, mlp_ratio=2,
                   num_selected=2, capacity_factor=float(e))
    params = dense.init(key, x)["params"]
    y_ref, _ = dense.apply({"params": params}, x,
                           mutable=["intermediates"])

    local = MoEMLP(embed_dim=m, num_experts=e, mlp_ratio=2,
                   num_selected=2, capacity_factor=float(e),
                   axis_name="expert", expert_parallel_size=ep)
    mesh = Mesh(np.asarray(jax.devices()[:ep]), ("expert",))
    specs = lm_moe_pspecs(params, axis="expert")

    def fwd(p, xx):
        y, _ = local.apply({"params": p}, xx, mutable=["intermediates"])
        return y

    y_ep = jax.jit(shard_map(
        fwd, mesh=mesh, in_specs=(specs, P("expert")),
        out_specs=P("expert"), check_vma=False))(
        jax.device_put(params, jax.tree_util.tree_map(
            lambda sp: NamedSharding(mesh, sp), specs)),
        jax.device_put(x, NamedSharding(mesh, P("expert"))))
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                               rtol=2e-4, atol=2e-5)


def test_expert_grads_complete_without_psum():
    """EP grad contract: differentiating the per-device shard function
    yields expert-kernel grads that already equal the single-device
    grads (the backward all_to_all accumulates them); replicated params
    need the explicit psum that moe_sync_grads applies."""
    key = jax.random.PRNGKey(3)
    ep, m, e, b, s = 4, 8, 4, 8, 4
    x = jax.random.normal(key, (b, s, m), jnp.float32)
    dense = MoEMLP(embed_dim=m, num_experts=e, mlp_ratio=2,
                   num_selected=2, capacity_factor=float(e))
    params = dense.init(key, x)["params"]

    def dense_loss(p):
        y, _ = dense.apply({"params": p}, x, mutable=["intermediates"])
        return jnp.sum(y * y)

    g_ref = jax.grad(dense_loss)(params)

    local = dense.clone(axis_name="expert", expert_parallel_size=ep)
    mesh = Mesh(np.asarray(jax.devices()[:ep]), ("expert",))
    specs = lm_moe_pspecs(params, axis="expert")

    def shard_grads(p, xx):
        def loss(pp):
            y, _ = local.apply({"params": pp}, xx,
                               mutable=["intermediates"])
            return jnp.sum(y * y)
        g = jax.grad(loss)(p)
        return moe_sync_grads(g, specs, "expert")

    g_ep = jax.jit(shard_map(
        shard_grads, mesh=mesh, in_specs=(specs, P("expert")),
        out_specs=specs, check_vma=False))(
        jax.device_put(params, jax.tree_util.tree_map(
            lambda sp: NamedSharding(mesh, sp), specs)),
        jax.device_put(x, NamedSharding(mesh, P("expert"))))
    for k in ("router", "wi", "bi", "wo", "bo"):
        np.testing.assert_allclose(
            np.asarray(g_ep[k]), np.asarray(g_ref[k]),
            rtol=5e-4, atol=1e-5, err_msg=k)


def test_ep_aux_objective_grads_match_manual_shard_mean():
    """The EP aux objective is the mean of per-shard balance terms
    (GShard routing groups). After moe_sync_grads, the router grad must
    equal differentiating that exact objective computed shard-by-shard
    with no mesh — pinning the stop_gradient'd pmean's grad semantics
    (a differentiated bare psum would over-count by the axis size)."""
    key = jax.random.PRNGKey(4)
    ep, m, e, b, s = 4, 8, 4, 8, 4
    x = jax.random.normal(key, (b, s, m), jnp.float32)
    dense = MoEMLP(embed_dim=m, num_experts=e, mlp_ratio=2,
                   num_selected=2, capacity_factor=float(e))
    params = dense.init(key, x)["params"]

    def manual(p):
        auxes = []
        for i in range(ep):
            _, inter = dense.apply({"params": p}, x[i * 2:(i + 1) * 2],
                                   mutable=["intermediates"])
            auxes.append(moe_aux_total(inter["intermediates"]))
        return sum(auxes) / ep

    g_ref = jax.grad(manual)(params)

    local = dense.clone(axis_name="expert", expert_parallel_size=ep)
    mesh = Mesh(np.asarray(jax.devices()[:ep]), ("expert",))
    specs = lm_moe_pspecs(params, axis="expert")

    def shard_grads(p, xx):
        def loss(pp):
            _, inter = local.apply({"params": pp}, xx,
                                   mutable=["intermediates"])
            # sown value is already the shard-mean; grad path is this
            # shard's contribution, scaled to the mean by 1/ep
            return moe_aux_total(inter["intermediates"]) / ep
        return moe_sync_grads(jax.grad(loss)(p), specs, "expert")

    g_ep = jax.jit(shard_map(
        shard_grads, mesh=mesh, in_specs=(specs, P("expert")),
        out_specs=specs, check_vma=False))(
        jax.device_put(params, jax.tree_util.tree_map(
            lambda sp: NamedSharding(mesh, sp), specs)),
        jax.device_put(x, NamedSharding(mesh, P("expert"))))
    np.testing.assert_allclose(np.asarray(g_ep["router"]),
                               np.asarray(g_ref["router"]),
                               rtol=5e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# 4. TransformerLM integration
# ---------------------------------------------------------------------------

def test_lm_moe_blocks_alternate():
    from apex_tpu.models import TransformerLM
    lm = TransformerLM(vocab_size=64, num_layers=4, embed_dim=32,
                       num_heads=4, max_seq=16, moe_num_experts=4)
    toks = jnp.zeros((1, 16), jnp.int32)
    params = lm.init(jax.random.PRNGKey(0), toks)["params"]
    # moe_every=2 default: blocks 1 and 3 sparse, 0 and 2 dense
    assert "moe" in params["block_1"] and "moe" in params["block_3"]
    assert "fc1" in params["block_0"] and "fc1" in params["block_2"]
    assert params["block_1"]["moe"]["wi"].shape == (4, 32, 128)


def test_lm_moe_forward_and_aux_losses():
    from apex_tpu.models import TransformerLM
    from apex_tpu.models.gpt import next_token_loss
    lm = TransformerLM(vocab_size=64, num_layers=2, embed_dim=32,
                       num_heads=4, max_seq=16, moe_num_experts=4)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    params = lm.init(jax.random.PRNGKey(0), toks)["params"]

    def loss_fn(p):
        logits, inter = lm.apply({"params": p}, toks,
                                 mutable=["intermediates"])
        return (next_token_loss(logits, toks)
                + moe_aux_total(inter["intermediates"]))

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    g_router = grads["block_1"]["moe"]["router"]
    assert float(jnp.max(jnp.abs(g_router))) > 0


def test_num_selected_must_not_exceed_experts():
    moe = MoEMLP(embed_dim=8, num_experts=1, num_selected=2)
    x = jnp.zeros((1, 4, 8), jnp.float32)
    with pytest.raises(ValueError, match="num_selected"):
        moe.init(jax.random.PRNGKey(0), x)


def test_same_axis_for_tp_and_ep_rejected():
    from apex_tpu.models import TransformerLM
    lm = TransformerLM(vocab_size=16, num_layers=2, embed_dim=16,
                       num_heads=2, max_seq=8, moe_num_experts=2,
                       tensor_parallel_axis="model",
                       tensor_parallel_size=2,
                       expert_parallel_axis="model",
                       expert_parallel_size=2)
    with pytest.raises(ValueError, match="DIFFERENT mesh axes"):
        lm.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))


def test_hybrid_tp_attention_ep_mlp_matches_dense():
    """TP-sharded attention (model axis) + EP-sharded MoE MLP (expert
    axis) on a 2x2 mesh reproduces the dense single-device forward."""
    from apex_tpu.models import TransformerLM
    from apex_tpu.parallel import lm_tp_pspecs, tp_shard_lm_params

    tp, ep = 2, 2
    heads, e_dim, exp = 4, 32, 4
    dense = TransformerLM(vocab_size=64, num_layers=2, embed_dim=e_dim,
                          num_heads=heads, max_seq=8,
                          moe_num_experts=exp,
                          moe_capacity_factor=float(exp))
    toks = jax.random.randint(jax.random.PRNGKey(5), (ep * 2, 8), 0, 64)
    params = dense.init(jax.random.PRNGKey(6), toks)["params"]
    y_ref = dense.apply({"params": params}, toks)

    params_tp = tp_shard_lm_params(params, tp)
    specs = jax.tree_util.tree_map(
        lambda a, b: a if len(a) else b,
        lm_tp_pspecs(params_tp, axis="model"),
        lm_moe_pspecs(params_tp, axis="expert"))
    local = dense.clone(num_heads=heads // tp,
                        tensor_parallel_axis="model",
                        tensor_parallel_size=tp,
                        expert_parallel_axis="expert",
                        expert_parallel_size=ep)
    mesh = Mesh(np.asarray(jax.devices()[:tp * ep]).reshape(ep, tp),
                ("expert", "model"))

    def fwd(p, t):
        out, _ = local.apply({"params": p}, t,
                             mutable=["intermediates"])
        return out

    y = jax.jit(shard_map(
        fwd, mesh=mesh, in_specs=(specs, P("expert")),
        out_specs=P("expert"), check_vma=False))(
        jax.device_put(params_tp, jax.tree_util.tree_map(
            lambda sp: NamedSharding(mesh, sp), specs)),
        jax.device_put(toks, NamedSharding(mesh, P("expert"))))
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=2e-4, atol=2e-4)


def test_moe_aux_total_zero_for_dense_tree():
    assert float(moe_aux_total({})) == 0.0


def test_lm_moe_under_remat():
    """nn.remat(Block) must thread the sown intermediates through."""
    from apex_tpu.models import TransformerLM
    lm = TransformerLM(vocab_size=64, num_layers=2, embed_dim=32,
                       num_heads=4, max_seq=16, moe_num_experts=2,
                       remat=True)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 64)
    params = lm.init(jax.random.PRNGKey(0), toks)["params"]

    def loss_fn(p):
        logits, inter = lm.apply({"params": p}, toks,
                                 mutable=["intermediates"])
        aux = moe_aux_total(inter["intermediates"])
        return jnp.mean(logits ** 2) + aux

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    assert float(jnp.max(jnp.abs(
        grads["block_1"]["moe"]["wi"]))) > 0


# ---------------------------------------------------------------------------
# 5. EP x ZeRO composition (DeepSpeed-MoE-style expert+data parallelism)
# ---------------------------------------------------------------------------

def _pop_expert_leaves(params):
    """Split a TransformerLM tree into (rest, experts): the expert-stacked
    wi/bi/wo/bo leaves of every MoE block move to a flat dict keyed by
    (block, leaf); the router and everything else stay."""
    rest = {k: (dict(v) if isinstance(v, dict) else v)
            for k, v in params.items()}
    experts = {}
    for bk, sub in rest.items():
        if isinstance(sub, dict) and "moe" in sub:
            moe = dict(sub["moe"])
            for leaf in ("wi", "bi", "wo", "bo"):
                experts[(bk, leaf)] = moe.pop(leaf)
            sub["moe"] = moe
    return rest, experts


def _merge_expert_leaves(rest, experts):
    out = {k: (dict(v) if isinstance(v, dict) else v)
           for k, v in rest.items()}
    for (bk, leaf), val in experts.items():
        moe = dict(out[bk]["moe"])
        moe[leaf] = val
        out[bk] = {**out[bk], "moe": moe}
    return out


def test_ep_zero_composition_matches_dense_adam():
    """(data=2, expert=2) mesh: tokens shard over BOTH axes, experts
    exchange over 'expert', and the optimizer composes DeepSpeed-MoE
    style — ZeRO (DistributedFusedAdam over 'data') for the dense
    params, whose state is replicated waste otherwise, while expert
    params step locally (their state is already distributed by EP).
    One step must match dense FusedAdam on the global objective."""
    from apex_tpu import optimizers
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.models import TransformerLM
    from apex_tpu.models.gpt import next_token_loss

    d_dp = d_ep = 2
    e, heads, s, vocab, exp = 32, 4, 16, 64, 2
    dense = TransformerLM(vocab_size=vocab, num_layers=2, embed_dim=e,
                          num_heads=heads, max_seq=s,
                          moe_num_experts=exp,
                          moe_capacity_factor=float(exp) * 2)
    n_shard = d_dp * d_ep
    toks = jax.random.randint(jax.random.PRNGKey(30), (n_shard, s), 0,
                              vocab)
    params = dense.init(jax.random.PRNGKey(31), toks)["params"]

    # ---- reference: dense FusedAdam on the global mean objective
    def dense_loss(p):
        logits, _ = dense.apply({"params": p}, toks,
                                mutable=["intermediates"])
        return next_token_loss(logits, toks)

    ref_opt = optimizers.FusedAdam(lr=1e-3)
    ref_state = ref_opt.init(params)
    want, _ = ref_opt.step(jax.grad(dense_loss)(params), params,
                           ref_state)

    # ---- EP x ZeRO
    local = dense.clone(expert_parallel_axis="expert",
                        expert_parallel_size=d_ep)
    especs = lm_moe_pspecs(params, axis="expert")
    rest, experts = _pop_expert_leaves(params)
    exp_specs = {k: especs[k[0]]["moe"][k[1]] for k in experts}
    zopt = DistributedFusedAdam(lr=1e-3, axis_name="data",
                                shard_count=d_dp,
                                chunk_elements=2 ** 12)
    eopt = optimizers.FusedAdam(lr=1e-3)
    zstate = zopt.init(rest)
    zspecs = zopt.state_pspec()
    estate = eopt.init(experts)
    est_specs = type(estate)(step=P(), exp_avg=exp_specs,
                             exp_avg_sq=exp_specs)
    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(d_dp, d_ep),
                ("data", "expert"))

    def step(rest_, experts_, zst, est, t):
        def loss_fn(r_, x_):
            p = _merge_expert_leaves(r_, x_)
            logits, _ = local.apply({"params": p}, t,
                                    mutable=["intermediates"])
            # contribution to the global mean over all 4 shards
            return next_token_loss(logits, t) / n_shard

        (g_rest, g_exp) = jax.grad(loss_fn, argnums=(0, 1))(
            rest_, experts_)
        # dense params: sum the expert-axis contributions here; the
        # ZeRO psum_scatter performs the data-axis sum + shard
        g_rest = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, "expert"), g_rest)
        new_rest, new_zst = zopt.step(g_rest, rest_, zst)
        # expert params: backward all_to_all completed the expert-axis
        # accumulation; only the data-axis sum remains, state local
        g_exp = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, "data"), g_exp)
        new_exp, new_est = eopt.step(g_exp, experts_, est)
        return new_rest, new_exp, new_zst, new_est

    rep = jax.tree_util.tree_map(lambda _: P(), rest)
    f = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(rep, exp_specs, zspecs, est_specs,
                  P(("data", "expert"))),
        out_specs=(rep, exp_specs, zspecs, est_specs),
        check_vma=False))
    put = lambda tree, specs: jax.device_put(
        tree, jax.tree_util.tree_map(
            lambda sp: NamedSharding(mesh, sp), specs))
    new_rest, new_exp, _, _ = f(
        rest, put(experts, exp_specs), put(zstate, zspecs), estate,
        jax.device_put(toks, NamedSharding(mesh, P(("data", "expert")))))

    got = _merge_expert_leaves(jax.device_get(new_rest),
                               jax.device_get(new_exp))
    flat_got, _ = jax.tree_util.tree_flatten_with_path(got)
    flat_want, _ = jax.tree_util.tree_flatten_with_path(want)
    assert len(flat_got) == len(flat_want)
    for (pg, gg), (_, gw) in zip(flat_got, flat_want):
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(gw), rtol=5e-4, atol=1e-6,
            err_msg=str(pg))
