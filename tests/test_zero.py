"""ZeRO sharded optimizer tests: the sharded pipeline (psum_scatter -> local
shard step -> all_gather) must produce the SAME trajectory as the dense
single-device fused optimizer — the invariant behind the reference's
DistributedFusedAdam being a drop-in for FusedAdam."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import optimizers, parallel
from apex_tpu.contrib.optimizers import (DistributedFusedAdam,
                                         DistributedFusedLAMB)

NDEV = 8


@pytest.fixture(scope="module")
def mesh():
    return parallel.make_mesh(axis_names=("data",))


def tree_params(key):
    ks = jax.random.split(key, 3)
    # sizes deliberately NOT divisible by 8 to exercise padding
    return {"w1": jax.random.normal(ks[0], (37, 11)),
            "w2": jax.random.normal(ks[1], (501,)),
            "b": jax.random.normal(ks[2], (3,))}


def run_zero(opt, mesh, params, grads_seq):
    state = opt.init(params)
    state_specs = opt.state_pspec()

    def per_device(g, p, s):
        return opt.step(g, p, s)

    step = jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), P(), state_specs),
        out_specs=(P(), state_specs), check_vma=False))

    # place state with its sharding
    state = jax.device_put(
        state, jax.tree_util.tree_map(
            lambda sp: NamedSharding(mesh, sp), state_specs))
    for g in grads_seq:
        params, state = step(g, params, state)
    return params


def make_grads(key, params, n, scale_per_rank=False):
    out = []
    for _ in range(n):
        key, k = jax.random.split(key)
        ks = jax.random.split(k, len(params))
        out.append({name: jax.random.normal(kk, v.shape, jnp.float32)
                    for kk, (name, v) in zip(ks, params.items())})
    return out


def test_zero_adam_matches_dense(mesh):
    params = tree_params(jax.random.PRNGKey(0))
    grads = make_grads(jax.random.PRNGKey(1), params, 4)

    zopt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, axis_name="data",
                                shard_count=NDEV)
    got = run_zero(zopt, mesh, params, grads)

    dense = optimizers.FusedAdam(lr=1e-2, weight_decay=0.01)
    st = dense.init(params)
    want = params
    for g in grads:
        want, st = dense.step(g, want, st)

    for k in params:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=2e-5, atol=2e-6)


def test_zero_lamb_matches_dense(mesh):
    params = tree_params(jax.random.PRNGKey(2))
    grads = make_grads(jax.random.PRNGKey(3), params, 4)

    zopt = DistributedFusedLAMB(lr=1e-2, weight_decay=0.01,
                                max_grad_norm=1.0, axis_name="data",
                                shard_count=NDEV)
    got = run_zero(zopt, mesh, params, grads)

    dense = optimizers.FusedLAMB(lr=1e-2, weight_decay=0.01,
                                 max_grad_norm=1.0)
    st = dense.init(params)
    want = params
    for g in grads:
        want, st = dense.step(g, want, st)

    for k in params:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=3e-5, atol=3e-6)


def test_zero_adam_grad_mean_semantics(mesh):
    # psum_scatter/world must equal the MEAN of per-device grads: feed
    # device-dependent grads and compare against dense with averaged grads.
    params = {"w": jnp.ones((64,))}
    zopt = DistributedFusedAdam(lr=0.1, axis_name="data", shard_count=NDEV)
    state = zopt.init(params)
    state_specs = zopt.state_pspec()

    def per_device(p, s):
        r = jax.lax.axis_index("data").astype(jnp.float32)
        g = {"w": jnp.full((64,), r)}  # mean over ranks = 3.5
        return zopt.step(g, p, s)

    step = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(P(), state_specs),
        out_specs=(P(), state_specs), check_vma=False))
    state = jax.device_put(
        state, jax.tree_util.tree_map(
            lambda sp: NamedSharding(mesh, sp), state_specs))
    got, _ = step(params, state)

    dense = optimizers.FusedAdam(lr=0.1)
    want, _ = dense.step({"w": jnp.full((64,), 3.5)}, params,
                         dense.init(params))
    np.testing.assert_allclose(np.asarray(got["w"]), np.asarray(want["w"]),
                               rtol=1e-5)


def test_zero_state_is_actually_sharded(mesh):
    params = tree_params(jax.random.PRNGKey(4))
    zopt = DistributedFusedAdam(lr=1e-3, axis_name="data", shard_count=NDEV)
    state = zopt.init(params)
    specs = zopt.state_pspec()
    state = jax.device_put(
        state, jax.tree_util.tree_map(
            lambda sp: NamedSharding(mesh, sp), specs))
    # each device holds 1/8 of the flat master
    shard_bytes = state.master.addressable_shards[0].data.nbytes
    assert shard_bytes * NDEV == state.master.nbytes


def test_amp_zero_overflow_skip_under_shard_map(mesh):
    """AmpOptimizer(DistributedFusedAdam) composition: the lax.cond
    overflow-skip wraps a step whose branches contain psum_scatter/all_gather
    collectives under shard_map (VERDICT r1 weak #9). An inf grad must skip
    the step (params + sharded state unchanged, scale halved); a clean grad
    must step."""
    from apex_tpu import amp

    params32 = tree_params(jax.random.PRNGKey(7))
    inner = DistributedFusedAdam(lr=0.1, axis_name="data", shard_count=NDEV)
    _, aopt = amp.initialize(None, inner, opt_level="O5",
                             loss_scale="dynamic", verbosity=0)
    params = amp.cast_model(params32, amp.resolve("O5"))
    st = aopt.init(params)

    zspecs = inner.state_pspec()
    st_specs = type(st)(inner=zspecs, master=P(), scaler=P())

    step = jax.jit(shard_map(
        lambda g, p, s: aopt.step(g, p, s), mesh=mesh,
        in_specs=(P(), P(), st_specs),
        out_specs=(P(), st_specs, P()), check_vma=False))

    st = jax.device_put(st, jax.tree_util.tree_map(
        lambda sp: NamedSharding(mesh, sp), st_specs,
        is_leaf=lambda x: isinstance(x, P)))

    scale0 = float(st.scaler.loss_scale[0])
    bad = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, float("inf"), p.dtype), params)
    p1, st1, info = step(bad, params, st)
    assert bool(info["overflow"])
    assert float(st1.scaler.loss_scale[0]) == scale0 / 2
    for k in params:
        np.testing.assert_array_equal(
            np.asarray(p1[k], np.float32), np.asarray(params[k], np.float32))
    np.testing.assert_array_equal(np.asarray(st1.inner.exp_avg),
                                  np.asarray(st.inner.exp_avg))
    assert int(st1.inner.step) == 0  # skipped step leaves ZeRO state alone

    good = jax.tree_util.tree_map(
        lambda p: jnp.ones(p.shape, p.dtype) * st1.scaler.loss_scale[0],
        params)
    p2, st2, info = step(good, p1, st1)
    assert not bool(info["overflow"])
    assert int(st2.inner.step) == 1
    for k in params:
        assert not np.array_equal(np.asarray(p2[k], np.float32),
                                  np.asarray(p1[k], np.float32))


def test_zero_bf16_allgather(mesh):
    params = {"w": jnp.ones((128,), jnp.bfloat16)}
    zopt = DistributedFusedAdam(lr=0.1, axis_name="data", shard_count=NDEV,
                                allgather_dtype=jnp.bfloat16)
    got = run_zero(zopt, mesh, params,
                   [{"w": jnp.full((128,), 0.5, jnp.bfloat16)}])
    assert got["w"].dtype == jnp.bfloat16
    assert float(got["w"][0]) < 1.0


# --- r3: leaf-grouped (chunked) bucketing -------------------------------


@pytest.mark.parametrize("optname", ["adam", "lamb"])
def test_zero_chunked_matches_dense(mesh, optname):
    """chunk_elements small enough to force multiple buckets must not
    change the trajectory: the bucketed reduce-scatter/all-gather is a
    pure re-chunking of the same math (VERDICT r2 #1)."""
    params = tree_params(jax.random.PRNGKey(20))
    grads = make_grads(jax.random.PRNGKey(21), params, 4)

    if optname == "adam":
        zopt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                    axis_name="data", shard_count=NDEV,
                                    chunk_elements=128)
        dense = optimizers.FusedAdam(lr=1e-2, weight_decay=0.01)
    else:
        zopt = DistributedFusedLAMB(lr=1e-2, weight_decay=0.01,
                                    max_grad_norm=1.0, axis_name="data",
                                    shard_count=NDEV, chunk_elements=128)
        dense = optimizers.FusedLAMB(lr=1e-2, weight_decay=0.01,
                                     max_grad_norm=1.0)
    assert len(zopt._pack(params)["buckets"]) > 1
    got = run_zero(zopt, mesh, params, grads)

    st = dense.init(params)
    want = params
    for g in grads:
        want, st = dense.step(g, want, st)
    for k in params:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=3e-5, atol=3e-6)


def test_zero_chunked_collective_structure(mesh):
    """The compiled program must contain one reduce-scatter and one
    all-gather PER BUCKET, each consuming a concat of only that bucket's
    leaves — the dataflow that lets XLA overlap collectives with backward
    (VERDICT r2 #1 'done' criterion)."""
    import re
    params = tree_params(jax.random.PRNGKey(22))
    zopt = DistributedFusedAdam(lr=1e-2, axis_name="data", shard_count=NDEV,
                                chunk_elements=256)
    n_buckets = len(zopt._pack(params)["buckets"])
    assert n_buckets > 1
    state = zopt.init(params)
    specs = zopt.state_pspec()
    low = jax.jit(shard_map(
        lambda g, p, s: zopt.step(g, p, s), mesh=mesh,
        in_specs=(P(), P(), specs), out_specs=(P(), specs),
        check_vma=False)).lower(params, params, state).as_text()
    assert len(re.findall(r"reduce_scatter", low)) == n_buckets
    assert len(re.findall(r'"stablehlo.all_gather"', low)) == n_buckets


def test_zero_layout_fingerprint_guards_restore(mesh):
    """r3 ADVICE: ZeroState's flat layout depends on chunk_elements /
    shard_count and nothing in the arrays records it — a checkpoint
    restored under a different layout scrambles silently. The
    fingerprint + check_layout pair makes that a loud failure."""
    params = tree_params(jax.random.PRNGKey(9))
    opt = DistributedFusedAdam(lr=1e-2, axis_name="data", shard_count=NDEV,
                               chunk_elements=128)
    fp = opt.layout_fingerprint(params)
    assert fp["shard_count"] == NDEV and fp["chunk_elements"] == 128
    assert fp["padded"] >= fp["total"] > 0 and fp["n_buckets"] >= 2

    # same config: passes
    opt.check_layout(fp, params)
    # a JSON round-trip (how checkpoints would carry it): still passes
    import json as _json
    opt.check_layout(_json.loads(_json.dumps(fp)), params)

    # different chunk_elements (the r3 layout change): loud failure
    opt2 = DistributedFusedAdam(lr=1e-2, axis_name="data",
                                shard_count=NDEV, chunk_elements=2 ** 23)
    with pytest.raises(ValueError, match="layout mismatch"):
        opt2.check_layout(fp, params)

    # different shard_count: loud failure
    opt3 = DistributedFusedAdam(lr=1e-2, axis_name="data", shard_count=4,
                                chunk_elements=128)
    with pytest.raises(ValueError, match="layout mismatch"):
        opt3.check_layout(fp, params)
