"""apex_tpu.resilience tests: atomic snapshot publish + retention,
corrupt-generation fallback, preemption, fault injection, the
kill-and-resume bitwise guarantee (real SIGKILL via subprocess), and the
telemetry resume accounting."""

import json
import os
import signal
import subprocess
import sys
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import resilience, telemetry
from apex_tpu.resilience import faults
from apex_tpu.resilience.snapshot import MANIFEST, PAYLOAD

WORKER = os.path.join(os.path.dirname(__file__), "resilience_worker.py")


def _state(mul=1.0):
    return {"w": jnp.arange(8, dtype=jnp.float32) * mul,
            "n": jnp.asarray(3 * mul, jnp.float32)}


def _template():
    return {"w": jnp.zeros(8, jnp.float32), "n": jnp.asarray(0.0)}


# ---------------------------------------------------------------------------
# snapshot store
# ---------------------------------------------------------------------------

def test_snapshot_publish_manifest_and_no_tmp(tmp_path):
    mgr = resilience.SnapshotManager(str(tmp_path))
    assert mgr.save(_state(), step=4, layout={"a": 1},
                    loader={"offset": 8}, extra={"seed": 0})
    gens = mgr.generations()
    assert gens == [0]
    man = mgr.manifest(0)
    assert man["step"] == 4 and man["complete"] is True
    assert man["layout"] == {"a": 1} and man["loader"] == {"offset": 8}
    assert man["extra"] == {"seed": 0}
    gdir = tmp_path / "gen_00000000"
    assert (gdir / MANIFEST).exists() and (gdir / PAYLOAD).exists()
    assert man["bytes"] == os.path.getsize(gdir / PAYLOAD)
    # nothing unpublished left behind
    assert not [p for p in os.listdir(tmp_path) if p.startswith("_tmp.")]


def test_restore_latest_roundtrip_and_loader_state(tmp_path):
    mgr = resilience.SnapshotManager(str(tmp_path))
    mgr.save(_state(1.0), step=2)
    mgr.save(_state(2.0), step=4, loader={"offset": 4})
    found = mgr.restore_latest(_template())
    assert found.step == 4 and found.generation == 1
    assert found.manifest["loader"] == {"offset": 4}
    np.testing.assert_array_equal(np.asarray(found.state["w"]),
                                  np.arange(8, dtype=np.float32) * 2)


def test_retention_last_k_plus_every_nth(tmp_path):
    mgr = resilience.SnapshotManager(str(tmp_path), keep_last=2,
                                     keep_every=4)
    for s in range(1, 9):
        mgr.save(_state(float(s)), step=s)
    kept_steps = [mgr.manifest(g)["step"] for g in mgr.generations()]
    # last 2 (steps 7, 8) + every step % 4 == 0 (4, 8)
    assert kept_steps == [4, 7, 8]


def test_restore_skips_corrupt_payload_with_warning(tmp_path):
    mgr = resilience.SnapshotManager(str(tmp_path))
    mgr.save(_state(1.0), step=2)
    mgr.save(_state(2.0), step=4)
    latest = tmp_path / "gen_00000001" / PAYLOAD
    with open(latest, "r+b") as f:
        f.truncate(64)   # mid-write crash shape (pre-atomic era / disk rot)
    with telemetry.capture() as col:
        with pytest.warns(UserWarning, match="skipping corrupt"):
            found = mgr.restore_latest(_template())
    assert found.generation == 0 and found.step == 2
    np.testing.assert_array_equal(np.asarray(found.state["w"]),
                                  np.arange(8, dtype=np.float32))
    names = [e.name for e in col.snapshot()]
    assert "resilience/skipped_generation" in names


def test_restore_skips_bad_manifest_and_crc(tmp_path):
    mgr = resilience.SnapshotManager(str(tmp_path))
    mgr.save(_state(1.0), step=2)
    mgr.save(_state(2.0), step=4)
    mgr.save(_state(3.0), step=6)
    (tmp_path / "gen_00000002" / MANIFEST).write_text("{not json")
    # flip payload bytes without truncating: only the crc can catch this
    p1 = tmp_path / "gen_00000001" / PAYLOAD
    blob = bytearray(p1.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    p1.write_bytes(bytes(blob))
    with pytest.warns(UserWarning, match="skipping corrupt"):
        found = mgr.restore_latest(_template())
    assert found.generation == 0 and found.step == 2


def test_restore_latest_empty_and_missing_dir(tmp_path):
    mgr = resilience.SnapshotManager(str(tmp_path / "never_created"))
    assert mgr.restore_latest(_template()) is None
    assert mgr.latest_step() is None


def test_layout_mismatch_fails_fast_not_skips(tmp_path):
    mgr = resilience.SnapshotManager(str(tmp_path))
    mgr.save(_state(), step=2, layout={"chunk_elements": 1024,
                                       "shard_count": 8})
    with pytest.raises(ValueError, match="layout fingerprint mismatch"):
        mgr.restore_latest(_template(),
                           layout={"chunk_elements": 4096,
                                   "shard_count": 8})
    # matching layout restores fine
    found = mgr.restore_latest(_template(),
                               layout={"chunk_elements": 1024,
                                       "shard_count": 8})
    assert found.step == 2


def test_async_snapshot_roundtrip(tmp_path):
    mgr = resilience.SnapshotManager(str(tmp_path), async_mode=True)
    for s in (2, 4):
        assert mgr.save(_state(float(s)), step=s)
    assert mgr.wait()
    found = mgr.restore_latest(_template())
    assert found.step == 4
    np.testing.assert_array_equal(np.asarray(found.state["w"]),
                                  np.arange(8, dtype=np.float32) * 4)


def test_save_retries_injected_io_error(tmp_path):
    inj = resilience.FaultInjector.parse("step:0:io_error").install()
    try:
        inj.fire(0)   # arms the one-shot OSError
        mgr = resilience.SnapshotManager(str(tmp_path), backoff_s=0.01)
        with telemetry.capture() as col:
            assert mgr.save(_state(), step=1)
        names = [e.name for e in col.snapshot()]
        assert "resilience/save_retry" in names
        assert mgr.generations() == [0]
    finally:
        inj.uninstall()


def test_save_degrades_after_exhausted_retries(tmp_path):
    blocker = tmp_path / "file"
    blocker.write_text("not a directory")
    # the snapshot root is a FILE: every attempt raises OSError
    mgr = resilience.SnapshotManager(str(blocker), save_retries=1,
                                     backoff_s=0.01)
    with telemetry.capture() as col:
        with pytest.warns(UserWarning, match="failed after 2 attempts"):
            assert mgr.save(_state(), step=1) is False
    assert "resilience/save_failed" in [e.name for e in col.snapshot()]


# ---------------------------------------------------------------------------
# preemption
# ---------------------------------------------------------------------------

def test_preemption_handler_sigterm_flag_and_restore():
    prev = signal.getsignal(signal.SIGTERM)
    with resilience.PreemptionHandler() as pre:
        assert not pre.requested()
        os.kill(os.getpid(), signal.SIGTERM)
        assert pre.requested()
        assert pre.reason() == "signal:SIGTERM"
    assert signal.getsignal(signal.SIGTERM) is prev


def test_preemption_deadline():
    with resilience.PreemptionHandler(deadline_s=0.0) as pre:
        assert pre.requested()
        assert pre.reason().startswith("deadline:")


def test_preempted_loop_takes_final_snapshot(tmp_path):
    inj = resilience.FaultInjector.parse("step:3:sigterm").install()
    try:
        r = resilience.resilient_loop(
            lambda st, b, i: st + 1, np.float32(0), lambda i: None,
            steps=10, snapshot_dir=str(tmp_path), snapshot_every=100,
            injector=inj)
    finally:
        inj.uninstall()
    assert r.preempted and r.exit_code == resilience.EXIT_PREEMPTED
    assert r.step == 3 and r.reason == "signal:SIGTERM"
    assert resilience.SnapshotManager(str(tmp_path)).latest_step() == 3


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

def test_fault_spec_parsing():
    inj = resilience.FaultInjector.parse("step:4:kill")
    assert inj.kind == "kill" and inj.step == 4
    inj = resilience.FaultInjector.parse("prob:0.25:nan_grad:7")
    assert inj.prob == 0.25 and inj.seed == 7
    for bad in ("", "step:4", "step:x:kill", "step:4:explode",
                "prob:1.5:kill", "nonsense"):
        with pytest.raises(ValueError):
            resilience.FaultInjector.parse(bad)


def test_nan_grad_fault_is_one_shot():
    inj = resilience.FaultInjector.parse("step:2:nan_grad")
    assert inj.loss_mult(0) == 1.0
    assert np.isnan(inj.loss_mult(2))
    assert inj.loss_mult(2) == 1.0   # fired once


def test_prob_fault_seeded_reproducible():
    sched = []
    for _ in range(2):
        inj = resilience.FaultInjector("io_error", prob=0.3, seed=11)
        sched.append([inj._matches(i) for i in range(32)])
    assert sched[0] == sched[1] and any(sched[0])


def test_node_loss_slow_node_spec_parsing():
    inj = resilience.FaultInjector.parse("step:3:node_loss")
    assert inj.kind == "node_loss" and inj.step == 3 and inj.rank == 1
    inj = resilience.FaultInjector.parse("step:3:node_loss:0")
    assert inj.rank == 0
    inj = resilience.FaultInjector.parse("step:2:slow_node:250")
    assert inj.kind == "slow_node" and inj.delay_ms == 250.0 \
        and inj.rank == 1
    inj = resilience.FaultInjector.parse("step:2:slow_node:250:3")
    assert inj.delay_ms == 250.0 and inj.rank == 3
    inj = resilience.FaultInjector.parse("prob:0.5:node_loss:9")
    assert inj.prob == 0.5 and inj.seed == 9 and inj.rank == 1
    inj = resilience.FaultInjector.parse("prob:0.5:slow_node:40:9")
    assert inj.delay_ms == 40.0 and inj.seed == 9
    for bad in ("step:2:slow_node",          # missing delay
                "step:2:slow_node:x",        # non-numeric delay
                "step:2:node_loss:1:2",      # too many fields
                "step:2:kill:1",             # rank on untargeted kind
                "step:2:slow_node:10:1:2"):
        with pytest.raises(ValueError):
            resilience.FaultInjector.parse(bad)


def test_node_loss_targets_only_its_rank(monkeypatch):
    """fire() on a NON-target rank must be a no-op — every member of a
    fleet shares one APEX_TPU_FAULT env and exactly one dies."""
    monkeypatch.setenv("APEX_TPU_RANK", "0")
    inj = resilience.FaultInjector.parse("step:1:node_loss")  # rank 1
    assert not inj.targets_me()
    inj.fire(1)   # would SIGKILL us if mis-targeted
    assert not inj._fired
    monkeypatch.setenv("APEX_TPU_RANK", "1")
    assert inj.targets_me()
    # PROCESS_ID fallback
    monkeypatch.delenv("APEX_TPU_RANK")
    monkeypatch.setenv("PROCESS_ID", "1")
    assert inj.targets_me()


def test_slow_node_recurring_delay(monkeypatch):
    """slow_node is a CONDITION, not an event: every step at/after the
    trigger sleeps, on the target rank only."""
    import time as _time
    monkeypatch.setenv("APEX_TPU_RANK", "0")
    inj = resilience.FaultInjector.parse("step:2:slow_node:30:0")
    t0 = _time.perf_counter()
    inj.fire(0)
    inj.fire(1)
    fast = _time.perf_counter() - t0
    assert fast < 0.02
    for step in (2, 3):
        t0 = _time.perf_counter()
        inj.fire(step)
        assert _time.perf_counter() - t0 >= 0.025, step
    # off-target rank never sleeps
    monkeypatch.setenv("APEX_TPU_RANK", "5")
    t0 = _time.perf_counter()
    inj.fire(4)
    assert _time.perf_counter() - t0 < 0.02


def test_node_loss_kills_target_rank_subprocess(tmp_path):
    """A real node_loss SIGKILL through resilient_loop: the worker run
    AS rank 1 dies at the fault step; the same spec run as rank 0
    completes untouched."""
    p = _run_worker([6, tmp_path / "snap", tmp_path / "out.npz"],
                    extra_env={"APEX_TPU_FAULT": "step:3:node_loss",
                               "APEX_TPU_RANK": "1"},
                    check=False)
    assert p.returncode in (-signal.SIGKILL, 128 + signal.SIGKILL), \
        f"expected SIGKILL, got rc={p.returncode}\n{p.stderr}"
    _run_worker([6, tmp_path / "snap0", tmp_path / "out0.npz"],
                extra_env={"APEX_TPU_FAULT": "step:3:node_loss",
                           "APEX_TPU_RANK": "0"})
    assert (tmp_path / "out0.npz").exists()


def test_io_error_consumed_once():
    inj = resilience.FaultInjector.parse("step:1:io_error").install()
    try:
        inj.fire(0)
        faults.raise_if_io_error()   # not armed yet: no raise
        inj.fire(1)
        with pytest.raises(OSError, match="injected fault"):
            faults.raise_if_io_error()
        faults.raise_if_io_error()   # one-shot: consumed
    finally:
        inj.uninstall()


# ---------------------------------------------------------------------------
# resilient_loop
# ---------------------------------------------------------------------------

def test_loop_resume_matches_uninterrupted_inprocess(tmp_path):
    def step_fn(st, x, i):
        return st * 0.9 + x, float(st.sum())

    def data(i):
        return np.full(4, i + 1, np.float32)

    # uninterrupted
    full = resilience.resilient_loop(
        step_fn, np.zeros(4, np.float32), data, steps=8,
        handle_signals=False)
    # interrupted at 5 (graceful stop via max steps), then resumed
    part = resilience.resilient_loop(
        step_fn, np.zeros(4, np.float32), data, steps=5,
        snapshot_dir=str(tmp_path), snapshot_every=2,
        handle_signals=False)
    assert part.step == 5 and part.snapshots >= 2
    cont = resilience.resilient_loop(
        step_fn, np.zeros(4, np.float32), data, steps=8,
        snapshot_dir=str(tmp_path), snapshot_every=2,
        handle_signals=False)
    assert cont.resumed_from is not None
    np.testing.assert_array_equal(cont.state, full.state)


def test_loop_does_not_double_skip_self_offsetting_loader(tmp_path):
    """A loader exposing loader_state() manages its own offset (the
    documented PrefetchLoader skip=offset recipe) — the loop must NOT
    also fast-forward it, or `start` items would silently be dropped."""
    from apex_tpu.runtime import PrefetchLoader

    seen = []

    def step_fn(st, x, i):
        seen.append((i, x))
        return st

    resilience.resilient_loop(
        step_fn, 0, PrefetchLoader(iter(range(100)), workers=1), steps=3,
        snapshot_dir=str(tmp_path), snapshot_every=1,
        handle_signals=False)
    assert [x for _, x in sorted(seen)] == [0, 1, 2]
    # resume: reconstruct the loader at the SAVED offset
    mgr = resilience.SnapshotManager(str(tmp_path))
    offset = mgr.latest_manifest()["loader"]["offset"]
    assert offset == 3
    seen.clear()
    resilience.resilient_loop(
        step_fn, 0, PrefetchLoader(iter(range(100)), skip=offset,
                                   workers=1),
        steps=6, snapshot_dir=str(tmp_path), snapshot_every=1,
        handle_signals=False)
    assert [x for _, x in sorted(seen)] == [3, 4, 5]


def test_second_signal_redelivers_with_prev_disposition():
    """The second-signal escape hatch re-delivers the signal under the
    PREVIOUS disposition (real signal death semantics), instead of
    raising a Python traceback from inside the handler."""
    hits = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: hits.append(s))
    try:
        with resilience.PreemptionHandler() as pre:
            os.kill(os.getpid(), signal.SIGTERM)
            assert pre.requested() and not hits
            os.kill(os.getpid(), signal.SIGTERM)   # re-delivered to prev
        assert hits == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_loop_fast_forwards_plain_iterator(tmp_path):
    seen = []

    def step_fn(st, x, i):
        seen.append((i, x))
        return st

    resilience.resilient_loop(step_fn, 0, iter(range(100)), steps=3,
                              snapshot_dir=str(tmp_path),
                              snapshot_every=1, handle_signals=False)
    seen.clear()
    resilience.resilient_loop(step_fn, 0, iter(range(100)), steps=6,
                              snapshot_dir=str(tmp_path),
                              snapshot_every=1, handle_signals=False)
    # resumed at step 3: iterator fast-forwarded so step i gets item i
    assert seen == [(3, 3), (4, 4), (5, 5)]


def test_loop_corrupt_latest_falls_back_and_still_matches(tmp_path):
    def step_fn(st, x, i):
        return st + x

    def data(i):
        return np.float32(i + 1)

    full = resilience.resilient_loop(step_fn, np.float32(0), data,
                                     steps=6, handle_signals=False)
    resilience.resilient_loop(
        step_fn, np.float32(0), data, steps=4,
        snapshot_dir=str(tmp_path), snapshot_every=2,
        handle_signals=False)
    # corrupt the newest generation; resume must fall back to step 2 and
    # recompute 2..6 to the identical answer
    gens = sorted(p for p in os.listdir(tmp_path) if p.startswith("gen_"))
    with open(tmp_path / gens[-1] / PAYLOAD, "r+b") as f:
        f.truncate(32)
    with pytest.warns(UserWarning, match="skipping corrupt"):
        cont = resilience.resilient_loop(
            step_fn, np.float32(0), data, steps=6,
            snapshot_dir=str(tmp_path), snapshot_every=2,
            handle_signals=False)
    assert cont.resumed_from == 0
    np.testing.assert_array_equal(cont.state, full.state)


def test_loop_emits_resume_marker_and_summarize_reports_it(tmp_path):
    def step_fn(st, x, i):
        return st + 1, float(st)

    resilience.resilient_loop(step_fn, np.float32(0), lambda i: None,
                              steps=4, snapshot_dir=str(tmp_path),
                              snapshot_every=2, handle_signals=False)
    with telemetry.capture() as col:
        resilience.resilient_loop(
            step_fn, np.float32(0), lambda i: None, steps=8,
            snapshot_dir=str(tmp_path), snapshot_every=2,
            handle_signals=False,
            on_step=lambda i, st, loss: telemetry.record(
                "train/loss", loss, step=i))
        events = [e.to_dict() for e in col.drain()]
    markers = [e for e in events if e["name"] == "resilience/resume"]
    assert len(markers) == 1
    assert markers[0]["meta"]["step"] == 4
    agg = telemetry.summarize(events)
    assert agg["resilience"]["resumes"] == [
        {"step": 4, "generation": markers[0]["meta"]["generation"]}]
    assert "snapshot_s" in agg["resilience"]


def test_summarize_supersedes_pre_resume_samples():
    ev = [{"name": "train/loss", "value": 1.0, "ts": float(s), "step": s}
          for s in range(5)]
    ev.append({"name": "resilience/resume", "value": 1.0, "ts": 10.0,
               "step": 3, "meta": {"generation": 1, "step": 3}})
    ev += [{"name": "train/loss", "value": 2.0, "ts": 10.0 + s, "step": s}
           for s in range(3, 7)]
    from apex_tpu.telemetry.export import _dedup_points
    series, superseded = _dedup_points(ev)
    # steps 3, 4 were re-executed: the resumed run's samples win
    assert series["train/loss"] == [1.0, 1.0, 1.0, 2.0, 2.0, 2.0, 2.0]
    assert superseded == 2
    agg = telemetry.summarize(ev)
    assert agg["resilience"]["superseded_samples"] == 2


def test_loop_rejects_bad_resume_mode():
    with pytest.raises(ValueError, match="resume must be"):
        resilience.resilient_loop(lambda st, b, i: st, 0, lambda i: None,
                                  steps=1, resume="yes")


def test_loop_rejects_orphaned_manager_kwargs():
    """keep_last= etc. without snapshot_dir must raise, not silently
    configure nothing (the user believes snapshotting is on)."""
    with pytest.raises(ValueError, match="need\\s+snapshot_dir"):
        resilience.resilient_loop(lambda st, b, i: st, 0, lambda i: None,
                                  steps=1, keep_last=5,
                                  handle_signals=False)


def test_preempted_failed_final_snapshot_is_not_exit_75(tmp_path):
    """Exit 75 promises 'state persisted, resubmit with resume auto'; a
    preempted run whose final snapshot failed must NOT claim it."""
    blocker = tmp_path / "file"
    blocker.write_text("not a directory")
    mgr = resilience.SnapshotManager(str(blocker), save_retries=0,
                                     backoff_s=0.01)
    inj = resilience.FaultInjector.parse("step:2:sigterm").install()
    try:
        with pytest.warns(UserWarning, match="failed after"):
            r = resilience.resilient_loop(
                lambda st, b, i: st + 1, np.float32(0), lambda i: None,
                steps=10, manager=mgr, snapshot_every=100, injector=inj)
    finally:
        inj.uninstall()
    assert r.preempted and not r.final_snapshot_ok
    assert r.exit_code == 1 and r.snapshots == 0


def test_failed_cadence_save_retried_at_next_cadence(tmp_path):
    """A failed cadence save must not advance last_saved_step — the next
    cadence retries instead of treating the step as covered."""
    real = resilience.SnapshotManager(str(tmp_path), backoff_s=0.01)
    calls = []
    orig = resilience.SnapshotManager.save

    def flaky_save(self, state, **kw):
        calls.append(kw["step"])
        if len(calls) == 1:
            return False   # transient failure, already-warned contract
        return orig(self, state, **kw)

    real.save = flaky_save.__get__(real)
    r = resilience.resilient_loop(
        lambda st, b, i: st + 1, np.float32(0), lambda i: None, steps=4,
        manager=real, snapshot_every=2, handle_signals=False)
    assert calls == [2, 4] and r.snapshots == 1 and r.final_snapshot_ok
    assert resilience.SnapshotManager(str(tmp_path)).latest_step() == 4


def test_wait_timeout_keeps_tracking_inflight_write(tmp_path):
    import threading

    mgr = resilience.SnapshotManager(str(tmp_path), async_mode=True)
    gate = threading.Event()
    orig = mgr._write_with_retries

    def slow_write(*args):
        gate.wait(10)
        return orig(*args)

    mgr._write_with_retries = slow_write
    assert mgr.save(_state(), step=1)
    assert mgr.wait(timeout=0.05) is False   # still in flight: honest
    gate.set()
    assert mgr.wait() is True                # now landed
    assert mgr.generations() == [0]


def test_summarize_segments_stepped_counters():
    """Counter ticks of re-executed steps must not sum across the
    pre-kill attempt and the resumed one."""
    ev = [{"name": "data/starvation", "value": 1.0, "ts": float(s),
           "step": s, "kind": "counter"} for s in range(4)]
    ev.append({"name": "resilience/resume", "value": 0.0, "ts": 9.0,
               "step": 2, "meta": {"generation": 0, "step": 2}})
    ev += [{"name": "data/starvation", "value": 1.0, "ts": 10.0 + s,
            "step": s, "kind": "counter"} for s in range(2, 6)]
    ev.append({"name": "telemetry/dropped", "value": 3.0, "ts": 20.0,
               "kind": "counter"})
    agg = telemetry.summarize(ev)
    # steps 0..5 once each (2, 3 re-executed, counted once), not 8
    assert agg["counters"]["data/starvation"] == 6.0
    assert agg["counters"]["telemetry/dropped"] == 3.0


# ---------------------------------------------------------------------------
# ZeRO layout fingerprint across the sharded family
# ---------------------------------------------------------------------------

def test_zero_layout_fingerprint_guards_restore(tmp_path):
    from apex_tpu.contrib.optimizers.zero import DistributedFusedAdam

    params = {"a": jnp.ones((64, 8)), "b": jnp.ones((32,))}
    opt8 = DistributedFusedAdam(lr=1e-3, shard_count=8)
    opt4 = DistributedFusedAdam(lr=1e-3, shard_count=4)
    fp8 = opt8.layout_fingerprint(params)
    # the fingerprint must survive the manifest's JSON round trip
    assert json.loads(json.dumps(fp8)) == fp8
    assert opt8.layout_mismatch(fp8, params) == {}
    assert "shard_count" in opt4.layout_mismatch(fp8, params)

    mgr = resilience.SnapshotManager(str(tmp_path))
    state = opt8.init(params)
    mgr.save(state, step=2, layout=fp8)
    found = mgr.restore_latest(state, layout=fp8)
    assert found.step == 2
    with pytest.raises(ValueError, match="layout fingerprint mismatch"):
        mgr.restore_latest(state, layout=opt4.layout_fingerprint(params))


# ---------------------------------------------------------------------------
# PrefetchLoader resume state
# ---------------------------------------------------------------------------

def test_prefetch_loader_skip_and_state():
    from apex_tpu.runtime import PrefetchLoader

    loader = PrefetchLoader(iter(range(10)), skip=3, depth=2)
    got = list(loader)
    assert sorted(got) == list(range(3, 10))
    assert loader.loader_state() == {"offset": 10}
    assert loader.stats()["skip"] == 3

    # skip past the end is harmless
    short = PrefetchLoader(iter(range(2)), skip=5)
    assert list(short) == []
    assert short.loader_state() == {"offset": 2}


# ---------------------------------------------------------------------------
# the acceptance test: real SIGKILL + bitwise resume (subprocess)
# ---------------------------------------------------------------------------

def _run_worker(args, extra_env=None, check=True):
    env = dict(os.environ)
    env.pop("APEX_TPU_FAULT", None)
    env.update(extra_env or {})
    p = subprocess.run([sys.executable, WORKER, *[str(a) for a in args]],
                       capture_output=True, text=True, env=env,
                       timeout=300)
    if check and p.returncode != 0:
        raise AssertionError(
            f"worker failed rc={p.returncode}\nstdout:{p.stdout}\n"
            f"stderr:{p.stderr}")
    return p


def test_kill_and_resume_bitwise(tmp_path):
    """The headline guarantee: SIGKILL at step 3, auto-resume, and the
    final params / fp32 masters / Adam moments / scaler state / loss
    trajectory all match an uninterrupted run EXACTLY (the resilience
    analog of the tune/health jaxpr-equality tests)."""
    out_a = tmp_path / "a.npz"
    out_b = tmp_path / "b.npz"
    _run_worker([6, tmp_path / "snap_a", out_a])

    p = _run_worker([6, tmp_path / "snap_b", out_b],
                    extra_env={"APEX_TPU_FAULT": "step:3:kill"},
                    check=False)
    assert p.returncode in (-signal.SIGKILL, 128 + signal.SIGKILL), \
        f"expected SIGKILL, got rc={p.returncode}\n{p.stderr}"
    assert not out_b.exists()   # died before finishing — really killed

    # resume: snapshots exist only for step 2 (kill landed before step 4's)
    _run_worker([6, tmp_path / "snap_b", out_b],
                extra_env={"SNAP_ASYNC": "1"})
    a, b = np.load(out_a), np.load(out_b)
    assert int(b["resumed_from"]) >= 0 and int(a["resumed_from"]) == -1
    for key in a.files:
        if key in ("losses", "resumed_from"):
            continue
        np.testing.assert_array_equal(a[key], b[key], err_msg=key)
    # loss trajectory of the re-executed + new steps matches exactly
    la = {int(s): v for s, v in a["losses"]}
    lb = {int(s): v for s, v in b["losses"]}
    assert set(lb) == {2, 3, 4, 5}   # resumed from the step-2 snapshot
    for s, v in lb.items():
        assert la[s] == v, (s, la[s], v)


def test_kill_and_resume_bitwise_through_trainer(tmp_path):
    """The SIGKILL auto-resume bitwise guarantee re-run through
    ``apex_tpu.trainer`` + ``resilient_loop(trainer=...)``: donation +
    an in-flight dispatch window of 2 must not break the exit-75/resume
    contract. The baseline is the HAND-BUILT uninterrupted run — so this
    also pins trainer-built numerics to the pre-refactor step, not just
    trainer-to-trainer consistency."""
    out_a = tmp_path / "a.npz"
    out_b = tmp_path / "b.npz"
    _run_worker([6, tmp_path / "snap_a", out_a])     # hand-built, no kill

    p = _run_worker([6, tmp_path / "snap_b", out_b],
                    extra_env={"USE_TRAINER": "1",
                               "APEX_TPU_FAULT": "step:3:kill"},
                    check=False)
    assert p.returncode in (-signal.SIGKILL, 128 + signal.SIGKILL), \
        f"expected SIGKILL, got rc={p.returncode}\n{p.stderr}"
    assert not out_b.exists()

    _run_worker([6, tmp_path / "snap_b", out_b],
                extra_env={"USE_TRAINER": "1", "SNAP_ASYNC": "1"})
    a, b = np.load(out_a), np.load(out_b)
    assert int(b["resumed_from"]) >= 0 and int(a["resumed_from"]) == -1
    for key in a.files:
        if key in ("losses", "resumed_from"):
            continue
        np.testing.assert_array_equal(a[key], b[key], err_msg=key)
    la = {int(s): v for s, v in a["losses"]}
    lb = {int(s): v for s, v in b["losses"]}
    assert set(lb) == {2, 3, 4, 5}   # resumed from the step-2 snapshot
    for s, v in lb.items():
        assert la[s] == v, (s, la[s], v)


def test_worker_uninterrupted_is_deterministic(tmp_path):
    """Foundation for the bitwise claim: two independent uninterrupted
    runs agree bit-for-bit (otherwise the kill test proves nothing)."""
    out1, out2 = tmp_path / "r1.npz", tmp_path / "r2.npz"
    _run_worker([4, tmp_path / "s1", out1])
    _run_worker([4, tmp_path / "s2", out2])
    a, b = np.load(out1), np.load(out2)
    for key in a.files:
        np.testing.assert_array_equal(a[key], b[key], err_msg=key)
