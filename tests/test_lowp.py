"""apex_tpu.lowp (the fp8 compute tier, amp O6/O7) and the int8 wire
tier on the 8-device CPU mesh: the delayed-scaling state machine, the
e4m3/e5m2 QDQ custom_vjp contract, fp8_matmul backend parity (jnp
reference vs the Pallas kernel in interpret mode) and its off-TPU
decline, int8 gradient collectives (DDP / adasum / ZeRO reduce-scatter)
with their exact power-of-two loss-scale invariances, the O0-O5
jaxpr-identity guarantee, the planner's fp8/int8 pricing pins, the tune
satellite (fp8 candidates decline off-TPU), and the lowp/* health
series."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu import amp, lowp, parallel
from apex_tpu.amp import interposition as interp
from apex_tpu.amp import policy as amp_policy
from apex_tpu.lowp import interpose as lowp_interpose
from apex_tpu.lowp import matmul as lowp_mm
from apex_tpu.lowp import scaling
from apex_tpu.parallel import overlap

NDEV = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == NDEV, "conftest must set 8 CPU devices"
    return parallel.make_mesh(axis_names=("data",))


@pytest.fixture
def interposed():
    """amp interposition installed for the test, restored afterwards."""
    interp.install()
    try:
        yield
    finally:
        interp.uninstall()


def _params():
    k = jax.random.split(jax.random.PRNGKey(0), 3)
    return {"w1": jax.random.normal(k[0], (64, 64)),
            "w2": jax.random.normal(k[1], (64, 32)),
            "b": jax.random.normal(k[2], (32,)) * 0.1}


def _batch():
    return jax.random.normal(jax.random.PRNGKey(9), (16, 64))


def _loss(p, x):
    h = jnp.tanh(x @ p["w1"])
    return jnp.mean((h @ p["w2"] + p["b"]) ** 2)


def _mlp():
    """Fresh closure per call: jax.make_jaxpr caches by function
    identity, so a context-dependent trace comparison must never reuse
    the same callable across contexts."""
    def f(p, x):
        h = jnp.tanh(jnp.matmul(x, p["w1"]))
        return jnp.mean(jnp.matmul(h, p["w2"]) ** 2)
    return f


def _mlp_args():
    k = jax.random.split(jax.random.PRNGKey(3), 3)
    p = {"w1": jax.random.normal(k[0], (32, 32)),
         "w2": jax.random.normal(k[1], (32, 16))}
    return p, jax.random.normal(k[2], (8, 32))


# ---------------------------------------------------------------------------
# delayed-scaling state machine (lowp.scaling)
# ---------------------------------------------------------------------------

def test_init_state_shapes():
    st = scaling.init_state(3, history=5)
    assert st["amax_history"].shape == (3, 5)
    assert st["scale"].shape == (3,)
    np.testing.assert_array_equal(st["amax_history"], 0.0)
    np.testing.assert_array_equal(st["scale"], 1.0)


def test_init_state_validates():
    with pytest.raises(ValueError):
        scaling.init_state(-1)
    with pytest.raises(ValueError):
        scaling.init_state(2, history=0)


def test_pow2_scale_properties():
    amax = jnp.array([0.0, 1.0, 448.0, 1e-4, 3.7])
    s = np.asarray(scaling.pow2_scale(amax, scaling.E4M3_MAX, margin=0))
    # dead tensor -> unit scale
    assert s[0] == 1.0
    # every scale is a power of two
    assert np.all(np.exp2(np.round(np.log2(s))) == s)
    # amax * scale lands at or under fp8_max
    a = np.asarray(amax)[1:]
    assert np.all(a * s[1:] <= scaling.E4M3_MAX)
    # margin subtracts binades
    s1 = np.asarray(scaling.pow2_scale(amax, scaling.E4M3_MAX, margin=1))
    np.testing.assert_allclose(s1[1:], s[1:] / 2.0)


def test_pow2_scale_exponent_clamped():
    s_tiny = float(scaling.pow2_scale(1e-36, scaling.E4M3_MAX))
    s_huge = float(scaling.pow2_scale(1e38, scaling.E4M3_MAX))
    assert s_tiny == 2.0 ** 30
    assert s_huge == 2.0 ** -30
    assert np.isfinite(s_tiny) and s_huge > 0.0


def test_update_state_rolls_history_and_rescales():
    st = scaling.init_state(2, history=3)
    st = scaling.update_state(st, jnp.array([1.0, 448.0]))
    np.testing.assert_array_equal(st["amax_history"][:, 0], [1.0, 448.0])
    # scale derives from the history max at the default margin
    np.testing.assert_array_equal(
        np.asarray(st["scale"]),
        np.asarray(scaling.pow2_scale(jnp.array([1.0, 448.0]),
                                      scaling.E4M3_MAX)))
    # second push shifts the first into slot 1
    st2 = scaling.update_state(st, jnp.array([2.0, 4.0]))
    np.testing.assert_array_equal(st2["amax_history"][:, 0], [2.0, 4.0])
    np.testing.assert_array_equal(st2["amax_history"][:, 1], [1.0, 448.0])
    # the history MAX drives the scale: tensor 1's 448 still governs
    np.testing.assert_array_equal(
        np.asarray(st2["scale"])[1],
        np.asarray(scaling.pow2_scale(448.0, scaling.E4M3_MAX)))


def test_update_state_bounded_history_forgets():
    st = scaling.init_state(1, history=2)
    st = scaling.update_state(st, jnp.array([448.0]))
    small = scaling.update_state(
        scaling.update_state(st, jnp.array([1.0])), jnp.array([1.0]))
    # the 448 spike has aged out of the 2-deep ring
    np.testing.assert_array_equal(
        np.asarray(small["scale"]),
        np.asarray(scaling.pow2_scale(jnp.array([1.0]), scaling.E4M3_MAX)))


def test_update_state_count_mismatch_raises():
    st = scaling.init_state(2)
    with pytest.raises(ValueError, match="does not match"):
        scaling.update_state(st, jnp.array([1.0, 2.0, 3.0]))


def test_quantize_dequantize_pow2_exact():
    # values already representable in e4m3 at a pow2 scale round-trip
    # bit-exactly (pow2 scales multiply mantissas exactly)
    x = jnp.array([0.5, 1.0, 1.5, -2.0, 0.0])
    for s in (1.0, 2.0, 0.25):
        q = scaling.quantize(x, s)
        np.testing.assert_array_equal(
            np.asarray(scaling.dequantize(q, s)), np.asarray(x))
    # a full-mantissa e4m3 value survives at unit scale
    np.testing.assert_array_equal(
        np.asarray(scaling.dequantize(
            scaling.quantize(jnp.array([240.0]), 1.0), 1.0)), [240.0])


def test_quantize_saturates_not_inf():
    q = scaling.quantize(jnp.array([1e6, -1e6]), 1.0, scaling.E5M2)
    d = np.asarray(scaling.dequantize(q, 1.0))
    assert np.all(np.isfinite(d))
    np.testing.assert_array_equal(np.abs(d), scaling.E5M2_MAX)


# ---------------------------------------------------------------------------
# QDQ cast pairs (lowp.qdq)
# ---------------------------------------------------------------------------

def test_qdq_roundtrip_error_bound():
    x = jax.random.normal(jax.random.PRNGKey(1), (256,))
    s = scaling.pow2_scale(jnp.max(jnp.abs(x)), scaling.E4M3_MAX, margin=0)
    y = np.asarray(lowp.qdq(x, s))
    # e4m3: 3 mantissa bits -> half-ulp relative error 2^-4
    err = np.abs(y - np.asarray(x))
    bound = np.maximum(2.0 ** -3 * np.abs(np.asarray(x)), 2.0 ** -6)
    assert np.all(err <= bound)


def test_fake_quant_forward_matches_qdq():
    x = jax.random.normal(jax.random.PRNGKey(2), (64,))
    s = jnp.float32(4.0)
    np.testing.assert_array_equal(np.asarray(lowp.fake_quant(x, s)),
                                  np.asarray(lowp.qdq(x, s)))


def test_fake_quant_grad_of_sum_is_exact_ones():
    # the cotangent of sum() is ones — exactly representable in e5m2 at
    # a pow2 scale, so the straight-through backward is bit-exact
    x = jax.random.normal(jax.random.PRNGKey(3), (32,))
    g = jax.grad(lambda x: jnp.sum(lowp.fake_quant(x, jnp.float32(1.0))))(x)
    np.testing.assert_array_equal(np.asarray(g), 1.0)


def test_fake_quant_grad_is_e5m2_of_cotangent():
    x = jax.random.normal(jax.random.PRNGKey(4), (128,))
    r = jax.random.normal(jax.random.PRNGKey(5), (128,))
    g = jax.grad(
        lambda x: jnp.sum(lowp.fake_quant(x, jnp.float32(1.0)) * r))(x)
    # backward = e5m2 QDQ of the cotangent r at its own JIT pow2 scale
    gs = scaling.pow2_scale(jnp.max(jnp.abs(r)), scaling.E5M2_MAX, margin=0)
    want = lowp.qdq(r, gs, scaling.E5M2)
    np.testing.assert_array_equal(np.asarray(g), np.asarray(want))
    # e5m2: 2 mantissa bits -> half-ulp relative error 2^-3
    err = np.abs(np.asarray(g) - np.asarray(r))
    assert np.all(err <= np.maximum(0.13 * np.abs(np.asarray(r)), 2e-2))


def test_fake_quant_scale_gets_zero_cotangent():
    x = jax.random.normal(jax.random.PRNGKey(6), (16,))
    gs = jax.grad(lambda s: jnp.sum(lowp.fake_quant(x, s)))(jnp.float32(2.0))
    assert float(gs) == 0.0


# ---------------------------------------------------------------------------
# fp8_matmul: reference path, Pallas parity, off-TPU decline
# ---------------------------------------------------------------------------

def _mm_operands(m=128, k=128, n=128, dtype=jnp.float32):
    kx, kw = jax.random.split(jax.random.PRNGKey(7))
    return (jax.random.normal(kx, (m, k)).astype(dtype),
            jax.random.normal(kw, (k, n)).astype(dtype))


def test_fp8_matmul_close_to_fp32():
    x, w = _mm_operands()
    got = np.asarray(lowp.fp8_matmul(x, w))
    want = np.asarray(x @ w)
    rel = np.linalg.norm(got - want) / np.linalg.norm(want)
    assert rel < 0.1  # bounded by e4m3 operand quantization


def test_fp8_matmul_explicit_scales_match_manual():
    x, w = _mm_operands(64, 32, 48)
    sx, sw = jnp.float32(64.0), jnp.float32(32.0)
    got = lowp.fp8_matmul(x, w, scale_x=sx, scale_w=sw)
    acc = jax.lax.dot_general(
        scaling.quantize(x, sx), scaling.quantize(w, sw),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(acc / (sx * sw)))


def test_fp8_matmul_out_dtype():
    x, w = _mm_operands(dtype=jnp.bfloat16)
    assert lowp.fp8_matmul(x, w).dtype == jnp.bfloat16
    assert lowp.fp8_matmul(x, w, out_dtype=jnp.float32).dtype == jnp.float32


def test_fp8_matmul_shape_validation():
    with pytest.raises(ValueError, match="fp8_matmul"):
        lowp.fp8_matmul(jnp.ones((4, 8)), jnp.ones((4, 8)))


def test_supported_requires_128_alignment():
    assert lowp.supported(128, 256, 512)
    assert not lowp.supported(100, 128, 128)
    assert not lowp.supported(128, 130, 128)


def test_backend_select():
    assert lowp_mm.backend() == "jnp"  # auto resolves to the reference
    with pytest.raises(ValueError):
        lowp_mm.set_backend("cuda")
    prev = lowp_mm.set_backend("pallas")
    try:
        assert lowp_mm.backend() == "pallas"
    finally:
        lowp_mm.set_backend(prev)


def test_pallas_backend_declines_off_tpu():
    """satellite: an fp8 Pallas candidate off-TPU must decline (fall to
    the jnp reference), not crash or silently interpret."""
    x, w = _mm_operands()
    want = lowp.fp8_matmul(x, w)
    prev = lowp_mm.set_backend("pallas")
    try:
        assert not lowp_mm._use_pallas(128, 128, 128)
        np.testing.assert_array_equal(np.asarray(lowp.fp8_matmul(x, w)),
                                      np.asarray(want))
    finally:
        lowp_mm.set_backend(prev)


@pytest.mark.slow
def test_pallas_interpret_parity():
    """The Mosaic kernel (via the interpreter — test hook only) must
    reproduce the jnp reference: bit-for-bit when one grid step covers
    the whole product (identical dot), and within f32 summation-
    reordering noise under real blocking (XLA's reduction order differs
    per dot shape; the fp8 operand quantization is identical)."""
    x, w = _mm_operands(256, 256, 256)
    want = lowp.fp8_matmul(x, w)
    prev = lowp_mm.set_backend("pallas")
    lowp_mm._ALLOW_INTERPRET = True
    try:
        assert lowp_mm._use_pallas(256, 256, 256)
        whole = lowp.fp8_matmul(x, w, block_m=256, block_n=256,
                                block_k=256)
        blocked = lowp.fp8_matmul(x, w, block_m=128, block_n=128,
                                  block_k=128)
    finally:
        lowp_mm._ALLOW_INTERPRET = False
        lowp_mm.set_backend(prev)
    np.testing.assert_array_equal(np.asarray(whole), np.asarray(want))
    np.testing.assert_allclose(np.asarray(blocked), np.asarray(want),
                               rtol=1e-4, atol=1e-3)


# ---------------------------------------------------------------------------
# fp8_autocast: interposition, warmup, state threading, O0-O5 identity
# ---------------------------------------------------------------------------

def test_interposition_inert_without_context(interposed):
    """The tentpole's jaxpr-identity pin: installed wrappers with no fp8
    context and no autocast dtype trace the ORIGINAL program."""
    p, x = _mlp_args()
    interp.uninstall()
    j_plain = str(jax.make_jaxpr(_mlp())(p, x))
    interp.install()
    j_installed = str(jax.make_jaxpr(_mlp())(p, x))
    assert j_installed == j_plain
    with lowp.fp8_autocast(track=False):
        j_fp8 = str(jax.make_jaxpr(_mlp())(p, x))
    assert j_fp8 != j_plain
    assert "f8_e4m3" in j_fp8  # QDQ pairs actually spliced in


def test_autocast_without_install_is_inert():
    interp.uninstall()
    p, x = _mlp_args()
    j_plain = str(jax.make_jaxpr(_mlp())(p, x))
    with lowp.fp8_autocast(track=False) as ctx:
        j_ctx = str(jax.make_jaxpr(_mlp())(p, x))
        assert ctx.num_tensors == 0
    assert j_ctx == j_plain


def test_opt_levels_o0_to_o5_have_no_fp8():
    for lvl in ("O0", "O1", "O2", "O3", "O4", "O5"):
        assert amp_policy.resolve(lvl).fp8 is False


def test_opt_level_o6_o7_properties():
    o6 = amp_policy.resolve("O6")
    assert o6.fp8 and o6.cast_model_type == jnp.bfloat16
    assert not o6.master_weights and o6.loss_scale == 1.0
    o7 = amp_policy.resolve("O7")
    assert o7.fp8 and o7.master_weights
    assert o7.cast_model_type == jnp.bfloat16


def test_warmup_state_counts_intercepted_tensors(interposed):
    p, x = _mlp_args()
    st = lowp.warmup_state(_mlp(), p, x)
    # two matmuls x two float operands each = 4 tensor slots
    assert st["scale"].shape == (4,)
    assert st["amax_history"].shape == (4, scaling.DEFAULT_HISTORY)


def test_suspend_deactivates_context():
    with lowp.fp8_autocast(track=False) as ctx:
        assert lowp_interpose.current() is ctx
        with lowp_interpose.suspend():
            assert lowp_interpose.current() is None
        assert lowp_interpose.current() is ctx
    assert lowp_interpose.current() is None


def test_disable_casts_suspends_fp8_context():
    with lowp.fp8_autocast(track=False) as ctx:
        with interp.disable_casts():
            assert lowp_interpose.current() is None
        assert lowp_interpose.current() is ctx


def test_state_threading_through_jitted_steps(interposed):
    f = _mlp()
    p, x = _mlp_args()
    st0 = lowp.warmup_state(f, p, x)

    @jax.jit
    def step(p, st, x):
        def loss_fn(p):
            with lowp.fp8_autocast(st, track=False) as ctx:
                loss = f(p, x)
            return loss, ctx.new_state()
        (loss, new_st), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
        return loss, new_st, g

    l1, st1, g1 = step(p, st0, x)
    assert np.isfinite(float(l1))
    # scales moved off the unit init once real amaxes arrived
    assert not np.all(np.asarray(st1["scale"]) == 1.0)
    l2, st2, g2 = step(p, st1, x)
    # same data -> same history max -> scales are a fixed point
    np.testing.assert_array_equal(np.asarray(st2["scale"]),
                                  np.asarray(st1["scale"]))
    # fp8 grads track the fp32 grads within quantization noise
    g32 = jax.grad(lambda p: f(p, x))(p)
    for k in g32:
        rel = (np.linalg.norm(np.asarray(g1[k]) - np.asarray(g32[k]))
               / np.linalg.norm(np.asarray(g32[k])))
        assert rel < 0.35, (k, rel)


def test_new_state_count_mismatch_raises(interposed):
    p, x = _mlp_args()
    st = lowp.warmup_state(_mlp(), p, x)  # 4 slots
    with lowp.fp8_autocast(st, track=False) as ctx:
        jnp.matmul(x, p["w1"])  # only 2 slots used
    with pytest.raises(ValueError, match="warmup"):
        ctx.new_state()


def test_new_state_axis_name_syncs_amaxes(mesh, interposed):
    """Data-parallel shards each observe only their batch shard's
    activations: without ``new_state(axis_name=)`` the threaded state
    diverges across replicas; with it every shard gets the pmax-combined
    amaxes. Runs inside a value_and_grad aux, which also pins the
    stop_gradient guard in front of the pmax (pmax has no
    differentiation rule)."""
    f = _mlp()
    p, x = _mlp_args()
    # give every shard a DIFFERENT input magnitude -> different local
    # amaxes on the activation slots
    xs = jnp.concatenate([x * (i + 1) for i in range(NDEV)])
    st0 = lowp.warmup_state(f, p, x)

    def run(axis_name):
        def body(p, xs):
            def loss_fn(p):
                with lowp.fp8_autocast(st0, track=False) as ctx:
                    loss = f(p, xs)
                return loss, ctx.new_state(axis_name=axis_name)
            (_, st), _ = jax.value_and_grad(loss_fn, has_aux=True)(p)
            # newest history row = this step's amaxes; scale consumes it
            return st["amax_history"][0], st["scale"]
        amax, scale = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P(), P("data")),
            out_specs=P("data"), check_vma=False))(p, xs)
        return (np.asarray(amax).reshape(NDEV, -1),
                np.asarray(scale).reshape(NDEV, -1))

    amax_local, _ = run(None)
    assert not np.all(amax_local == amax_local[0]), \
        "shards should disagree without the sync"
    amax_sync, scale_sync = run("data")
    # every shard holds the same, globally max-combined amaxes -> the
    # next step's scales are replica-consistent
    np.testing.assert_array_equal(amax_sync,
                                  np.broadcast_to(amax_sync[0],
                                                  amax_sync.shape))
    np.testing.assert_array_equal(scale_sync,
                                  np.broadcast_to(scale_sync[0],
                                                  scale_sync.shape))
    np.testing.assert_array_equal(amax_sync[0], amax_local.max(axis=0))


def test_amp_initialize_o6_trains(interposed):
    from apex_tpu import optimizers
    k = jax.random.split(jax.random.PRNGKey(11), 4)
    p = {"w1": jax.random.normal(k[0], (32, 32)) * 0.3,
         "w2": jax.random.normal(k[1], (32, 8)) * 0.3}
    x = jax.random.normal(k[2], (16, 32))
    y = jax.random.normal(k[3], (16, 8))

    def apply_fn(q, x):
        return jnp.matmul(jnp.tanh(jnp.matmul(x, q["w1"])), q["w2"])

    model, aopt = amp.initialize(apply_fn, optimizers.FusedSGD(lr=0.1),
                                 opt_level="O6", verbosity=0)
    st = lowp.warmup_state(lambda q: model(q, x), p)
    ost = aopt.init(p)

    @jax.jit
    def step(p, ost, st):
        def loss_fn(q):
            with lowp.fp8_autocast(st, track=False) as ctx:
                pred = model(q, x)
                loss = jnp.mean((pred.astype(jnp.float32) - y) ** 2)
            return loss, ctx.new_state()
        (loss, new_st), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
        p2, ost2, _ = aopt.step(g, p, ost)
        return loss, p2, ost2, new_st

    l0, p, ost, st = step(p, ost, st)
    losses = [float(l0)]
    for _ in range(3):
        l, p, ost, st = step(p, ost, st)
        losses.append(float(l))
    assert all(np.isfinite(v) for v in losses)
    assert losses[-1] < losses[0]  # fp8 O6 actually optimizes


# ---------------------------------------------------------------------------
# int8 wire tier (parallel.overlap / DDP / adasum / ZeRO)
# ---------------------------------------------------------------------------

def test_int8_wire_scale_value_and_bound():
    a, w = 3.0, 8
    s = float(overlap.int8_wire_scale(jnp.float32(a), w))
    assert s == pytest.approx(a * w / (overlap.INT8_MAX - 0.5 * w))
    # the derivation's fixed point: w replicas each shipping
    # |q_i| <= amax/s + 1/2 sum to exactly the int8 ceiling
    assert w * (a / s + 0.5) == pytest.approx(overlap.INT8_MAX)
    # dead bucket -> unit scale
    assert float(overlap.int8_wire_scale(jnp.float32(0.0), w)) == 1.0


def test_int8_wire_scale_world_too_large_raises():
    with pytest.raises(ValueError, match="headroom"):
        overlap.int8_wire_scale(jnp.float32(1.0), 253)
    # w = 252 is the last world size with >= 1 integer of headroom
    overlap.int8_wire_scale(jnp.float32(1.0), 252)


def test_int8_quantize_roundtrip_bound():
    y = jax.random.normal(jax.random.PRNGKey(8), (1024,)) * 0.1
    s = overlap.int8_wire_scale(jnp.max(jnp.abs(y)), 8)
    d = overlap.int8_dequantize(overlap.int8_quantize(y, s), s)
    assert np.abs(np.asarray(d) - np.asarray(y)).max() <= float(s) * 0.51


def test_resolve_reduce_dtype_int8():
    assert overlap.resolve_reduce_dtype("int8") == jnp.int8


def _grads(mesh, scale=1.0, **kw):
    def body(p, x):
        g = jax.grad(lambda p, x: scale * _loss(p, x))(p, x)
        return parallel.allreduce_gradients(g, "data", **kw)
    return jax.jit(shard_map(body, mesh=mesh,
                             in_specs=(P(), P("data")), out_specs=P(),
                             check_vma=False))(_params(), _batch())


def test_allreduce_int8_close_to_fp32(mesh):
    g32 = _grads(mesh)
    g8 = _grads(mesh, reduce_dtype="int8")
    ref = max(np.abs(np.asarray(v)).max() for v in g32.values())
    for k in g32:
        err = np.abs(np.asarray(g8[k]) - np.asarray(g32[k])).max()
        # worst case w*s/2 where s tracks the pre-averaged local amax;
        # ~15% of the global grad max in practice on this model
        assert err <= 0.2 * ref + 1e-7, (k, err, ref)


def test_allreduce_int8_pow2_loss_scale_exact(mesh):
    """The composition pin: a 2^16 amp loss scale passes through the
    int8 wire EXACTLY — the per-bucket scale is linear in the global
    amax, so the quantized integers are identical and the pow2 factor
    cancels bit-for-bit on dequant."""
    g1 = _grads(mesh, reduce_dtype="int8")
    g2 = _grads(mesh, scale=2.0 ** 16, reduce_dtype="int8")
    for k in g1:
        np.testing.assert_array_equal(np.asarray(g2[k]),
                                      np.asarray(g1[k]) * 2.0 ** 16)


def test_staged_backward_matches_posthoc_int8(mesh):
    def staged(p, x):
        return jax.grad(lambda p: _loss(
            overlap.sync_in_backward(p, "data", reduce_dtype="int8"), x))(p)
    gs = jax.jit(shard_map(staged, mesh=mesh,
                           in_specs=(P(), P("data")), out_specs=P(),
                           check_vma=False))(_params(), _batch())
    gp = _grads(mesh, reduce_dtype="int8")
    for k in gs:
        np.testing.assert_allclose(np.asarray(gs[k]), np.asarray(gp[k]),
                                   rtol=1e-6, atol=1e-7)


def test_adasum_int8_pow2_scale_invariance_exact(mesh):
    """Adasum's defining property survives the int8 wire: scaling every
    input by a power of two scales the output by exactly that factor
    (int8 level scales are linear in the pair amax)."""
    g1 = _grads(mesh, adasum=True, reduce_dtype="int8")
    g2 = _grads(mesh, scale=2.0 ** 16, adasum=True, reduce_dtype="int8")
    for k in g1:
        np.testing.assert_array_equal(np.asarray(g2[k]),
                                      np.asarray(g1[k]) * 2.0 ** 16)


def test_adasum_int8_close_to_adasum_fp32(mesh):
    g32 = _grads(mesh, adasum=True)
    g8 = _grads(mesh, adasum=True, reduce_dtype="int8")
    for k in g32:
        rel = (np.linalg.norm(np.asarray(g8[k]) - np.asarray(g32[k]))
               / max(np.linalg.norm(np.asarray(g32[k])), 1e-12))
        # pairwise tree of w=2 int8 stages: ~15 int levels per operand
        assert rel < 0.15, (k, rel)


def _zero_scatter(mesh, reduce_dtype=None, scale=1.0):
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    opt = DistributedFusedAdam(lr=0.1, axis_name="data",
                               reduce_dtype=reduce_dtype)
    p = _params()
    g = jax.tree_util.tree_map(
        lambda a: a * (0.1 * scale), p)
    spec = opt._pack(p)
    f = jax.jit(shard_map(lambda t: opt._scatter_grads(t, spec),
                          mesh=mesh, in_specs=(P(),),
                          out_specs=P("data"), check_vma=False))
    return f(g)


def test_zero_scatter_int8_close_to_fp32(mesh):
    s32 = np.asarray(_zero_scatter(mesh))
    s8 = np.asarray(_zero_scatter(mesh, reduce_dtype="int8"))
    err = np.abs(s8 - s32).max()
    assert err <= 0.15 * np.abs(s32).max() + 1e-7


def test_zero_scatter_int8_pow2_scale_exact(mesh):
    s1 = np.asarray(_zero_scatter(mesh, reduce_dtype="int8"))
    s2 = np.asarray(_zero_scatter(mesh, reduce_dtype="int8",
                                  scale=2.0 ** 16))
    np.testing.assert_array_equal(s2, s1 * 2.0 ** 16)


# ---------------------------------------------------------------------------
# planner: fp8/int8 pricing pins, layout grammar
# ---------------------------------------------------------------------------

def _desc(flops=1e15, params=int(1e8)):
    from apex_tpu.plan import ModelDesc
    return ModelDesc(name="pin", param_count=params,
                     param_bytes=params * 4, flops_per_step=flops,
                     bytes_per_step=1e12, act_bytes_per_sample=1e6,
                     opt_state_bytes=params * 12,
                     dims={"batch": 64, "seq": 128, "heads": 8,
                           "embed": 512, "layers": 4, "vocab": 1024,
                           "mlp_width": 2048})


def test_layout_id_roundtrip_int8_fp8():
    from apex_tpu.plan import Layout, parse_layout_id
    for kw in (dict(dp=8, reduce_dtype="int8"),
               dict(dp=8, fp8=True),
               dict(dp=4, tp=2, reduce_dtype="int8", fp8=True),
               dict(dp=8, zero=2, reduce_dtype="bf16"),
               dict(dp=8, reduce_dtype="int8", fp8=True, overlap=False)):
        lid = Layout(**kw).layout_id()
        assert parse_layout_id(lid).layout_id() == lid
    assert Layout(dp=8, reduce_dtype="int8", fp8=True).layout_id() \
        == "dp8-int8-fp8"


def test_layout_fp8_must_be_bool():
    from apex_tpu.plan import Layout
    with pytest.raises(ValueError):
        Layout(dp=8, fp8="yes").validate()


def test_int8_wire_bytes_quarter_of_fp32(mesh):
    from apex_tpu.plan import Layout, analytic_wire
    desc = _desc()

    def wire_bytes(**kw):
        return sum(w.bytes_wire * w.count
                   for w in analytic_wire(desc, Layout(dp=8, **kw)))

    full = wire_bytes()
    assert wire_bytes(reduce_dtype="bf16") == pytest.approx(0.5 * full)
    assert wire_bytes(reduce_dtype="int8") == pytest.approx(0.25 * full)


def test_planner_fp8_pick_flip():
    """fp8 pricing must flip a pick on a compute-bound model: the same
    mesh with the fp8 bit wins the ranking."""
    from apex_tpu.plan import Layout, estimate
    desc = _desc(flops=1e16, params=int(1e7))  # compute-dominated
    peaks = {"flops": 2e14, "bytes_per_s": 1e12, "hbm_bytes": 16e9}
    base = estimate(desc, Layout(dp=8), peaks=peaks)
    f8 = estimate(desc, Layout(dp=8, fp8=True), peaks=peaks)
    assert f8.step_s < base.step_s
    assert f8.compute_s == pytest.approx(base.compute_s * 0.5)
    assert any("fp8" in n for n in f8.notes)
    assert not any("fp8" in n for n in base.notes)


def test_planner_int8_wire_pick_flip():
    """int8 wire must rank below bf16 below fp32 on a comm-bound model."""
    from apex_tpu.plan import Layout, estimate
    desc = _desc(flops=1e12, params=int(4e9))  # wire-dominated
    peaks = {"flops": 2e14, "bytes_per_s": 1e12, "hbm_bytes": 64e9}

    def step_s(rd):
        return estimate(desc, Layout(dp=8, reduce_dtype=rd),
                        peaks=peaks).step_s

    assert step_s("int8") < step_s("bf16") < step_s(None)


def test_enumerate_fp8_default_inert():
    from apex_tpu.plan import Constraints, enumerate_candidates
    desc = _desc()
    base = enumerate_candidates(8, desc, Constraints())
    assert all(not l.fp8 for l in base)
    both = enumerate_candidates(
        8, desc, Constraints(fp8_modes=(False, True)))
    assert {l.layout_id() for l in base} <= {l.layout_id() for l in both}
    assert any(l.fp8 for l in both)


def test_adapters_veto_fp8_builds():
    from apex_tpu.plan import GPTAdapter, Layout
    veto = GPTAdapter().veto(Layout(dp=8, fp8=True))
    assert veto is not None and "fp8" in veto
    assert GPTAdapter().veto(Layout(dp=8)) is None


# ---------------------------------------------------------------------------
# tune: fp8 sweep declines off-TPU (satellite), block resolution
# ---------------------------------------------------------------------------

def test_supports_fp8_false_off_tpu():
    from apex_tpu.tune import measure
    assert jax.default_backend() != "tpu"
    assert measure.supports_fp8() is False


def test_fp8_sweep_runner_declines_off_tpu():
    from apex_tpu.tune import sweeps
    spec = sweeps.registry()["fp8_matmul"]
    key = spec.sweep_keys()[0]
    cands = spec.candidates(key)
    assert cands[0] == spec.heuristic(key)  # heuristic leads the sweep
    assert spec.runner(key, cands[0]) is None  # decline, don't crash


def test_fp8_matmul_blocks_defaults_and_alignment():
    from apex_tpu import tune
    bm, bn, bk = tune.fp8_matmul_blocks(m=1024, k=1024, n=1024)
    assert (bm, bn, bk) == (128, 128, 128)
    for b in (bm, bn, bk):
        assert 128 <= b <= 4096 and b % 128 == 0


# ---------------------------------------------------------------------------
# telemetry: lowp/* health series
# ---------------------------------------------------------------------------

def test_lowp_stats_emits_series():
    from apex_tpu.telemetry import events as tel_events
    from apex_tpu.telemetry import health
    prev = health._health_enabled
    with tel_events.capture() as col:
        health.enable()
        try:
            health.lowp_stats(jnp.array([1.0, 500.0]),
                              jnp.array([128.0, 1.0]),
                              labels=("t0:matmul", "t1:matmul"), step=3)
            names = {e.name for e in col.snapshot()}
        finally:
            if not prev:
                health.disable()
    assert "lowp/t0:matmul/amax" in names
    assert "lowp/t0:matmul/scale" in names
    # tensor 1 saturated (amax * scale > 448) -> provenance event
    assert "lowp/saturated" in names


def test_lowp_stats_label_mismatch_raises():
    from apex_tpu.telemetry import events as tel_events
    from apex_tpu.telemetry import health
    prev = health._health_enabled
    with tel_events.capture():
        health.enable()
        try:
            with pytest.raises(ValueError, match="labels"):
                health.lowp_stats(jnp.ones((2,)), jnp.ones((2,)),
                                  labels=("only-one",))
        finally:
            if not prev:
                health.disable()


def test_autocast_emits_lowp_series(interposed):
    from apex_tpu.telemetry import events as tel_events
    from apex_tpu.telemetry import health
    p, x = _mlp_args()
    prev = health._health_enabled
    with tel_events.capture() as col:
        health.enable()
        try:
            with lowp.fp8_autocast(telemetry_step=0) as ctx:
                _mlp()(p, x)
            ctx.new_state()
            names = {e.name for e in col.snapshot()}
        finally:
            if not prev:
                health.disable()
    assert any(n.startswith("lowp/") and n.endswith("/amax")
               for n in names)
    assert any(n.startswith("lowp/") and n.endswith("/scale")
               for n in names)
