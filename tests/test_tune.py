"""apex_tpu.tune test tier: cache durability, policy semantics, the
inert-by-default contract, and the satellite guards.

The load-bearing test is the jaxpr-equality block: under the default
``APEX_TPU_TUNE=off`` policy every ``None``-defaulted call site must
trace to a program BIT-IDENTICAL to passing the pre-PR frozen constants
explicitly — the autotuner must be provably invisible until opted into.
"""

import json
import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import telemetry, tune
from apex_tpu.tune import cache as tcache
from apex_tpu.tune import cli as tcli
from apex_tpu.tune import heuristics, measure, sweeps
from apex_tpu.tune import tuner


@pytest.fixture(autouse=True)
def _isolated_tuner(tmp_path, monkeypatch):
    """Every test gets its own cache dir, a clean memo, and the env
    policy (off) — no test can leak tuned state into another."""
    monkeypatch.setenv("APEX_TPU_TUNE_CACHE_DIR", str(tmp_path / "tune"))
    monkeypatch.delenv("APEX_TPU_TUNE", raising=False)
    tuner.set_policy(None)
    tuner.reset()
    yield
    tuner.set_policy(None)
    tuner.reset()


# ---------------------------------------------------------------------------
# pick_block (satellite: factored out of ops/attention, edges fixed)
# ---------------------------------------------------------------------------

def test_pick_block_reference_cases():
    # the documented r3 cases keep their historical answers
    assert tune.pick_block(1024, 4096) == 1024
    assert tune.pick_block(1024, 1088) == 256   # 1024 would pad to 2048
    assert tune.pick_block(512, 4096) == 512
    assert tune.pick_block(128, 4096) == 128


def test_pick_block_always_valid():
    """The structural contract: a 128-multiple in [128, minimal padded
    length] for EVERY input, including s < 128 and pref < 128 (the old
    in-kernel version relied on the candidate loop to stay in range)."""
    for s in list(range(1, 300, 7)) + [1024, 1088, 1111, 4096, 9999]:
        sp_min = ((s + 127) // 128) * 128
        for pref in (1, 64, 127, 128, 200, 256, 512, 1000, 1024, 1 << 20):
            b = tune.pick_block(pref, s)
            assert b % 128 == 0, (pref, s, b)
            assert 128 <= b <= sp_min, (pref, s, b)


def test_pick_block_is_attentions_pick_block():
    from apex_tpu.ops import attention
    assert attention._pick_block is heuristics.pick_block


def test_shape_bucket():
    assert tune.shape_bucket(1) == 1
    assert tune.shape_bucket(1000) == 1024
    assert tune.shape_bucket(1024) == 1024
    assert tune.shape_bucket(1025) == 2048


# ---------------------------------------------------------------------------
# policy plumbing
# ---------------------------------------------------------------------------

def test_default_policy_is_off():
    assert tune.policy() == "off"


def test_env_policy(monkeypatch):
    monkeypatch.setenv("APEX_TPU_TUNE", "cache")
    assert tune.policy() == "cache"
    monkeypatch.setenv("APEX_TPU_TUNE", "bogus")
    with pytest.raises(ValueError, match="APEX_TPU_TUNE"):
        tune.policy()


def test_set_policy_overrides_env(monkeypatch):
    monkeypatch.setenv("APEX_TPU_TUNE", "cache")
    tune.set_policy("auto")
    assert tune.policy() == "auto"
    tune.set_policy(None)
    assert tune.policy() == "cache"
    with pytest.raises(ValueError):
        tune.set_policy("sideways")


def test_unknown_op_raises():
    with pytest.raises(KeyError, match="unknown tunable op"):
        tune.resolve("warp_drive", {})


def test_off_resolves_to_frozen_heuristics():
    cfg, prov = tune.resolve("attention_fwd",
                             {"sq": 4096, "sk": 4096, "d": 64,
                              "dtype": "bfloat16"})
    assert prov == "default"
    assert cfg == {"block_q": heuristics.ATTENTION_BLOCK_Q,
                   "block_k": heuristics.ATTENTION_BLOCK_K}
    cfg, prov = tune.resolve("ddp_message_size", {"total": 1 << 24,
                                                  "world": 8})
    assert prov == "default"
    assert cfg == {"message_size": heuristics.DDP_MESSAGE_SIZE}


def test_off_touches_no_disk(tmp_path):
    tune.resolve("mt_block", {"n": 1 << 20, "dtype": "float32"})
    assert not os.path.exists(tcache.cache_path())


# ---------------------------------------------------------------------------
# cache: round-trip, corruption, read-only mode, concurrency
# ---------------------------------------------------------------------------

def test_cache_round_trip():
    c = tcache.get_cache()
    key = tuner.cache_key("mt_block", {"n": 1 << 20, "dtype": "float32"})
    assert c.get(key) is None
    assert c.put(key, {"config": {"block_rows": 256},
                       "provenance": "measured", "measured_s": 1e-3})
    entry = c.get(key)
    assert entry["config"] == {"block_rows": 256}
    assert entry["provenance"] == "measured"
    assert "ts" in entry
    # the file itself is valid schema-1 JSON
    with open(c.path) as f:
        data = json.load(f)
    assert data["version"] == tcache.SCHEMA_VERSION
    assert key in data["entries"]


def test_cache_mode_reads_entry():
    c = tcache.get_cache()
    key_d = {"n": 1 << 20, "dtype": "float32"}
    c.put(tuner.cache_key("mt_block", key_d),
          {"config": {"block_rows": 256}, "provenance": "measured"})
    tune.set_policy("cache")
    cfg, prov = tune.resolve("mt_block", key_d)
    assert cfg == {"block_rows": 256}
    assert prov == "measured"


def test_cache_mode_miss_falls_back_and_writes_nothing():
    tune.set_policy("cache")
    key_d = {"n": 1 << 20, "dtype": "float32"}
    cfg, prov = tune.resolve("mt_block", key_d)
    assert prov == "heuristic"
    assert cfg == {"block_rows": heuristics.MT_BLOCK_ROWS}
    assert not os.path.exists(tcache.cache_path())   # read-only: no fill


def test_corrupted_cache_recovers(tmp_path):
    path = tcache.cache_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write("{not json")
    tune.set_policy("cache")
    with pytest.warns(UserWarning, match="unreadable cache"):
        cfg, prov = tune.resolve("mt_block",
                                 {"n": 1 << 20, "dtype": "float32"})
    assert prov == "heuristic"
    assert cfg == {"block_rows": heuristics.MT_BLOCK_ROWS}


def test_wrong_schema_version_recovers():
    path = tcache.cache_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump({"version": 999, "entries": {"x": {}}}, f)
    with pytest.warns(UserWarning, match="unreadable cache"):
        assert tcache.TuneCache(path).entries() == {}


def test_garbage_config_values_degrade_not_crash():
    """A hand-edited entry with unusable values must resolve to the
    heuristic, never trace an illegal block or raise mid-step."""
    c = tcache.get_cache()
    c.put(tuner.cache_key("layer_norm_fwd", {"d": 768, "dtype": "float32"}),
          {"config": {"rows": "many"}, "provenance": "measured"})
    c.put(tuner.cache_key("attention_fwd",
                          {"sq": 4096, "sk": 4096, "d": 64,
                           "dtype": "bfloat16"}),
          {"config": {"block_q": None, "block_k": []},
           "provenance": "measured"})
    tune.set_policy("cache")
    rows = tune.layer_norm_rows(d=768, dtype=jnp.float32)
    assert rows == heuristics.layer_norm_fwd({"d": 768})["rows"]
    bq, bk = tune.attention_blocks("attention_fwd", sq=4096, sk=4096,
                                   d=64, dtype=jnp.bfloat16)
    assert (bq, bk) == (heuristics.ATTENTION_BLOCK_Q,
                        heuristics.ATTENTION_BLOCK_K)


def test_rows_out_of_range_degrade():
    c = tcache.get_cache()
    c.put(tuner.cache_key("moments", {"c": 128, "dtype": "float32"}),
          {"config": {"rows": 7}, "provenance": "measured"})   # < 8: illegal
    tune.set_policy("cache")
    assert tune.moments_rows(c=128, dtype=jnp.float32) \
        == heuristics.moments({"c": 128})["rows"]


def test_rows_respect_dtype_sublane():
    """A cached row count that breaks the dtype's Mosaic sublane rule
    (multiples of 16 for bf16, 32 for int8) degrades to the heuristic —
    a multiple of 8 is only legal for 4-byte dtypes."""
    c = tcache.get_cache()
    c.put(tuner.cache_key("layer_norm_fwd",
                          {"d": 768, "dtype": "bfloat16"}),
          {"config": {"rows": 24}, "provenance": "measured"})
    c.put(tuner.cache_key("layer_norm_fwd",
                          {"d": 768, "dtype": "float32"}),
          {"config": {"rows": 24}, "provenance": "measured"})
    tune.set_policy("cache")
    assert tune.layer_norm_rows(d=768, dtype=jnp.bfloat16) \
        == heuristics.layer_norm_fwd({"d": 768})["rows"]   # 24 % 16 != 0
    assert tune.layer_norm_rows(d=768, dtype=jnp.float32) == 24


def test_negative_cached_bucket_capacity_degrades():
    """A cached message_size/chunk_elements < 1 must fall back to the
    heuristic — clamping to 0 would silently disable bucketing (and for
    ZeRO, change the checkpointed flat layout). 0 stays reachable only
    as an explicit caller value."""
    c = tcache.get_cache()
    c.put(tuner.cache_key("ddp_message_size",
                          {"total": 1 << 24, "world": 8}),
          {"config": {"message_size": -1}, "provenance": "measured"})
    c.put(tuner.cache_key("zero_chunk_elements",
                          {"total": 1 << 24, "world": 8}),
          {"config": {"chunk_elements": 0}, "provenance": "measured"})
    tune.set_policy("cache")
    assert tune.ddp_message_size(total=1 << 24, world=8) \
        == heuristics.DDP_MESSAGE_SIZE
    assert tune.zero_chunk_elements(total=1 << 24, world=8) \
        == heuristics.ZERO_CHUNK_ELEMENTS


def test_mt_block_rows_single_definition():
    """heuristics.MT_BLOCK_ROWS is THE definition; pallas_mt re-exports
    it — a retune cannot silently diverge the off policy from the
    kernel-file constant."""
    from apex_tpu.ops import pallas_mt as mt
    assert mt.BLOCK_ROWS is heuristics.MT_BLOCK_ROWS


def test_concurrent_writers_never_corrupt():
    """8 writers with DISTINCT TuneCache objects (i.e. no shared lock —
    the cross-process shape) hammering one path: the file must stay valid
    JSON throughout and afterwards, and every surviving entry intact.
    Atomic os.replace publishing is what's under test."""
    path = tcache.cache_path()
    n_threads, n_rounds = 8, 12
    errors = []

    def writer(t):
        try:
            c = tcache.TuneCache(path)   # deliberately NOT get_cache()
            for r in range(n_rounds):
                c.put(f"op|thread={t},round={r}", {"config": {"v": t}})
                # interleaved reader: a torn file would explode right here
                tcache.TuneCache(path).entries()
        except Exception as e:           # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors
    with open(path) as f:
        data = json.load(f)               # valid to the end
    assert data["version"] == tcache.SCHEMA_VERSION
    entries = data["entries"]
    assert entries                        # concurrent merge lost SOME
    for key, e in entries.items():        # entries maybe, validity never
        assert e["config"]["v"] == int(key.split("thread=")[1].split(",")[0])


def test_in_process_memo_survives_cache_deletion():
    """auto-mode resolution is memoized per process: once resolved, a
    retrace re-reads the memo — never the disk, never a re-measurement."""
    tune.set_policy("auto")
    key_d = {"n": 1 << 20, "dtype": "float32"}
    cfg1, prov1 = tune.resolve("mt_block", key_d)
    assert prov1 == "heuristic"           # CPU: measurement declines
    path = tcache.cache_path()
    assert os.path.exists(path)           # ...but the cache was filled
    os.unlink(path)
    cfg2, _ = tune.resolve("mt_block", key_d)
    assert cfg2 == cfg1
    assert not os.path.exists(path)       # memo hit: no disk access


def test_auto_mode_on_cpu_is_deterministic_heuristic():
    """Hermetic-CI contract: no wall-clock may reach a compiled program
    on CPU/interpret backends — auto degrades to the heuristic config
    with 'heuristic' provenance, recorded in the cache."""
    assert not measure.measurable()
    tune.set_policy("auto")
    cfg, prov = tune.resolve("layer_norm_fwd",
                             {"d": 768, "dtype": "bfloat16"})
    assert prov == "heuristic"
    assert cfg == heuristics.layer_norm_fwd({"d": 768})
    entry = tcache.get_cache().get(
        tuner.cache_key("layer_norm_fwd", {"d": 768, "dtype": "bfloat16"}))
    assert entry["provenance"] == "heuristic"


# ---------------------------------------------------------------------------
# telemetry: resolutions are recorded
# ---------------------------------------------------------------------------

def test_resolution_emits_tune_event():
    with telemetry.capture() as col:
        tuner.reset()
        tune.resolve("mt_block", {"n": 1 << 20, "dtype": "float32"})
        events = [e for e in col.drain() if e.name == "tune/mt_block"]
    assert len(events) == 1
    meta = events[0].meta
    assert meta["provenance"] == "default"
    assert meta["policy"] == "off"
    assert meta["config"] == {"block_rows": heuristics.MT_BLOCK_ROWS}


# ---------------------------------------------------------------------------
# jaxpr equality: APEX_TPU_TUNE=off is provably inert
# ---------------------------------------------------------------------------

def _jaxpr(fn, *args):
    return str(jax.make_jaxpr(fn)(*args))


def test_off_attention_fwd_jaxpr_identical():
    from apex_tpu.ops import attention
    q = jnp.ones((1, 2, 256, 64), jnp.float32)
    k = jnp.ones((1, 2, 320, 64), jnp.float32)
    v = jnp.ones((1, 2, 320, 64), jnp.float32)

    def tuned(q, k, v):
        return attention._flash_fwd(q, k, v, causal=False, scale=0.125)

    def frozen(q, k, v):
        return attention._flash_fwd(q, k, v, causal=False, scale=0.125,
                                    block_q=1024, block_k=1024)

    assert _jaxpr(tuned, q, k, v) == _jaxpr(frozen, q, k, v)


def test_off_attention_bwd_jaxpr_identical():
    from apex_tpu.ops import attention
    q = jnp.ones((1, 1, 256, 64), jnp.float32)
    k = jnp.ones((1, 1, 256, 64), jnp.float32)
    v = jnp.ones((1, 1, 256, 64), jnp.float32)

    def loss_tuned(q, k, v):
        out = attention.flash_attention(q, k, v, causal=False)
        return jnp.sum(out)

    # the pre-PR backward constants were _BWD_BLOCK_Q/_BWD_BLOCK_K = 1024
    g_tuned = _jaxpr(jax.grad(loss_tuned), q, k, v)

    def loss_frozen(q, k, v):
        out, lse = attention._flash_fwd(q, k, v, causal=False,
                                        scale=64 ** -0.5,
                                        block_q=1024, block_k=1024)
        return jnp.sum(out)

    # spot-check the bwd entry point directly as well
    out, lse = attention._flash_fwd(q, k, v, causal=False, scale=0.125)
    g = jnp.ones_like(out)

    def bwd_tuned(q, k, v, out, lse, g):
        return attention._flash_bwd(q, k, v, out, lse, g, causal=False,
                                    scale=0.125)

    def bwd_frozen(q, k, v, out, lse, g):
        return attention._flash_bwd(q, k, v, out, lse, g, causal=False,
                                    scale=0.125, block_q=1024, block_k=1024)

    assert _jaxpr(bwd_tuned, q, k, v, out, lse, g) \
        == _jaxpr(bwd_frozen, q, k, v, out, lse, g)
    assert g_tuned  # traced without error through the tuner path


def test_off_layer_norm_jaxpr_identical():
    from apex_tpu.ops import pallas_layer_norm as plln
    x = jnp.ones((1000, 768), jnp.float32)
    w = jnp.ones((768,), jnp.float32)
    b = jnp.zeros((768,), jnp.float32)
    frozen_rows = plln._rows_per_block(768)
    assert _jaxpr(lambda x: plln.ln_fwd(x, w, b, 1e-5), x) \
        == _jaxpr(lambda x: plln.ln_fwd(x, w, b, 1e-5,
                                        rows=frozen_rows), x)
    _, mu, rstd = plln.ln_fwd(x, w, b, 1e-5)
    frozen_bwd = plln._rows_per_block(768, arrays=2)
    assert _jaxpr(lambda x: plln.ln_bwd(x, w, mu, rstd, x), x) \
        == _jaxpr(lambda x: plln.ln_bwd(x, w, mu, rstd, x,
                                        rows=frozen_bwd), x)


def test_off_moments_jaxpr_identical():
    from apex_tpu.ops import pallas_moments as pm
    x = jnp.ones((4096, 128), jnp.float32)
    frozen = pm._rows_per_block(128)
    assert _jaxpr(pm._moments_2d, x) \
        == _jaxpr(lambda x: pm._moments_2d(x, rows=frozen), x)


def test_off_mt_adam_jaxpr_identical():
    from apex_tpu.ops import pallas_mt as mt
    n = 3 * mt.BLOCK_ROWS * mt.LANES + 17
    g, p, m, v = (jnp.ones((n,), jnp.float32) for _ in range(4))

    def run(g, p, m, v, br):
        return mt.adam_flat(g, p, m, v, lr=1e-3, beta1=0.9, beta2=0.999,
                            eps=1e-8, bc1=1.0, bc2=1.0, adam_w_mode=True,
                            weight_decay=0.0, block_rows=br)

    assert _jaxpr(lambda *a: run(*a, None), g, p, m, v) \
        == _jaxpr(lambda *a: run(*a, mt.BLOCK_ROWS), g, p, m, v)


def test_off_ddp_jaxpr_identical():
    from apex_tpu.parallel import distributed as dist
    mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("data",))
    leaves = {f"p{i}": jnp.ones((257,), jnp.float32) for i in range(4)}

    def make(msg):
        def body(tree):
            return dist.allreduce_gradients(tree, "data",
                                            message_size=msg)
        return shard_map(body, mesh=mesh, in_specs=(P(),),
                         out_specs=P(), check_vma=False)

    assert _jaxpr(make(None), leaves) == _jaxpr(make(2 ** 23), leaves)


def test_off_zero_layout_matches_frozen():
    """ZeroState layout under chunk_elements=None (tuner off) must equal
    the pre-PR frozen 2**23 layout — the fingerprint guards checkpoints."""
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    params = {"a": jnp.ones((300, 7), jnp.float32),
              "b": jnp.ones((63,), jnp.float32)}
    fp_none = DistributedFusedAdam(lr=1e-3, shard_count=1) \
        .layout_fingerprint(params)
    fp_frozen = DistributedFusedAdam(lr=1e-3, shard_count=1,
                                     chunk_elements=2 ** 23) \
        .layout_fingerprint(params)
    assert fp_none == fp_frozen
    assert fp_none["chunk_elements"] == 2 ** 23


# ---------------------------------------------------------------------------
# degenerate-bucketing guards (satellite)
# ---------------------------------------------------------------------------

def test_zero_negative_chunk_elements_raises():
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    with pytest.raises(ValueError, match="chunk_elements"):
        DistributedFusedAdam(lr=1e-3, chunk_elements=-1)


def test_ddp_negative_message_size_raises():
    from apex_tpu.parallel import distributed as dist
    mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("data",))

    def body(tree):
        return dist.allreduce_gradients(tree, "data", message_size=-5)

    f = shard_map(body, mesh=mesh, in_specs=({"g": P()},),
                  out_specs={"g": P()}, check_vma=False)
    with pytest.raises(ValueError, match="message_size must be >= 1"):
        jax.make_jaxpr(f)({"g": jnp.ones((64,), jnp.float32)})


def test_warn_bucket_count_fires_once_and_records():
    tune._warned_bucket_counts.clear()
    with telemetry.capture() as col:
        with pytest.warns(UserWarning, match="collective buckets"):
            tune.warn_bucket_count("ddp", 300, 16)
        tune.warn_bucket_count("ddp", 300, 16)   # dedup: no second warn
        events = [e for e in col.drain()
                  if e.name == "tune/warn/ddp_buckets"]
    assert len(events) == 1
    assert events[0].value == 300.0
    assert events[0].meta["threshold"] == heuristics \
        .BUCKET_COUNT_WARN_THRESHOLD


def test_warn_bucket_count_quiet_below_threshold():
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        tune.warn_bucket_count("ddp", 256, 2 ** 23)   # at threshold: quiet


def test_ddp_tiny_message_size_warns():
    from apex_tpu.parallel import distributed as dist
    tune._warned_bucket_counts.clear()
    mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("data",))
    leaves = {f"p{i}": jnp.ones((64,), jnp.float32) for i in range(300)}

    def body(tree):
        return dist.allreduce_gradients(tree, "data", message_size=1)

    f = shard_map(body, mesh=mesh, in_specs=(P(),), out_specs=P(),
                  check_vma=False)
    with pytest.warns(UserWarning, match="collective buckets"):
        jax.make_jaxpr(f)(leaves)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_sweep_dry_run(capsys):
    assert tcli.main(["sweep", "--dry-run"]) == 0
    out = capsys.readouterr().out
    assert "nothing measured or written" in out
    for op in sweeps.registry():
        assert op in out
    assert not os.path.exists(tcache.cache_path())


def test_cli_sweep_on_cpu_records_heuristics(capsys):
    assert tcli.main(["sweep", "--ops", "layer_norm_fwd,mt_block"]) == 0
    out = capsys.readouterr().out
    assert "heuristic" in out
    with open(tcache.cache_path()) as f:
        data = json.load(f)
    assert data["version"] == tcache.SCHEMA_VERSION
    provs = {e["provenance"] for e in data["entries"].values()}
    assert provs == {"heuristic"}


def test_cli_sweep_unknown_op():
    with pytest.raises(SystemExit):
        tcli.main(["sweep", "--ops", "warp_drive"])


def test_cli_show_and_clear(capsys):
    tcli.main(["sweep", "--ops", "mt_block"])
    capsys.readouterr()
    assert tcli.main(["show"]) == 0
    assert "mt_block" in capsys.readouterr().out
    assert tcli.main(["clear"]) == 0
    assert not os.path.exists(tcache.cache_path())
    assert tcli.main(["show"]) == 0
    assert "no cache entries" in capsys.readouterr().out


def test_cli_cache_dir_flag(tmp_path, capsys):
    d = str(tmp_path / "elsewhere")
    tcli.main(["--cache-dir", d, "sweep", "--ops", "mt_block"])
    assert os.path.isdir(d)
    assert any(n.endswith(".json") for n in os.listdir(d))
