"""Unified goodput ledger (telemetry.ledger, ROADMAP item 6): train-
side membership-event time accounting (stall + degraded capacity in
equivalent full-fleet seconds), serve-side token goodput, the
summarize rendering that names time lost per event, and the ledger/*
re-emission."""

import pytest

from apex_tpu import telemetry
from apex_tpu.telemetry import ledger


def _step(step, ts, value=1.0, name="step/time_s"):
    return {"name": name, "value": value, "ts": ts, "step": step,
            "kind": "point", "meta": {}}


def _train_events():
    """10 steps at a 1s cadence with a reshard (world 4 -> 3) that
    stalls the run 3s at t=104.2 and leaves it degraded to the end, and
    an earlier resume marker inside the normal cadence."""
    ev = [_step(i, 100.0 + i) for i in range(5)]            # 100..104
    ev.append({"name": "resilience/resume", "value": 1.0, "ts": 101.5,
               "step": 1, "kind": "counter",
               "meta": {"generation": 1, "step": 1,
                        "path": "/snap/gen1"}})
    ev.append({"name": "resilience/reshard", "value": 3.0, "ts": 104.2,
               "step": 4, "kind": "counter",
               "meta": {"from_world": 4, "to_world": 3,
                        "reshard_s": 2.8}})
    ev += [_step(5 + i, 107.0 + i) for i in range(5)]       # 107..111
    return ev


class TestTrainLedger:
    def test_names_time_lost_per_membership_event(self):
        led = ledger.train_ledger(_train_events())
        assert led is not None
        assert led["wall_s"] == pytest.approx(11.0)
        assert led["step_s_median"] == pytest.approx(1.0)
        assert led["max_world"] == 4.0
        by_kind = {e["kind"]: e for e in led["events"]}
        assert set(by_kind) == {"resume", "reshard"}
        # the resume sat inside the normal cadence: no stall billed
        assert by_kind["resume"]["lost_s"] == pytest.approx(0.0)
        # the reshard: 3s gap - 1s cadence = 2s stall, plus the
        # degraded 3/4-capacity tail 104.2 -> 111 = 6.8s * 1/4 = 1.7s
        assert by_kind["reshard"]["stall_s"] == pytest.approx(2.0)
        assert by_kind["reshard"]["degraded_s"] == pytest.approx(1.7)
        assert by_kind["reshard"]["lost_s"] == pytest.approx(3.7)
        assert by_kind["reshard"]["detail"] == "reshard world 4 -> 3"
        assert led["lost_s_total"] == pytest.approx(3.7)
        assert led["goodput"] == pytest.approx(1.0 - 3.7 / 11.0,
                                               abs=1e-3)

    def test_none_without_membership_events_or_cadence(self):
        assert ledger.train_ledger(
            [_step(i, 100.0 + i) for i in range(5)]) is None
        assert ledger.train_ledger([
            _step(0, 100.0),
            {"name": "resilience/reshard", "value": 2.0, "ts": 100.5,
             "step": 0, "kind": "counter",
             "meta": {"from_world": 4, "to_world": 2}}]) is None

    def test_summarize_renders_goodput_section(self):
        s = telemetry.summarize(_train_events())
        t = s["ledger"]["train"]
        assert len(t["events"]) == 2
        text = telemetry.format_summary(s)
        assert "goodput ledger:" in text
        assert "reshard world 4 -> 3" in text
        assert "train goodput:" in text


def _rec(rid, state="done", tokens=3, in_deadline=True):
    return {"rid": rid, "process": 0, "state": state, "prompt_len": 4,
            "max_new": 3, "deadline_s": 1.0, "ts_submit": 100.0 + rid,
            "queued_s": 0.01, "prefill_s": 0.02, "decode_s": 0.03,
            "e2e_s": 0.06, "ttft_s": 0.03, "tpot_s": 0.015,
            "tokens": tokens, "slot": 0,
            "reason": "queue_full" if state == "rejected" else None,
            "in_deadline": in_deadline}


class TestServeLedger:
    def test_token_accounting(self):
        recs = ([_rec(i) for i in range(3)]
                + [_rec(3, state="expired", tokens=2,
                        in_deadline=False)]
                + [_rec(4, state="rejected", tokens=0)])
        led = ledger._serve_account(recs)
        assert led["requests"] == 5
        assert led["completed"] == 3 and led["shed"] == 1
        assert led["expired_inflight"] == 1
        assert led["tokens_decoded"] == 11
        assert led["tokens_useful"] == 9
        assert led["tokens_wasted"] == 2
        assert led["goodput_tokens"] == pytest.approx(9 / 11, abs=1e-3)
        assert led["goodput_requests"] == pytest.approx(0.6)

    def test_emit_serve_writes_ledger_statics(self):
        led = ledger._serve_account([_rec(0)])
        with telemetry.capture() as col:
            ledger.emit_serve(led)
        names = {e.name for e in col.drain()}
        assert {ledger.LEDGER_TOKENS_DECODED, ledger.LEDGER_TOKENS_USEFUL,
                ledger.LEDGER_TOKENS_WASTED,
                ledger.LEDGER_GOODPUT_TOKENS,
                ledger.LEDGER_GOODPUT_REQUESTS} <= names

    def test_compute_keys_present_only_with_producers(self):
        assert ledger.compute([_step(0, 1.0)]) == {}
        both = _train_events()
        both.append({"name": "req/submit", "value": 0.0, "ts": 200.0,
                     "step": None, "kind": "req",
                     "meta": {"rid": 0, "prompt_len": 4, "max_new": 2}})
        both.append({"name": "req/finish", "value": 0.0, "ts": 200.5,
                     "step": None, "kind": "req",
                     "meta": {"rid": 0, "slot": 0, "tokens": 2,
                              "decode_s": 0.1, "e2e_s": 0.5,
                              "in_deadline": True}})
        out = ledger.compute(both)
        assert set(out) == {"train", "serve"}
        assert out["serve"]["tokens_useful"] == 2

    def test_format_ledger_text(self):
        led = {"serve": ledger._serve_account(
            [_rec(0), _rec(1, state="expired", tokens=1,
                           in_deadline=False)])}
        lines = ledger.format_ledger(led)
        assert lines[0] == "goodput ledger:"
        joined = "\n".join(lines)
        assert "decoded tokens useful" in joined
        assert "1 in-flight expiries" in joined
