"""apex_tpu.lint mem verifier (APX301-APX307) — the liveness engine's
hand-derived synthetic timeline (equation by equation), donation
aliasing deltas, structural scan composition, per-rule firing fixtures
with corrected twins and per-line suppressions, the committed-baseline
regression machinery, the trainer's check_mem seam (+ telemetry
static), and the analyzer calibrated against XLA's own
``memory_analysis()`` on the CPU backend.

The bad/suppressed fixtures live in THIS file on purpose: findings
attribute to real source lines via jaxpr source_info, so the
suppression tests exercise the same file-line mechanics users rely on.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import telemetry, trainer
from apex_tpu.lint import (analyze_entry_mem, builtin_entries,
                           check_entry_mem, compute_timeline,
                           load_peak_baseline, run_entries_mem,
                           verified_peak_bytes, write_peak_baseline)
from apex_tpu.lint import main as lint_main
from apex_tpu.lint.jaxpr_checks import EntrySpec
from apex_tpu.lint.report import apply_suppressions
from apex_tpu.lint.rules import MEM_RULE_IDS, RULES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh(n=1):
    return Mesh(np.asarray(jax.devices()[:n]), ("data",))


def mem_ids(fn, args, **kw):
    return sorted({f.rule_id for f in check_entry_mem(fn, args, **kw)})


def run_suppressions(fn, args, **kw):
    """check_entry_mem + the real file/line suppression machinery."""
    findings = check_entry_mem(fn, args, **kw)
    sources = {}
    for f in findings:
        if f.path not in sources and os.path.exists(f.path):
            with open(f.path, encoding="utf-8") as fh:
                sources[f.path] = fh.read().splitlines()
    return apply_suppressions(findings, sources)


def assert_all_suppressed(rule, fn, args, **kw):
    """Every finding (one or more — a rule can name several buffers on
    the same source line) must be ``rule`` and must be suppressed."""
    active, suppressed = run_suppressions(fn, args, **kw)
    assert [f.rule_id for f in active] == []
    assert suppressed and {f.rule_id for f in suppressed} == {rule}


# ---------------------------------------------------------------------------
# the liveness engine: a hand-derived synthetic timeline
# ---------------------------------------------------------------------------

def _synth(x):
    return jnp.sum(jnp.tanh(x @ x.T))


def test_timeline_synthetic_exact():
    """f32[8,8] -> transpose, dot_general, tanh, reduce_sum: four
    equations whose per-equation live bytes are derivable by hand.

    buffers: input 256 B [-1, 4]; transpose temp 256 B [0, 1];
    dot temp 256 B [1, 2]; tanh temp 256 B [2, 3]; scalar output
    4 B [3, 4].  live = input + whatever overlaps each equation."""
    x = jnp.ones((8, 8), jnp.float32)
    tl = compute_timeline(jax.make_jaxpr(_synth)(x), (x,))
    assert tl.n_eqns == 4
    assert tl.live_bytes == [512, 768, 768, 516]
    assert tl.peak_bytes == 768
    assert tl.peak_index == 1                 # dot: input + transpose + out
    got = sorted((b.kind, b.nbytes, b.birth, b.death) for b in tl.buffers)
    assert got == sorted([("input", 256, -1, 4),
                          ("temp", 256, 0, 1),
                          ("temp", 256, 1, 2),
                          ("temp", 256, 2, 3),
                          ("output", 4, 3, 4)])
    assert tl.input_bytes == 256 and tl.output_bytes == 4
    # peak residents are named largest-first
    assert tl.peak_residents[0][1] == 256
    assert len(tl.peak_residents) == 3


def test_timeline_matches_naive_recompute():
    """The O(buffers+eqns) interval diff-sum equals a naive
    O(buffers*eqns) per-equation recount on a realistic step."""
    def step(s, b):
        g = jax.grad(lambda p: jnp.mean(jnp.tanh(b @ p) ** 2))(s)
        return s - 0.1 * g
    s = jnp.ones((64, 64), jnp.float32)
    b = jnp.ones((16, 64), jnp.float32)
    tl = compute_timeline(jax.make_jaxpr(step)(s, b), (s, b),
                          donate_argnums=(0,))
    for i in range(tl.n_eqns):
        naive = sum(buf.nbytes for buf in tl.buffers
                    if buf.birth <= i <= buf.death)
        assert tl.live_bytes[i] == naive + tl.extra_bytes[i], i
    assert tl.peak_bytes == max(tl.live_bytes)
    assert tl.live_bytes[tl.peak_index] == tl.peak_bytes


def test_donation_delta_equals_state_bytes():
    """Cleanly-donated state is ONE buffer: peak(undonated) -
    peak(donated) is exactly the state's byte size when the peak sits
    at the update equation."""
    s = jnp.ones((256, 256), jnp.float32)         # 262144 bytes

    def upd(s):
        return s - 0.1

    p0 = verified_peak_bytes(upd, (s,))
    p1 = verified_peak_bytes(upd, (s,), donate_argnums=(0,))
    assert p0 - p1 == s.nbytes == 262144
    tl = compute_timeline(jax.make_jaxpr(upd)(s), (s,),
                          donate_argnums=(0,))
    assert tl.donated_pairs == [(0, 0)] and tl.donation_copies == []
    [buf] = [b for b in tl.buffers if b.kind == "input"]
    assert "(donated)" in buf.name and buf.death == tl.n_eqns


def test_donation_late_read_forces_copy():
    """A donated arg read AFTER its aliased output is produced cannot
    share the buffer (XLA copies): modeled as two buffers, so donation
    buys nothing."""
    s = jnp.ones((256, 256), jnp.float32)
    b = jnp.ones((8, 256), jnp.float32)

    def late(s, batch):
        new = s - 0.1 * batch.sum()
        aux = jnp.sum(s * new)        # reads s after new exists
        return new, aux

    tl = compute_timeline(jax.make_jaxpr(late)(s, b), (s, b),
                          donate_argnums=(0,))
    assert tl.donation_copies == [0] and tl.donated_pairs == []
    assert verified_peak_bytes(late, (s, b), donate_argnums=(0,)) == \
        verified_peak_bytes(late, (s, b))


def test_scan_composition_is_structural_not_multiplicative():
    """A scan body is analyzed ONCE; its interior working set does not
    scale with trip count — only the stacked xs/ys buffers (priced by
    their OUTER avals) do."""
    def scanned(c, xs):
        def body(c, x):
            h = jnp.tanh(c @ c.T)
            return c + 0.1 * (h @ x), jnp.sum(h)
        return jax.lax.scan(body, c, xs)

    c = jnp.ones((64, 64), jnp.float32)
    runs = {}
    for L in (8, 16):
        xs = jnp.ones((L, 64, 64), jnp.float32)
        tl = compute_timeline(jax.make_jaxpr(scanned)(c, xs), (c, xs))
        [si] = [i for i, e in enumerate(tl.body.eqns)
                if e.primitive.name == "scan"]
        runs[L] = (tl.peak_bytes, tl.extra_bytes[si], xs.nbytes)
    # interior extra identical across trip counts
    assert runs[8][1] == runs[16][1] > 0
    # peak grows by exactly the stacked xs + stacked ys (f32 scalar/step)
    assert runs[16][0] - runs[8][0] == (runs[16][2] - runs[8][2]) + 8 * 4


# ---------------------------------------------------------------------------
# APX301: peak exceeds device HBM capacity
# ---------------------------------------------------------------------------

def _sup301(x):
    return jnp.sum(jnp.tanh(x @ x.T))  # apexlint: disable=APX301 -- test fixture


def test_apx301_capacity_fires_and_names_residents():
    x = jnp.ones((8, 8), jnp.float32)
    rep = analyze_entry_mem(_synth, (x,), capacity_bytes=512)
    assert [f.rule_id for f in rep.findings] == ["APX301"]
    msg = rep.findings[0].message
    assert "exceed device HBM capacity" in msg and "residents" in msg
    assert rep.peak_bytes == 768
    # fits: silent
    assert check_entry_mem(_synth, (x,), capacity_bytes=1 << 30) == []


def test_apx301_suppression():
    x = jnp.ones((8, 8), jnp.float32)
    assert_all_suppressed("APX301", _sup301, (x,), capacity_bytes=512)


def test_mem_report_to_json_shape():
    x = jnp.ones((8, 8), jnp.float32)
    rep = analyze_entry_mem(_synth, (x,), name="synth",
                            capacity_bytes=512)
    doc = rep.to_json()
    assert doc["entry"] == "synth" and doc["peak_bytes"] == 768
    assert doc["capacity_bytes"] == 512.0 and doc["peak_index"] == 1
    assert doc["findings"] == ["APX301"]
    assert all(r["bytes"] > 0 for r in doc["peak_residents"])


# ---------------------------------------------------------------------------
# APX302: declared carried state, updated but not donated
# ---------------------------------------------------------------------------

def _state_step(s, b):
    g = jax.grad(lambda p: jnp.mean((b @ p) ** 2))(s)
    return s - 0.1 * g


def test_apx302_undonated_state_fires_donated_twin_passes():
    s = jnp.ones((512, 512), jnp.float32)         # 1 MiB = the floor
    b = jnp.ones((8, 512), jnp.float32)
    assert mem_ids(_state_step, (s, b), state_argnums=(0,)) == ["APX302"]
    [f] = check_entry_mem(_state_step, (s, b), state_argnums=(0,))
    assert "NOT donated" in f.message and "double-buffer" in f.message
    # donated twin: silent
    assert mem_ids(_state_step, (s, b), state_argnums=(0,),
                   donate_argnums=(0,)) == []
    # not declared as state: silent (grads aval-match params everywhere;
    # only an explicit declaration arms the rule)
    assert mem_ids(_state_step, (s, b)) == []


def test_apx302_small_state_below_floor_is_silent():
    s = jnp.ones((64, 64), jnp.float32)           # 16 KiB << 1 MiB
    b = jnp.ones((8, 64), jnp.float32)
    assert mem_ids(_state_step, (s, b), state_argnums=(0,)) == []


# ---------------------------------------------------------------------------
# APX303: large activation live into the late backward
# ---------------------------------------------------------------------------

def _loss3(p, x):
    h1 = jnp.tanh(x @ p)
    h2 = jnp.tanh(h1 @ p)
    h3 = jnp.tanh(h2 @ p)
    return jnp.mean(h3 ** 2)


def _bad303(p, x):
    return jax.grad(_loss3)(p, x)


def _good303(p, x):
    return jax.grad(jax.checkpoint(_loss3))(p, x)


def _sup303(p, x):
    return jax.grad(lambda p: jnp.mean(jnp.tanh(jnp.tanh(jnp.tanh(x @ p) @ p) @ p) ** 2))(p)  # apexlint: disable=APX303 -- test fixture


def test_apx303_long_lived_activation_fires_remat_twin_passes(monkeypatch):
    monkeypatch.setenv("APEX_TPU_LINT_MEM_ACT_BYTES", "4096")
    p = jnp.ones((64, 64), jnp.float32)
    x = jnp.ones((32, 64), jnp.float32)
    assert mem_ids(_bad303, (p, x)) == ["APX303"]
    msgs = [f.message for f in check_entry_mem(_bad303, (p, x))]
    assert any("stays live into the late backward" in m for m in msgs)
    # remat twin: activations are recomputed, nothing spans the step
    assert mem_ids(_good303, (p, x)) == []


def test_apx303_default_threshold_spares_small_activations():
    p = jnp.ones((64, 64), jnp.float32)
    x = jnp.ones((32, 64), jnp.float32)
    assert mem_ids(_bad303, (p, x)) == []         # 8 KiB << 8 MiB default


def test_apx303_suppression(monkeypatch):
    monkeypatch.setenv("APEX_TPU_LINT_MEM_ACT_BYTES", "4096")
    p = jnp.ones((48, 48), jnp.float32)
    x = jnp.ones((32, 48), jnp.float32)
    assert_all_suppressed("APX303", _sup303, (p, x))


# ---------------------------------------------------------------------------
# APX304: all_gather result parked across the step
# ---------------------------------------------------------------------------

def _parked(x):
    g = jax.lax.all_gather(x, "data")
    y = x
    for _ in range(12):
        y = y + 1.0
    return jnp.sum(g) + jnp.sum(y)


def _prompt304(x):
    g = jax.lax.all_gather(x, "data")
    t = jnp.sum(g)                                # consumed immediately
    y = x
    for _ in range(12):
        y = y + 1.0
    return t + jnp.sum(y)


def _sup304(x):
    g = jax.lax.all_gather(x, "data")  # apexlint: disable=APX304 -- test fixture
    y = x
    for _ in range(12):
        y = y + 1.0
    return jnp.sum(g) + jnp.sum(y)


def _gmap(fn):
    return jax.shard_map(fn, mesh=_mesh(), in_specs=(P("data"),),
                         out_specs=P(), check_vma=False)


def test_apx304_parked_gather_fires_prompt_consumer_passes():
    x = jnp.ones((512, 512), jnp.float32)         # gather >= 1 MiB floor
    assert mem_ids(_gmap(_parked), (x,)) == ["APX304"]
    [f] = check_entry_mem(_gmap(_parked), (x,))
    assert "full-parameter materialization" in f.message
    assert mem_ids(_gmap(_prompt304), (x,)) == []


def test_apx304_small_gather_is_silent():
    x = jnp.ones((16, 16), jnp.float32)           # 1 KiB << 1 MiB floor
    assert mem_ids(_gmap(_parked), (x,)) == []


def test_apx304_suppression():
    x = jnp.ones((512, 512), jnp.float32)
    assert_all_suppressed("APX304", _gmap(_sup304), (x,))


# ---------------------------------------------------------------------------
# APX305: scan carry rebuilt through concat/pad
# ---------------------------------------------------------------------------

def _bad305(c, xs):
    def body(c, x):
        c2 = jnp.concatenate([c[:, 1:], x[:, None]], axis=1)
        return c2, jnp.sum(c2)
    return jax.lax.scan(body, c, xs)


def _good305(buf, xs):
    def body(state, x):
        buf, i = state
        buf = jax.lax.dynamic_update_slice(buf, x[None, :], (i, 0))
        return (buf, i + 1), jnp.sum(x)
    return jax.lax.scan(body, (buf, jnp.int32(0)), xs)


def _sup305(c, xs):
    def body(c, x):
        c2 = jnp.concatenate([c[:, 1:], x[:, None]], axis=1)
        return c2, jnp.sum(c2)
    return jax.lax.scan(body, c, xs)  # apexlint: disable=APX305 -- test fixture


def test_apx305_concat_carry_fires_preallocated_twin_passes():
    xs = jnp.ones((4, 16), jnp.float32)
    assert mem_ids(_bad305, (jnp.ones((16, 8), jnp.float32), xs)) \
        == ["APX305"]
    [f] = check_entry_mem(_bad305, (jnp.ones((16, 8), jnp.float32), xs))
    assert "concatenate" in f.message and "O(steps^2)" in f.message
    assert mem_ids(_good305, (jnp.zeros((4, 16), jnp.float32), xs)) == []


def test_apx305_suppression():
    xs = jnp.ones((4, 16), jnp.float32)
    assert_all_suppressed("APX305", _sup305,
                          (jnp.ones((16, 8), jnp.float32), xs))


# ---------------------------------------------------------------------------
# APX306: host callback moving real bytes inside the step
# ---------------------------------------------------------------------------

def _bad306(x):
    y = jax.pure_callback(lambda a: np.asarray(a),
                          jax.ShapeDtypeStruct(x.shape, x.dtype), x)
    return jnp.sum(y)


def _good306(x):
    t = jax.pure_callback(lambda a: np.asarray(a),
                          jax.ShapeDtypeStruct((), x.dtype), jnp.sum(x))
    return jnp.sum(x) + t


def _sup306(x):
    y = jax.pure_callback(lambda a: np.asarray(a), jax.ShapeDtypeStruct(x.shape, x.dtype), x)  # apexlint: disable=APX306 -- test fixture
    return jnp.sum(y)


def test_apx306_bulk_callback_fires_scalar_tap_passes():
    x = jnp.ones((256, 256), jnp.float32)         # 256 KiB each way
    assert mem_ids(_bad306, (x,)) == ["APX306"]
    [f] = check_entry_mem(_bad306, (x,))
    assert "pure_callback" in f.message and "PCIe" in f.message
    assert mem_ids(_good306, (x,)) == []          # scalar tap: silent


def test_apx306_threshold_is_env_overridable(monkeypatch):
    x = jnp.ones((16,), jnp.float32)              # 64 B payload
    assert mem_ids(_bad306, (x,)) == []
    monkeypatch.setenv("APEX_TPU_LINT_MEM_HOST_BYTES", "1")
    assert mem_ids(_bad306, (x,)) == ["APX306"]


def test_apx306_suppression():
    x = jnp.ones((256, 256), jnp.float32)
    assert_all_suppressed("APX306", _sup306, (x,))


# ---------------------------------------------------------------------------
# APX307: peak regression vs the committed baseline
# ---------------------------------------------------------------------------

def test_apx307_regression_fires_within_tolerance_silent():
    x = jnp.ones((8, 8), jnp.float32)
    peak = analyze_entry_mem(_synth, (x,)).peak_bytes
    [f] = check_entry_mem(_synth, (x,), baseline_bytes=peak / 2)
    assert f.rule_id == "APX307"
    assert "+100.0%" in f.message and "re-baseline deliberately" in f.message
    # equal and within-tolerance (default 5%) baselines: silent
    assert check_entry_mem(_synth, (x,), baseline_bytes=peak) == []
    assert check_entry_mem(_synth, (x,), baseline_bytes=peak / 1.04) == []


def test_baseline_roundtrip_and_version_guard(tmp_path):
    p = str(tmp_path / "mem_baseline.json")
    write_peak_baseline(p, {"b": 2, "a": 1})
    assert load_peak_baseline(p) == {"a": 1, "b": 2}
    import json
    with open(p) as fh:
        doc = json.load(fh)
    assert doc["version"] == 1 and "tolerance_pct" in doc
    doc["version"] = 99
    with open(p, "w") as fh:
        json.dump(doc, fh)
    with pytest.raises(ValueError, match="unsupported version"):
        load_peak_baseline(p)


def _synth_spec(name="synth_entry"):
    x = jnp.ones((8, 8), jnp.float32)
    return EntrySpec(name=name, path=__file__,
                     make=lambda: (_synth, (x,)))


def test_run_entries_mem_baseline_arms_apx307_per_entry():
    spec = _synth_spec()
    peak = verified_peak_bytes(_synth, (jnp.ones((8, 8), jnp.float32),))
    assert run_entries_mem([spec], baseline={spec.name: peak}) == []
    regressed = run_entries_mem([spec],
                                baseline={spec.name: int(peak / 1.2)})
    assert [f.rule_id for f in regressed] == ["APX307"]
    assert f"[entry {spec.name}]" in regressed[0].message


def test_run_entries_mem_build_failure_is_loud():
    def boom():
        raise RuntimeError("no such model")
    spec = EntrySpec(name="broken", path=__file__, make=boom)
    with pytest.raises(RuntimeError, match="broken"):
        run_entries_mem([spec])


# ---------------------------------------------------------------------------
# rules / catalog / entry sweep
# ---------------------------------------------------------------------------

def test_mem_rule_ids_registered():
    assert MEM_RULE_IDS == tuple(f"APX30{i}" for i in range(1, 8))
    for rid in MEM_RULE_IDS:
        assert RULES[rid].severity in ("error", "warning")
    assert RULES["APX301"].severity == "error"
    assert RULES["APX305"].severity == "error"
    assert RULES["APX307"].severity == "error"


def test_cli_list_rules_includes_mem(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in MEM_RULE_IDS:
        assert rid in out


def test_cli_update_mem_baseline_requires_file(capsys):
    assert lint_main(["--update-mem-baseline"]) == 2


@pytest.mark.apexlint
def test_builtin_entry_sweep_mem_clean_vs_committed_baseline():
    """Every registered entry verifies clean, INCLUDING against the
    committed peak baseline — the same contract the CI gate enforces
    (and whose doctored-baseline inverse the gate checks)."""
    baseline = load_peak_baseline(os.path.join(REPO, "ci",
                                               "mem_baseline.json"))
    assert set(baseline), "committed baseline must not be empty"
    assert run_entries_mem(baseline=baseline) == []


# ---------------------------------------------------------------------------
# calibration: the analyzer vs XLA's own memory_analysis (CPU backend)
# ---------------------------------------------------------------------------

@pytest.mark.apexlint
@pytest.mark.parametrize("entry", ["gpt_tiny_fwd_loss@O5",
                                   "ddp_syncbn_grads"])
def test_analyzer_within_band_of_xla_memory_analysis(entry):
    """The timeline's peak must land within [0.6x, 1.5x] of XLA's
    compiled buffer-assignment total (args + outputs + temps - aliased)
    for the GPT and ResNet entries. The analyzer prices jaxpr-level
    live ranges, XLA prices post-fusion allocations, so exact equality
    is not expected — measured ratios on this backend are ~0.83 (GPT)
    and ~0.88 (ResNet); the band catches an analyzer that drifts into
    fantasy in either direction."""
    spec = next(s for s in builtin_entries() if s.name == entry)
    fn, args = spec.make()
    stats = jax.jit(fn).lower(*args).compile().memory_analysis()
    if stats is None:
        pytest.skip("backend provides no memory_analysis()")
    total = (stats.argument_size_in_bytes + stats.output_size_in_bytes
             + stats.temp_size_in_bytes - stats.alias_size_in_bytes)
    if total <= 0:
        pytest.skip("backend reports zero-size memory_analysis()")
    mine = verified_peak_bytes(fn, args,
                               donate_argnums=spec.donate_argnums)
    ratio = mine / total
    assert 0.6 <= ratio <= 1.5, (entry, mine, total, ratio)


# ---------------------------------------------------------------------------
# the trainer seam
# ---------------------------------------------------------------------------

def _tstate():
    return {"w": jnp.ones((64, 8), jnp.float32)}


def _tstep(state, batch):
    loss, g = jax.value_and_grad(
        lambda p: jnp.mean((batch @ p["w"]) ** 2))(state)
    return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, state, g), loss


def test_trainer_check_mem_seam():
    tr = trainer.build(_tstep, _tstate(), jnp.ones((4, 64)))
    assert tr.check_mem() == []                  # donated by default
    # a 1-KiB capacity makes ANY step overflow -> APX301
    assert [f.rule_id for f in tr.check_mem(capacity_bytes=1024)] \
        == ["APX301"]
    # and a halved baseline is a regression -> APX307
    ids = [f.rule_id for f in tr.check_mem(
        capacity_bytes=1 << 40,
        baseline_bytes=verified_peak_bytes(
            tr.traced_fn, tr.example_args,
            donate_argnums=tr.donate_argnums) / 2)]
    assert ids == ["APX307"]


def test_trainer_check_mem_emits_telemetry_static():
    telemetry.enable()
    try:
        telemetry.get_collector().clear()
        tr = trainer.build(_tstep, _tstate(), jnp.ones((4, 64)))
        assert tr.check_mem() == []
        evs = [e for e in telemetry.get_collector().snapshot()
               if e.name == "trainer/peak_hbm_bytes"]
        assert len(evs) == 1 and evs[0].value > 0
        assert evs[0].meta["findings"] == []
        assert evs[0].meta["peak_bytes"] == evs[0].value
    finally:
        telemetry.disable()


def test_trainer_constructed_directly_raises_on_mem_seam():
    tr = trainer.Trainer(fn=lambda s, b: (s, 0.0),
                         traced_fn=lambda s, b: (s, 0.0),
                         config=trainer.TrainerConfig(), donation=None)
    with pytest.raises(ValueError, match="example_args"):
        tr.check_mem()
