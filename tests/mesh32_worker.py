"""32-device virtual-mesh worker (VERDICT r3 next #5): BASELINE row 4
names "BERT-large FusedLAMB, 32 chips", but nothing anywhere in the repo
had ever instantiated a mesh wider than 8. This builds the 32-device
topology (XLA-CPU, ``--xla_force_host_platform_device_count=32`` set by
the spawning test) and runs the BERT-shaped ZeRO-LAMB step on it — the
real bert-large LEAF STRUCTURE (24 layers, every param type: QKV/output
projections, LayerNorm scales/biases, MLP, embeddings) at small dims —
comparing a 3-step trajectory against the dense FusedLAMB on one device.

The analog of the reference's 32-GPU scale-out config for
DistributedFusedLAMB (apex/contrib/optimizers/distributed_fused_lamb.py:
7-607) at the only scale this environment can build.

Run: spawned by tests/test_mesh32.py; prints one ``RESULT {json}`` line.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    # standalone process (no conftest): jax version shims for the
    # `from jax import shard_map` import below
    import apex_tpu._compat  # noqa: F401
    import jax.numpy as jnp
    import numpy as np
    from jax import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import models, optimizers, parallel
    from apex_tpu.contrib.optimizers import DistributedFusedLAMB

    world = 32
    assert len(jax.devices()) == world, (
        f"expected {world} virtual devices, got {len(jax.devices())}")

    # bert-large leaf structure (24 layers), small dims
    model = models.BertEncoder(vocab_size=512, max_len=64, hidden=64,
                               layers=24, heads=4, mlp_dim=128)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, 16), jnp.int32))["params"]
    leaves = jax.tree_util.tree_leaves(params)
    n_leaves = len(leaves)
    n_params = sum(int(np.prod(l.shape)) for l in leaves)

    key = jax.random.PRNGKey(1)
    grads_seq = []
    for _ in range(3):
        key, k = jax.random.split(key)
        ks = jax.random.split(k, n_leaves)
        flat = [jax.random.normal(kk, l.shape, jnp.float32) * 0.1
                for kk, l in zip(ks, leaves)]
        grads_seq.append(jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params), flat))

    mesh = parallel.make_mesh(axis_names=("data",))
    zopt = DistributedFusedLAMB(lr=1e-3, weight_decay=0.01,
                                max_grad_norm=1.0, axis_name="data",
                                shard_count=world)
    state = zopt.init(params)
    specs = zopt.state_pspec()

    step = jax.jit(shard_map(
        lambda g, p, s: zopt.step(g, p, s), mesh=mesh,
        in_specs=(P(), P(), specs), out_specs=(P(), specs),
        check_vma=False))
    state = jax.device_put(state, jax.tree_util.tree_map(
        lambda sp: NamedSharding(mesh, sp), specs))
    got = params
    for g in grads_seq:
        got, state = step(g, got, state)

    dense = optimizers.FusedLAMB(lr=1e-3, weight_decay=0.01,
                                 max_grad_norm=1.0)
    dstate = dense.init(params)
    want = params
    for g in grads_seq:
        want, dstate = dense.step(g, want, dstate)

    max_diff = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32)
                              - b.astype(jnp.float32))))
        for a, b in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(want)))

    print("RESULT " + json.dumps({
        "world": world,
        "n_leaves": n_leaves,
        "n_params": n_params,
        "max_diff_vs_dense": max_diff,
        # state really is 32-way sharded: per-device shard rows
        "master_global_elems": int(state.master.shape[0]),
        "master_shard_elems": int(
            state.master.addressable_shards[0].data.size),
        "num_shards": len(state.master.addressable_shards),
    }), flush=True)


if __name__ == "__main__":
    main()
