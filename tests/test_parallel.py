"""Distributed-layer tests on the 8-device virtual CPU mesh — ports of the
reference tests/distributed/ suite:

 * DDP gradient math under any bucketing config (ddp_race_condition_test.py's
   invariant: analytically-known grads identical for every config — on TPU the
   stream-race class is gone, but the "same math for any bucketing/fp32/
   predivide config" property is the surviving contract, SURVEY.md §5.2)
 * amp master params identical across ranks after DDP steps
   (amp_master_params test)
 * SyncBatchNorm parity vs single-device BN over the full batch
   (synced_batchnorm two_gpu_unit_test)
 * Sub-group stat sync (test_groups.py)
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu import amp, optimizers, parallel

NDEV = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == NDEV, "conftest must set 8 CPU devices"
    return parallel.make_mesh(axis_names=("data",))


def test_allreduce_gradients_math(mesh):
    # grads = rank+1 on each device -> mean = (1+...+8)/8 = 4.5
    def body():
        r = jax.lax.axis_index("data").astype(jnp.float32)
        grads = {"w": jnp.full((1000,), r + 1.0),
                 "b": jnp.full((7,), (r + 1.0) * 2.0)}
        return parallel.allreduce_gradients(grads, "data")

    out = jax.jit(shard_map(body, mesh=mesh, in_specs=(),
                            out_specs={"w": P(), "b": P()},
                            check_vma=False))()
    np.testing.assert_allclose(np.asarray(out["w"]), 4.5, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out["b"]), 9.0, rtol=1e-6)


@pytest.mark.parametrize("kw", [
    dict(),
    dict(message_size=128),
    dict(allreduce_always_fp32=True),
    dict(gradient_predivide_factor=4.0),
    dict(message_size=333, allreduce_always_fp32=True,
         gradient_predivide_factor=2.0),
])
def test_allreduce_config_invariance(mesh, kw):
    # The ddp_race_condition contract: every config gives the same averaged
    # gradient (within fp32 tolerance).
    def body():
        r = jax.lax.axis_index("data").astype(jnp.float32)
        grads = {"w": (jnp.arange(2048, dtype=jnp.float32) * 1e-3 + r)}
        return parallel.allreduce_gradients(grads, "data", **kw)

    out = jax.jit(shard_map(body, mesh=mesh, in_specs=(),
                            out_specs={"w": P()}, check_vma=False))()
    expected = np.arange(2048, dtype=np.float32) * 1e-3 + 3.5
    np.testing.assert_allclose(np.asarray(out["w"]), expected,
                               rtol=1e-5, atol=1e-6)


def test_allreduce_bf16_grads(mesh):
    def body():
        grads = {"w": jnp.full((512,), 2.0, jnp.bfloat16)}
        return parallel.allreduce_gradients(grads, "data",
                                            allreduce_always_fp32=True)
    out = jax.jit(shard_map(body, mesh=mesh, in_specs=(),
                            out_specs={"w": P()}, check_vma=False))()
    assert out["w"].dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out["w"], np.float32), 2.0)


def test_ddp_train_step_end_to_end(mesh):
    # linear regression, data sharded over 8 devices; params replicated;
    # verifies grads sync (loss decreases & params identical across devices)
    w_true = jnp.asarray([1.5, -2.0, 0.5, 3.0])
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (64, 4))
    y = x @ w_true

    def loss_fn(params, batch):
        bx, by = batch
        pred = bx @ params["w"]
        return jnp.mean((pred - by) ** 2)

    opt = optimizers.FusedSGD(lr=0.1)
    params = {"w": jnp.zeros((4,))}
    opt_state = opt.init(params)
    step = parallel.ddp_train_step(loss_fn, opt, mesh, "data", donate=False)

    losses = []
    for _ in range(60):
        params, opt_state, loss = step(params, opt_state, (x, y))
        losses.append(float(loss))
    assert losses[-1] < 1e-3, losses[-5:]
    np.testing.assert_allclose(np.asarray(params["w"]), np.asarray(w_true),
                               atol=1e-2)


def test_amp_ddp_master_params_consistent(mesh):
    # amp_master_params test analog: after amp O5 + DDP steps, master (fp32)
    # and model (bf16) params satisfy model == master.astype(bf16), and are
    # identical on every device (replicated by construction, verified
    # numerically through the jit boundary).
    def loss_fn(apply_fn, params, batch):
        bx, by = batch
        pred = apply_fn(params, bx)
        return jnp.mean((pred - by) ** 2)

    w0 = jax.random.normal(jax.random.PRNGKey(1), (8, 1), jnp.float32)
    apply_fn = lambda p, x: x @ p["w"]
    aopt = amp.AmpOptimizer(optimizers.FusedSGD(lr=0.05), amp.resolve("O5"))
    params = amp.cast_model({"w": w0}, "O5")
    st = aopt.init(params)

    x = jax.random.normal(jax.random.PRNGKey(2), (32, 8))
    y = jnp.sum(x, axis=1, keepdims=True)

    def per_device(params, st, batch):
        def scaled_loss(p):
            return aopt.scale_loss(loss_fn(apply_fn, p, batch), st)
        grads = jax.grad(scaled_loss)(params)
        grads = parallel.allreduce_gradients(grads, "data")
        new_p, new_st, info = aopt.step(grads, params, st)
        return new_p, new_st

    step = jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), P(), P("data")),
        out_specs=(P(), P()), check_vma=False))

    for _ in range(5):
        params, st = step(params, st, (x, y))

    assert params["w"].dtype == jnp.bfloat16
    assert st.master["w"].dtype == jnp.float32
    np.testing.assert_array_equal(
        np.asarray(params["w"], np.float32),
        np.asarray(st.master["w"].astype(jnp.bfloat16), np.float32))


# ---------------------------------------------------------------------------
# SyncBatchNorm
# ---------------------------------------------------------------------------

def test_syncbn_matches_global_bn(mesh):
    # stats over the sharded batch must equal single-device BN on full batch
    feats = 16
    x = jax.random.normal(jax.random.PRNGKey(3), (NDEV * 4, 10, feats))

    bn = parallel.SyncBatchNorm(features=feats, axis_name="data",
                                momentum=0.1)
    variables = bn.init(jax.random.PRNGKey(4), x[:4],
                        use_running_average=False)

    def per_device(vars_, xs):
        y, updates = bn.apply(vars_, xs, use_running_average=False,
                              mutable=["batch_stats"])
        return y, updates["batch_stats"]

    y, stats = jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), P("data")),
        out_specs=(P("data"), P()), check_vma=False))(variables, x)

    # reference: plain normalization over the FULL batch
    x32 = np.asarray(x, np.float64)
    mean = x32.mean(axis=(0, 1))
    var = x32.var(axis=(0, 1))
    want = (x32 - mean) / np.sqrt(var + bn.eps)
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-4)

    # running stats: (1-m)*init + m*batch, unbiased var
    n = x32.shape[0] * x32.shape[1]
    unbiased = var * n / (n - 1)
    np.testing.assert_allclose(np.asarray(stats["mean"]), 0.1 * mean,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(stats["var"]),
                               0.9 * 1.0 + 0.1 * unbiased,
                               rtol=1e-4, atol=1e-5)


def test_syncbn_subgroups(mesh):
    # test_groups.py analog: groups of 4 sync only within their subgroup
    feats = 4
    groups = parallel.create_syncbn_process_group(NDEV, 4)
    assert groups == [[0, 1, 2, 3], [4, 5, 6, 7]]

    bn = parallel.SyncBatchNorm(features=feats, axis_name="data",
                                axis_index_groups=groups, affine=False)

    # device r sees constant input r -> within-group mean differs per group
    def per_device(vars_):
        r = jax.lax.axis_index("data").astype(jnp.float32)
        xs = jnp.full((2, 3, feats), r)
        y, _ = bn.apply(vars_, xs, use_running_average=False,
                        mutable=["batch_stats"])
        # return the group-mean-subtracted value of this device
        return y[:1]

    variables = bn.init(jax.random.PRNGKey(5), jnp.ones((2, 3, feats)),
                        use_running_average=False)
    y = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(P(),),
        out_specs=P("data"), check_vma=False))(variables)
    y = np.asarray(y)  # (8, 3, feats): per-device normalized constants
    # group 0 devices have inputs 0..3 (mean 1.5), group 1: 4..7 (mean 5.5)
    # normalized value for device r: (r - group_mean)/sqrt(group_var+eps)
    gvar = np.var([0, 1, 2, 3])
    for r in range(8):
        gmean = 1.5 if r < 4 else 5.5
        want = (r - gmean) / np.sqrt(gvar + bn.eps)
        np.testing.assert_allclose(y[r], want, rtol=1e-5, atol=1e-5)


def test_syncbn_eval_uses_running_stats(mesh):
    feats = 8
    bn = parallel.SyncBatchNorm(features=feats, axis_name=None)
    x = jax.random.normal(jax.random.PRNGKey(6), (4, feats))
    variables = bn.init(jax.random.PRNGKey(7), x, use_running_average=False)
    y = bn.apply(variables, x, use_running_average=True)
    # fresh stats: mean 0, var 1 -> identity modulo eps and affine init
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-3,
                               atol=1e-3)


# ---------------------------------------------------------------------------
# LARC
# ---------------------------------------------------------------------------

def test_larc_clip_reduces_effective_lr():
    params = {"w": jnp.full((64,), 1e-3)}  # tiny params, big grads
    grads = {"w": jnp.full((64,), 10.0)}
    inner = optimizers.FusedSGD(lr=1.0)
    larc = parallel.LARC(inner, trust_coefficient=0.02)
    st = larc.init(params)
    new_p, _ = larc.step(grads, params, st)
    raw_step = 1.0 * 10.0
    actual_step = float(params["w"][0] - new_p["w"][0])
    assert actual_step < raw_step * 1e-3  # trust ratio clipped the update


def test_larc_keeps_small_updates():
    params = {"w": jnp.full((64,), 10.0)}
    grads = {"w": jnp.full((64,), 1e-4)}
    inner = optimizers.FusedSGD(lr=0.1)
    larc = parallel.LARC(inner, trust_coefficient=0.02)
    st = larc.init(params)
    new_p, _ = larc.step(grads, params, st)
    # ratio = 0.02*|p|/|g| huge -> clip to 1/lr*lr = full update.
    # loose rtol: the update (1e-5) is near the fp32 ulp of params (~1e-6)
    np.testing.assert_allclose(float(params["w"][0] - new_p["w"][0]),
                               0.1 * 1e-4, rtol=0.1)


def test_hybrid_mesh_cpu_fallback():
    """hybrid_mesh lays out (dcn..., ici...) axes; on CPU it falls back to a
    row-major reshape but the axis structure must hold."""
    from apex_tpu.parallel import hybrid_mesh

    mesh = hybrid_mesh(ici_axes=(4,), dcn_axes=(2,),
                       axis_names=("data", "model"))
    assert mesh.shape == {"data": 2, "model": 4}
    # collectives run over both axes
    import jax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    def f(x):
        return jax.lax.psum(x, "model")

    out = jax.jit(shard_map(
        f, mesh=mesh, in_specs=P("data", "model"),
        out_specs=P("data", None), check_vma=False))(
            jnp.ones((2, 4), jnp.float32))
    np.testing.assert_allclose(np.asarray(out), 4.0)


def test_init_distributed_single_process_noop():
    from apex_tpu.parallel import init_distributed

    init_distributed()  # must not raise or hang on single-process CPU


# ---------------------------------------------------------------------------
# groupbn (contrib BatchNorm2d_NHWC over bn_group subgroups)
# ---------------------------------------------------------------------------

def test_groupbn_local_matches_syncbn():
    from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
    from apex_tpu.parallel import SyncBatchNorm

    x = jax.random.normal(jax.random.PRNGKey(40), (4, 8, 8, 32))
    gbn = BatchNorm2d_NHWC(planes=32)
    sbn = SyncBatchNorm(features=32, axis_name=None)
    vg = gbn.init(jax.random.PRNGKey(41), x, use_running_average=False)
    vs = {"params": vg["params"]["bn"],
          "batch_stats": vg["batch_stats"]["bn"]}
    yg, _ = gbn.apply(vg, x, use_running_average=False,
                      mutable=["batch_stats"])
    ys, _ = sbn.apply(vs, x, use_running_average=False,
                      mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(yg), np.asarray(ys), rtol=1e-5,
                               atol=1e-5)


def test_groupbn_addrelu():
    from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

    x = jax.random.normal(jax.random.PRNGKey(42), (2, 4, 4, 16))
    res = jax.random.normal(jax.random.PRNGKey(43), (2, 4, 4, 16))
    m = BatchNorm2d_NHWC(planes=16, fuse_relu=True)
    v = m.init(jax.random.PRNGKey(44), x, res,
               use_running_average=False)
    y, _ = m.apply(v, x, res, use_running_average=False,
                   mutable=["batch_stats"])
    assert (np.asarray(y) >= 0).all()  # relu applied after bn+residual
    # zero residual + no relu reference
    m2 = BatchNorm2d_NHWC(planes=16, fuse_relu=False)
    y2, _ = m2.apply(v, x, jnp.zeros_like(res),
                     use_running_average=False, mutable=["batch_stats"])
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(jax.nn.relu(y2 + res)), rtol=1e-5,
        atol=1e-5)


def test_groupbn_subgroup_stats(mesh):
    """bn_group=4 on an 8-device axis: stats sync within each group of 4
    only — devices in different groups see different statistics (the
    reference's CUDA-IPC bn_group semantics via axis_index_groups)."""
    from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

    m = BatchNorm2d_NHWC(planes=8, bn_group=4, world_size=8,
                         axis_name="data")
    # per-device distinct data: group {0..3} gets mean 0, group {4..7}
    # mean 10 -> normalized outputs must differ across groups but whiten
    # within each group
    x = jnp.concatenate([
        jax.random.normal(jax.random.PRNGKey(45), (4, 2, 2, 2, 8)),
        jax.random.normal(jax.random.PRNGKey(46), (4, 2, 2, 2, 8)) + 10.0,
    ])  # (8 devices, local batch 2, 2, 2, 8)
    v = m.init(jax.random.PRNGKey(47), x[0], use_running_average=False)

    def per_device(x_):
        y, _ = m.apply(v, x_[0], use_running_average=False,
                       mutable=["batch_stats"])
        return y[None]

    y = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(P("data"),),
        out_specs=P("data"), check_vma=False))(x)
    y = np.asarray(y)
    # both groups whitened to ~zero mean despite the +10 shift
    assert abs(y[:4].mean()) < 0.05
    assert abs(y[4:].mean()) < 0.05


def test_convert_syncbn_apply_compact_model(mesh):
    """convert_syncbn_apply: apply-time interception reaches BatchNorms
    inside @nn.compact models (which convert_syncbn_model cannot rewrite).
    With stats synced, an 8-device run on batch shards must match the
    dense run on the global batch."""
    import flax.linen as nn

    class CompactNet(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(16)(x)
            x = nn.BatchNorm(use_running_average=False, momentum=0.9,
                             name="bn")(x)
            return nn.relu(x)

    model = CompactNet()
    x = jax.random.normal(jax.random.PRNGKey(70), (16, 8))
    variables = model.init(jax.random.PRNGKey(71), x)

    want, want_upd = model.apply(variables, x, mutable=["batch_stats"])

    def per_device(x_):
        with parallel.convert_syncbn_apply("data"):
            y, upd = model.apply(variables, x_, mutable=["batch_stats"])
        return y, upd["batch_stats"]

    got, got_bs = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(P("data"),),
        out_specs=(P("data"), P()), check_vma=False))(x)

    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        got_bs, want_upd["batch_stats"])


def test_convert_syncbn_apply_noop_outside_mesh():
    """Without the context, the same compact model keeps local (unsynced)
    stats — the interceptor is strictly opt-in."""
    import flax.linen as nn

    class CompactNet(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.BatchNorm(use_running_average=False, name="bn")(x)

    model = CompactNet()
    x = jax.random.normal(jax.random.PRNGKey(72), (8, 4))
    variables = model.init(jax.random.PRNGKey(73), x)
    y, _ = model.apply(variables, x, mutable=["batch_stats"])
    assert np.isfinite(np.asarray(y)).all()


def test_allreduce_leaf_grouped_structure(mesh):
    """With message_size set, the lowered program must contain one psum per
    leaf-grouped bucket (plus per-chunk psums for oversize single leaves) —
    NOT one whole-tree concat feeding every collective, which would be a
    dataflow barrier between backward and communication (VERDICT r2 #1)."""
    import re
    grads = {"a": jnp.ones((300,)), "b": jnp.ones((50,)),
             "c": jnp.ones((128,)), "d": jnp.ones((9,)),
             "e": jnp.ones((77,))}
    out_specs = jax.tree_util.tree_map(lambda _: P(), grads)

    def lower(msg):
        def body(g):
            return parallel.allreduce_gradients(g, "data", message_size=msg)
        return jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P(),), out_specs=out_specs,
            check_vma=False)).lower(grads).as_text()

    # capacity 128: a(300) alone -> 3 chunked psums; [b], [c], [d,e] -> 3
    assert len(re.findall(r'"stablehlo.all_reduce"', lower(128))) == 6
    # unbounded: single whole-tree (per-dtype) bucket, one psum
    assert len(re.findall(r'"stablehlo.all_reduce"', lower(0))) == 1
