"""Serving observability (PR 18): req/* lifecycle emission + offline
join, expired-in-flight accounting, canonical shed reasons, the SLO
engine + CLI exit contract (0 met / 3 violated / 1 bad input), the
two-process clock-join on serve streams (committed fixture, known
+1.75s skew), the pyprof timeline's requests pid, the summarize serve
section, and the disabled-telemetry jaxpr pin."""

import itertools
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import telemetry, trace
from apex_tpu.serve import metrics, slo
from apex_tpu.serve.admission import AdmissionController
from apex_tpu.serve.cli import main as serve_main
from apex_tpu.serve.engine import Engine
from apex_tpu.serve.loader import LoadedModel
from apex_tpu.serve.model import ModelSpec
from apex_tpu.telemetry import merge, requests

VOCAB = 61
FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures")
P0 = os.path.join(FIXDIR, "serve_run-p0.jsonl")
P1 = os.path.join(FIXDIR, "serve_run-p1.jsonl")


@pytest.fixture(scope="module")
def loaded():
    spec = ModelSpec(vocab=VOCAB, layers=2, embed_dim=32, heads=4,
                     max_seq=64)
    lm = spec.model()
    params = lm.init(jax.random.PRNGKey(3),
                     jnp.zeros((1, 8), jnp.int32))["params"]
    return LoadedModel(model=lm, params=params, spec=spec, step=0,
                       generation=0, manifest={}, directory="<mem>")


def _prompts(n, length=6):
    return [[int(t) for t in np.asarray(jax.random.randint(
        jax.random.PRNGKey(i), (length,), 0, VOCAB))] for i in range(n)]


def _capture_run(loaded, n=4, max_new=3, **eng_kw):
    """Run n requests through a fresh engine with telemetry+trace
    captured; returns (requests, event dicts)."""
    with telemetry.capture() as col:
        trace.enable()
        try:
            eng = Engine(loaded, max_batch=2, page=8, max_context=16,
                         max_prompt=8, in_flight=1, **eng_kw)
            reqs = [eng.request(p, max_new) for p in _prompts(n)]
            eng.run(reqs)
        finally:
            trace.disable()
    return reqs, [e.to_dict() for e in col.drain()]


# ---------------------------------------------------------------------------
# request lifecycle events + offline join
# ---------------------------------------------------------------------------

class TestLifecycle:
    def test_every_request_joins_to_a_done_record(self, loaded):
        reqs, events = _capture_run(loaded, n=4, max_new=3)
        recs = requests.join(events)
        assert len(recs) == 4
        assert {r["rid"] for r in recs} == {r.rid for r in reqs}
        for rec in recs:
            assert rec["state"] == "done"
            assert rec["tokens"] == 3
            assert rec["slot"] in (0, 1)
            assert rec["prompt_len"] == 6 and rec["max_new"] == 3
            # every phase measured, and they compose into e2e
            for k in ("queued_s", "prefill_s", "decode_s", "e2e_s",
                      "ttft_s", "tpot_s"):
                assert rec[k] is not None and rec[k] >= 0.0, k
            total = rec["queued_s"] + rec["prefill_s"] + rec["decode_s"]
            assert total == pytest.approx(rec["e2e_s"], abs=0.05)
            assert rec["ttft_s"] == pytest.approx(
                rec["queued_s"] + rec["prefill_s"], abs=0.05)

    def test_req_events_ride_kind_req(self, loaded):
        """kind="req" keeps lifecycle events invisible to the existing
        point/counter/span aggregations (summarize tables stay clean)."""
        _, events = _capture_run(loaded, n=2)
        req_rows = [e for e in events
                    if str(e["name"]).startswith("req/")
                    and e["kind"] == "req"]
        assert {e["name"] for e in req_rows} >= {
            metrics.REQ_SUBMIT, metrics.REQ_ADMIT, metrics.REQ_FIRST,
            metrics.REQ_FINISH}
        for e in req_rows:
            assert e["meta"]["rid"] == int(e["value"])

    def test_phase_spans_carry_rid_and_slot(self, loaded):
        _, events = _capture_run(loaded, n=2)
        rows = trace.span_rows(events)
        fams = {r["family"] for r in rows}
        assert {metrics.REQ_QUEUED, metrics.REQ_PREFILL,
                metrics.REQ_DECODE, metrics.ENGINE_STEP,
                metrics.TTFT} <= fams
        for r in rows:
            if r["family"].startswith("req/") or r["family"] in (
                    metrics.TTFT, metrics.INTERTOKEN):
                assert r["rid"] is not None
        # engine-step spans carry the engine sequence as step
        steps = [r["step"] for r in rows
                 if r["family"] == metrics.ENGINE_STEP]
        assert steps and all(s is not None for s in steps)

    def test_kv_and_slot_gauges_emitted(self, loaded):
        _, events = _capture_run(loaded, n=3)
        names = {e["name"] for e in events}
        assert {metrics.KV_USED_PAGES, metrics.KV_FREE_PAGES,
                metrics.KV_OCCUPANCY, metrics.KV_FRAGMENTATION,
                metrics.SLOT_ACTIVE, metrics.PREFILL_TOKENS,
                metrics.DECODE_TOKENS} <= names
        occ = [e["value"] for e in events
               if e["name"] == metrics.KV_OCCUPANCY]
        assert all(0.0 <= v <= 1.0 for v in occ)


class TestExpiredInflight:
    def test_mid_decode_expiry_is_counted_separately(self, loaded):
        """A request whose deadline passes AFTER admission (1s fake-
        clock decode steps, 0.5s deadline screened too late) ends
        ``expired``, joins as such, and rides serve/expired_inflight —
        not the queued-expiry counter."""
        t = itertools.count()
        clock = lambda: float(next(t))                  # noqa: E731
        with telemetry.capture() as col:
            trace.enable()
            try:
                adm = AdmissionController(max_queue=4, clock=clock)
                eng = Engine(loaded, max_batch=1, page=8, max_context=16,
                             max_prompt=8, in_flight=1, admission=adm,
                             clock=clock)
                req = eng.request(_prompts(1)[0], 4, deadline_s=2.5)
                eng.run([req])
            finally:
                trace.disable()
        events = [e.to_dict() for e in col.drain()]
        assert req.state == "expired"
        assert eng.expired_inflight == [req]
        names = [e["name"] for e in events]
        assert metrics.EXPIRED_INFLIGHT in names
        assert metrics.REQ_EXPIRE_INFLIGHT in names
        rec = requests.join(events)[0]
        assert rec["state"] == "expired"
        assert rec["in_deadline"] is False
        assert rec["tokens"] >= 1          # wasted decode work recorded
        # its pages were reclaimed: the engine can serve another request
        nxt = eng.request(_prompts(2)[1], 2)
        eng.run([nxt])
        assert nxt.state == "done"


class TestShedReasons:
    def test_reasons_are_canonical(self):
        assert metrics.SHED_REASONS == ("queue_full", "deadline",
                                        "too_large")
        for r in metrics.SHED_REASONS:
            assert metrics.check_reason(r) == r
        with pytest.raises(ValueError, match="unknown shed reason"):
            metrics.check_reason("overloaded")

    def test_admission_emits_canonical_reject_events(self, loaded):
        with telemetry.capture() as col:
            eng = Engine(loaded, max_batch=1, page=8, max_context=16,
                         max_prompt=8, in_flight=1,
                         admission=AdmissionController(max_queue=1))
            reqs = [eng.request(p, 2) for p in _prompts(4)]
            eng.run(reqs)
        events = [e.to_dict() for e in col.drain()]
        rejects = [e for e in events if e["name"] == metrics.REQ_REJECT]
        assert rejects
        for e in rejects:
            assert e["meta"]["reason"] in metrics.SHED_REASONS
        recs = requests.join(events)
        assert {r["reason"] for r in recs
                if r["state"] == "rejected"} == {"queue_full"}


# ---------------------------------------------------------------------------
# the disabled-telemetry contract
# ---------------------------------------------------------------------------

class TestDisabledInert:
    def test_decode_jaxpr_identical_with_and_without_telemetry(
            self, loaded):
        """All observability is host-side Python around the jit: the
        decode program must be jaxpr-identical whether telemetry is on
        or off (the disabled path costs only no-op calls)."""
        def decode_jaxpr():
            eng = Engine(loaded, max_batch=2, page=8, max_context=16,
                         max_prompt=8, in_flight=1)
            active = jnp.zeros((eng.max_batch,), bool).at[0].set(True)
            return str(jax.make_jaxpr(eng._decode_fn)(
                eng.params, eng.pool, eng.last_tokens,
                jnp.asarray(eng.block_tables),
                jnp.asarray(eng.positions), active))

        telemetry.disable()
        off = decode_jaxpr()
        with telemetry.capture():
            trace.enable()
            try:
                on = decode_jaxpr()
            finally:
                trace.disable()
        assert on == off

    def test_disabled_run_emits_nothing(self, loaded):
        telemetry.disable()
        col = telemetry.get_collector()
        col.drain()                                # flush leftovers
        eng = Engine(loaded, max_batch=1, page=8, max_context=16,
                     max_prompt=8, in_flight=1)
        reqs = [eng.request(p, 2) for p in _prompts(2)]
        eng.run(reqs)
        assert all(r.state == "done" for r in reqs)
        assert col.drain() == []


# ---------------------------------------------------------------------------
# SLO engine + CLI exit contract
# ---------------------------------------------------------------------------

def _rec(rid, state="done", **kw):
    base = {"rid": rid, "process": 0, "state": state, "prompt_len": 4,
            "max_new": 3, "deadline_s": 1.0, "ts_submit": 100.0 + rid,
            "queued_s": 0.01, "prefill_s": 0.02, "decode_s": 0.03,
            "e2e_s": 0.06, "ttft_s": 0.03, "tpot_s": 0.015, "tokens": 3,
            "slot": 0, "reason": None, "in_deadline": True}
    if state == "rejected":
        base.update({k: None for k in
                     ("prefill_s", "decode_s", "e2e_s", "ttft_s",
                      "tpot_s", "slot", "in_deadline")},
                    tokens=0, reason="queue_full", queued_s=0.0)
    base.update(kw)
    return base


class TestSLO:
    def test_spec_rejects_unknown_keys(self):
        with pytest.raises(ValueError, match="unknown SLO spec keys"):
            slo.SLOSpec.from_dict({"ttft_p95_ms": 1.0})

    def test_met_and_violated(self):
        recs = [_rec(i) for i in range(8)]
        ok = slo.evaluate(recs, slo.SLOSpec(ttft_p99_ms=100.0,
                                            goodput_min=0.9))
        assert ok["met"] and not ok["violators"]
        bad = slo.evaluate(recs, slo.SLOSpec(ttft_p99_ms=1.0))
        assert not bad["met"]
        t = bad["targets"][0]
        assert t["attainment"] == 0.0 and t["burn"]["full"] > 1.0
        assert len(bad["violators"]) == 5           # top-5 of 8

    def test_shed_requests_are_misses_not_exemptions(self):
        recs = [_rec(i) for i in range(4)] + \
               [_rec(10 + i, state="rejected") for i in range(4)]
        rep = slo.evaluate(recs, slo.SLOSpec(e2e_p99_ms=100.0,
                                             goodput_min=0.9))
        t = rep["targets"][0]
        assert t["unbounded"] and not t["met"]      # p99 rides the inf tail
        assert t["attainment"] == 0.5
        assert rep["goodput"]["observed"] == 0.5
        assert not rep["met"]
        v = rep["violators"][0]
        assert v["state"] == "rejected" and v["reason"] == "queue_full"
        assert v["e2e_ms"] is None and v["queued_ms"] is not None

    def test_burn_rate_flags_late_run_regression(self):
        """Healthy early run, all misses in the last quarter: the
        quarter-window burn must exceed the full-window burn."""
        recs = [_rec(i, ts_submit=100.0 + i) for i in range(12)] + \
               [_rec(20 + i, ts_submit=115.0 + i * 0.1, e2e_s=5.0)
                for i in range(4)]
        rep = slo.evaluate(recs, slo.SLOSpec(e2e_p50_ms=100.0))
        burn = rep["targets"][0]["burn"]
        assert burn["quarter"] > burn["full"]

    def test_cli_exit_contract(self, tmp_path, capsys):
        jsonl = str(tmp_path / "run.jsonl")
        with telemetry.capture() as col:
            for i in range(3):
                metrics.req_event(metrics.REQ_SUBMIT, i,
                                  meta={"prompt_len": 4, "max_new": 2})
                metrics.req_event(
                    metrics.REQ_FINISH, i,
                    meta={"slot": 0, "tokens": 2, "queued_s": 0.001,
                          "prefill_s": 0.002, "decode_s": 0.003,
                          "ttft_s": 0.003, "e2e_s": 0.006,
                          "in_deadline": True})
            telemetry.write_jsonl(jsonl, col.drain())
        assert serve_main(["slo", jsonl, "--e2e-p99-ms", "1000"]) == 0
        out = capsys.readouterr().out
        assert "MET" in out
        assert serve_main(["slo", jsonl, "--e2e-p99-ms", "0.0001"]) == 3
        assert "VIOLATED" in capsys.readouterr().out
        # --json prints the full report dict
        assert serve_main(["slo", jsonl, "--e2e-p99-ms", "1000",
                           "--json"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["met"] and rep["requests"] == 3

    def test_cli_bad_input_is_exit_1(self, tmp_path, capsys):
        empty = str(tmp_path / "empty.jsonl")
        with telemetry.capture() as col:
            telemetry.record("train/loss", 1.0)
            telemetry.write_jsonl(empty, col.drain())
        # no req/* events -> 1; no targets -> 1; unreadable spec -> 1
        assert serve_main(["slo", empty, "--ttft-p99-ms", "5"]) == 1
        assert serve_main(["slo", empty]) == 1
        assert serve_main(["slo", empty, "--spec",
                           str(tmp_path / "missing.json")]) == 1
        assert serve_main(["slo", str(tmp_path / "nope.jsonl"),
                           "--ttft-p99-ms", "5"]) == 1
        capsys.readouterr()


# ---------------------------------------------------------------------------
# two-process clock join (committed fixture, +1.75s known skew)
# ---------------------------------------------------------------------------

class TestMergeServeStreams:
    def test_offset_recovered_from_serve_step_anchors(self):
        merged, offsets = merge.merge_files([P0, P1])
        assert offsets["p0"]["offset_s"] == 0.0
        assert offsets["p1"]["offset_s"] == pytest.approx(1.75, abs=1e-6)
        assert offsets["p1"]["anchors"] == 5

    def test_ttft_spans_align_after_merge(self):
        """Both processes saw rid 0's first token at the same true
        time; after the median-offset join their serve/ttft span ends
        coincide on the reference clock."""
        merged, _ = merge.merge_files([P0, P1])
        rows = trace.span_rows(merged)
        ends = {}
        for r in rows:
            if r["family"] == "serve/ttft" and r["rid"] == 0:
                ends[r["process"]] = r["ts"]
        assert set(ends) == {"p0", "p1"}
        assert ends["p0"] == pytest.approx(ends["p1"], abs=1e-6)

    def test_req_records_keep_per_process_rid_spaces(self):
        merged, _ = merge.merge_files([P0, P1])
        recs = requests.join(merged)
        assert len(recs) == 4                   # rid 0+1 in BOTH streams
        key = {(r["process"], r["rid"]): r["state"] for r in recs}
        assert key[("p0", 0)] == "done"
        assert key[("p0", 1)] == "rejected"
        assert key[("p1", 0)] == "done"
        assert key[("p1", 1)] == "expired"

    def test_summarize_renders_merged_serve_section(self):
        merged, _ = merge.merge_files([P0, P1])
        s = telemetry.summarize(merged)
        srv = s["serve"]
        assert srv["completed"] == 2
        assert srv["expired_inflight"] == 1
        assert srv["rejected_by_reason"] == {"queue_full": 1}
        assert srv["requests"]["by_state"] == {
            "done": 2, "rejected": 1, "expired": 1}
        assert s["ledger"]["serve"]["tokens_wasted"] == 1
        text = telemetry.format_summary(s)
        assert "serving (apex_tpu.serve):" in text
        assert "goodput ledger:" in text


# ---------------------------------------------------------------------------
# pyprof timeline: the requests pid
# ---------------------------------------------------------------------------

class TestTimelineRequestLanes:
    def test_request_lanes_render_under_their_own_pid(self):
        from apex_tpu.pyprof.parse import load_trace
        from apex_tpu.pyprof.timeline import build_timeline
        from apex_tpu.telemetry.export import load
        device = load_trace(os.path.join(FIXDIR, "synthetic_trace.json"))
        rows = trace.span_rows(load(P1))
        tl = build_timeline(device, rows)
        evs = tl["traceEvents"]
        pids = {e["args"]["name"] for e in evs
                if e.get("ph") == "M" and e["name"] == "process_name"}
        assert pids == {"host", "device", "requests"}
        req_x = [e for e in evs
                 if e.get("ph") == "X" and e["pid"] == 3]
        assert req_x and tl["metadata"]["request_spans"] == len(req_x)
        names = {e["name"] for e in req_x}
        assert {"r0/queued", "r0/prefill", "r0/decode"} <= names
        lanes = {e["args"]["name"] for e in evs
                 if e.get("ph") == "M" and e["name"] == "thread_name"
                 and e["pid"] == 3}
        assert {"slot 0", "slot 1"} <= lanes
        # valid Chrome trace: every X event JSON-serializes with ts/dur
        for e in req_x:
            assert e["dur"] >= 0 and e["ts"] >= 0
        json.dumps(tl)

    def test_no_requests_pid_without_req_spans(self):
        from apex_tpu.pyprof.parse import load_trace
        from apex_tpu.pyprof.timeline import build_timeline
        from apex_tpu.telemetry.export import load
        device = load_trace(os.path.join(FIXDIR, "synthetic_trace.json"))
        rows = [r for r in trace.span_rows(load(P0))
                if not r["family"].startswith("req/")]
        tl = build_timeline(device, rows)
        assert not any(e.get("pid") == 3 for e in tl["traceEvents"])
