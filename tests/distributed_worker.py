"""Multi-process worker for test_multiprocess.py (VERDICT r3 #5) — the
analog of the reference's launched distributed tests
(tests/distributed/DDP/ddp_race_condition_test.py, run via torch.launch).

Run as ONE of N processes (COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID in
the env, the apex_tpu.parallel.multiproc contract), each owning
``--local-devices`` virtual CPU devices. Executes one DDP allreduce + one
ZeRO (DistributedFusedAdam) step over the GLOBAL mesh and prints a JSON
line of replicated scalars; the parent compares them across processes and
against a single-process run of the same program.

Everything runs from REPLICATED inputs: the ZeRO state shard is built
in-graph (each device slices its own rows out of the deterministic global
init), so the test needs no multi-controller device_put of sharded arrays.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_step(opt, world):
    """step(params) -> dict of replicated scalars, to run under shard_map
    over axis 'data' of size ``world``. Pure function of params."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import parallel
    from apex_tpu.contrib.optimizers.zero import ZeroState

    def per_device(params):
        r = jax.lax.axis_index("data")
        # deterministic per-device grads (rank-dependent, like the
        # reference race test's rank-scaled gradients)
        grads = jax.tree_util.tree_map(
            lambda p: jnp.sin(p.astype(jnp.float32))
            * (1.0 + r.astype(jnp.float32) / 10.0), params)

        # DDP path: leaf-grouped bucketed allreduce
        avg = parallel.allreduce_gradients(grads, "data", message_size=128)

        # ZeRO path: build this device's state shard in-graph from the
        # deterministic global init, then run one sharded Adam step
        spec = opt._spec_cache or opt._pack(params)
        st = opt.init(params)                     # global layout (traced)
        k = spec["padded"] // world
        sl = lambda a: jax.lax.dynamic_slice_in_dim(a, r * k, k)
        st_local = ZeroState(step=st.step, master=sl(st.master),
                             exp_avg=sl(st.exp_avg),
                             exp_avg_sq=sl(st.exp_avg_sq))
        new_p, new_st = opt.step(avg, params, st_local)

        flat = jnp.concatenate(
            [l.astype(jnp.float32).reshape(-1)
             for l in jax.tree_util.tree_leaves(new_p)])
        return {
            "grad_norm": jnp.sqrt(sum(
                jnp.sum(g.astype(jnp.float32) ** 2)
                for g in jax.tree_util.tree_leaves(avg))),
            "param_sum": jnp.sum(flat),
            "param_norm": jnp.sqrt(jnp.sum(flat * flat)),
            "master_psum": jax.lax.psum(jnp.sum(new_st.master), "data"),
        }

    return per_device


def make_params():
    import jax
    import jax.numpy as jnp
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    return {"w1": jax.random.normal(ks[0], (37, 11)),
            "w2": jax.random.normal(ks[1], (501,)),
            "b": jax.random.normal(ks[2], (3,))}


def run(expected_devices: int):
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu import parallel
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    world = expected_devices
    assert len(jax.devices()) == world, (
        f"global device count {len(jax.devices())} != {world}")
    mesh = parallel.make_mesh(axis_names=("data",))
    opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                               axis_name="data", shard_count=world,
                               chunk_elements=128)
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x), make_params())

    fn = jax.jit(shard_map(
        build_step(opt, world), mesh=mesh, in_specs=(P(),),
        out_specs={k: P() for k in ("grad_norm", "param_sum",
                                    "param_norm", "master_psum")},
        check_vma=False))
    out = fn(params)
    return {k: float(v) for k, v in out.items()}


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")

    from apex_tpu.parallel import multiproc
    multiproc.initialize_distributed()

    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--global-devices", type=int, required=True)
    args = ap.parse_args()

    out = run(args.global_devices)
    out["process_id"] = int(os.environ.get("PROCESS_ID", "0"))
    out["local_devices"] = len(jax.local_devices())
    print("RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
