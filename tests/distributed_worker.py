"""Multi-process worker for test_multiprocess.py (VERDICT r3 #5) — the
analog of the reference's launched distributed tests
(tests/distributed/DDP/ddp_race_condition_test.py, run via torch.launch).

Run as ONE of N processes (COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID in
the env, the apex_tpu.parallel.multiproc contract), each owning
``--local-devices`` virtual CPU devices. Executes one DDP allreduce + one
ZeRO (DistributedFusedAdam) step over the GLOBAL mesh and prints a JSON
line of replicated scalars; the parent compares them across processes and
against a single-process run of the same program.

Everything runs from REPLICATED inputs: the ZeRO state shard is built
in-graph (each device slices its own rows out of the deterministic global
init), so the test needs no multi-controller device_put of sharded arrays.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# standalone process: conftest never runs here, so install the jax
# version shims (jax.shard_map / lax.axis_size on older releases) before
# any `from jax import shard_map` below
import apex_tpu._compat  # noqa: E402,F401


def local_zero_state(opt, params, rank, n_shards):
    """Build device ``rank``'s local ZeRO state shard IN-GRAPH from the
    deterministic global init — the single owner of the
    shard-interleaved-layout slicing used by both the 1-D and hybrid
    steps (no multi-controller device_put of sharded arrays needed)."""
    import jax

    from apex_tpu.contrib.optimizers.zero import ZeroState

    spec = opt._spec_cache or opt._pack(params)
    st = opt.init(params)                         # global layout (traced)
    k = spec["padded"] // n_shards
    sl = lambda a: jax.lax.dynamic_slice_in_dim(a, rank * k, k)
    return ZeroState(step=st.step, master=sl(st.master),
                     exp_avg=sl(st.exp_avg),
                     exp_avg_sq=sl(st.exp_avg_sq))


def build_step(opt, world):
    """step(params) -> dict of replicated scalars, to run under shard_map
    over axis 'data' of size ``world``. Pure function of params."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import parallel

    def per_device(params):
        r = jax.lax.axis_index("data")
        # deterministic per-device grads (rank-dependent, like the
        # reference race test's rank-scaled gradients)
        grads = jax.tree_util.tree_map(
            lambda p: jnp.sin(p.astype(jnp.float32))
            * (1.0 + r.astype(jnp.float32) / 10.0), params)

        # DDP path: leaf-grouped bucketed allreduce
        avg = parallel.allreduce_gradients(grads, "data", message_size=128)

        # ZeRO path: one sharded Adam step from the in-graph local shard
        st_local = local_zero_state(opt, params, r, world)
        new_p, new_st = opt.step(avg, params, st_local)

        flat = jnp.concatenate(
            [l.astype(jnp.float32).reshape(-1)
             for l in jax.tree_util.tree_leaves(new_p)])
        return {
            "grad_norm": jnp.sqrt(sum(
                jnp.sum(g.astype(jnp.float32) ** 2)
                for g in jax.tree_util.tree_leaves(avg))),
            "param_sum": jnp.sum(flat),
            "param_norm": jnp.sqrt(jnp.sum(flat * flat)),
            "master_psum": jax.lax.psum(jnp.sum(new_st.master), "data"),
        }

    return per_device


def make_params():
    import jax
    import jax.numpy as jnp
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    return {"w1": jax.random.normal(ks[0], (37, 11)),
            "w2": jax.random.normal(ks[1], (501,)),
            "b": jax.random.normal(ks[2], (3,))}


def run(expected_devices: int):
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu import parallel
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    world = expected_devices
    assert len(jax.devices()) == world, (
        f"global device count {len(jax.devices())} != {world}")
    mesh = parallel.make_mesh(axis_names=("data",))
    opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                               axis_name="data", shard_count=world,
                               chunk_elements=128)
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x), make_params())

    fn = jax.jit(shard_map(
        build_step(opt, world), mesh=mesh, in_specs=(P(),),
        out_specs={k: P() for k in ("grad_norm", "param_sum",
                                    "param_norm", "master_psum")},
        check_vma=False))
    out = fn(params)
    res = {k: float(v) for k, v in out.items()}
    res.update(run_hybrid(world))
    res.update(run_moe(world))
    return res


def run_moe(world: int):
    """Expert parallelism ACROSS process boundaries: a ('expert',) axis
    of the full global size, so the MoE token all_to_all (the one
    collective the DDP/ZeRO parts don't exercise) crosses the two
    processes in the 2x4 launch. One EP forward + synced grad step from
    replicated inputs (local shards sliced in-graph, same trick as
    local_zero_state); returns replicated scalars keyed moe_*, plus a
    moe_dense_diff anchor against the single-device dense module."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu import parallel
    from apex_tpu.parallel.expert_parallel import (
        MoEMLP, lm_moe_pspecs, moe_sync_grads)

    m = 16
    b, s = world, 4
    x = jax.random.normal(jax.random.PRNGKey(8), (b, s, m))
    dense = MoEMLP(embed_dim=m, num_experts=world, mlp_ratio=2,
                   num_selected=2, capacity_factor=float(world))
    params = dense.init(jax.random.PRNGKey(9), x)["params"]
    specs = lm_moe_pspecs(params, axis="expert")
    local = dense.clone(axis_name="expert", expert_parallel_size=world)
    mesh = parallel.make_mesh((world,), ("expert",))

    def per_device(p, xx):
        rank = jax.lax.axis_index("expert")
        p_loc = jax.tree_util.tree_map(
            lambda leaf, sp: (jax.lax.dynamic_slice_in_dim(
                leaf, rank * (leaf.shape[0] // world),
                leaf.shape[0] // world, axis=0)
                if len(sp) > 0 and sp[0] is not None else leaf),
            p, specs)
        x_loc = jax.lax.dynamic_slice_in_dim(xx, rank, 1, axis=0)

        def loss(pl):
            y, _ = local.apply({"params": pl}, x_loc,
                               mutable=["intermediates"])
            return jnp.sum(y * y), y

        (val, y), g = jax.value_and_grad(loss, has_aux=True)(p_loc)
        g = moe_sync_grads(g, specs, "expert")
        return {
            "moe_out_sum": jax.lax.psum(jnp.sum(y), "expert"),
            "moe_out_norm": jnp.sqrt(jax.lax.psum(val, "expert")),
            "moe_router_gnorm": jnp.sqrt(jnp.sum(
                g["router"].astype(jnp.float32) ** 2)),
        }

    fn = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(P(), P()),
        out_specs={k: P() for k in ("moe_out_sum", "moe_out_norm",
                                    "moe_router_gnorm")},
        check_vma=False))
    out = fn(params, x)
    res = {k: float(v) for k, v in out.items()}

    y_ref, _ = dense.apply({"params": params}, x,
                           mutable=["intermediates"])
    res["moe_dense_diff"] = float(jnp.abs(
        jnp.sum(y_ref) - out["moe_out_sum"]))
    return res


def run_hybrid(world: int):
    """The dwu_group_size two-level scheme ACROSS process boundaries
    (VERDICT r3 next #5): a ('group', 'data') = (2, world//2) mesh where
    state shards over 'data' (within a process in the 2x4 launch) and the
    cross-group allreduce rides 'group' — which SPANS the two processes
    (devices 0-3 are process 0, 4-7 process 1). The analog of the
    reference's intra-node reduce-scatter + inter-node allreduce
    (apex/contrib/optimizers/distributed_fused_adam.py:251-289).

    Returns replicated scalars after one hybrid ZeRO step, keyed hyb_*;
    must equal the same program single-process AND (numerically) the
    dense FusedAdam step — the latter is asserted by the parent test via
    the committed hyb_dense_diff value."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu import optimizers, parallel
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    shards = world // 2
    mesh2 = parallel.make_mesh((2, shards), ("group", "data"))
    opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                               axis_name="data", shard_count=shards,
                               group_axis="group", chunk_elements=128)
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x), make_params())

    def per_device(p):
        g_rank = jax.lax.axis_index("group")
        d_rank = jax.lax.axis_index("data")
        # rank-dependent grads over the FULL 2-D world; the two-level
        # reduction must average all of them
        r = g_rank * shards + d_rank
        grads = jax.tree_util.tree_map(
            lambda x: jnp.sin(x.astype(jnp.float32))
            * (1.0 + r.astype(jnp.float32) / 10.0), p)
        st_local = local_zero_state(opt, p, d_rank, shards)
        new_p, new_st = opt.step(grads, p, st_local)
        flat = jnp.concatenate(
            [l.astype(jnp.float32).reshape(-1)
             for l in jax.tree_util.tree_leaves(new_p)])
        return {
            "hyb_param_sum": jnp.sum(flat),
            "hyb_param_norm": jnp.sqrt(jnp.sum(flat * flat)),
            "hyb_master_psum": jax.lax.psum(
                jax.lax.psum(jnp.sum(new_st.master), "data"), "group"),
        }

    fn = jax.jit(shard_map(
        per_device, mesh=mesh2, in_specs=(P(),),
        out_specs={k: P() for k in ("hyb_param_sum", "hyb_param_norm",
                                    "hyb_master_psum")},
        check_vma=False))
    out = fn(params)
    res = {k: float(v) for k, v in out.items()}

    # dense-parity anchor: the mean of the SAME rank-dependent grads fed
    # to a dense FusedAdam step (leaf-wise dense parity of the group_axis
    # form is separately covered single-process in test_param_groups)
    mean_scale = sum(1.0 + r / 10.0 for r in range(world)) / world
    mean_grads = jax.tree_util.tree_map(
        lambda x: jnp.sin(x.astype(jnp.float32)) * mean_scale, params)
    dense = optimizers.FusedAdam(lr=1e-2, weight_decay=0.01)
    want, _ = dense.step(mean_grads, params, dense.init(params))
    dense_flat = jnp.concatenate(
        [l.astype(jnp.float32).reshape(-1)
         for l in jax.tree_util.tree_leaves(want)])
    res["hyb_dense_diff"] = float(
        jnp.abs(jnp.sum(dense_flat) - out["hyb_param_sum"]))
    return res


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")

    from apex_tpu.parallel import multiproc
    multiproc.initialize_distributed()

    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--global-devices", type=int, required=True)
    args = ap.parse_args()

    out = run(args.global_devices)
    out["process_id"] = int(os.environ.get("PROCESS_ID", "0"))
    out["local_devices"] = len(jax.local_devices())
    print("RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
