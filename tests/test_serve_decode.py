"""Paged-decode correctness pins (ISSUE 17 tentpole).

The pin chain: ``serve.model.decode_step`` (paged, jnp backend) is
BITWISE equal to the dense-cache einsum decode path
(``TransformerLM(decode=True, decode_impl="einsum")``) at matched batch
shapes, per dtype, over T consecutive steps — and that dense decode
path is itself pinned against the full-context flash forward at 2e-4
(tests/test_gpt.py::test_decode_logits_match_full_forward). Here we
also pin paged vs the full forward directly at the same tolerance.

Matched batch shapes matter: XLA reduces a batch-1 and a batch-2
matmul in different orders on CPU, so the dense reference runs at the
SAME batch as the paged step (1-ulp differences otherwise — not a
correctness signal, just reduction order).

Plus: the Pallas kernel vs the jnp reference (interpret mode on CPU),
the dead-slot zero guard, and the backend-select contract."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.serve import decode, kvcache
from apex_tpu.serve.model import ModelSpec, decode_step, prefill

VOCAB, LAYERS, EMBED, HEADS, MAX_SEQ = 97, 2, 32, 4, 32
PAGE, PPS = 8, 4          # pages_per_slot: 4*8 = 32 token capacity
PLEN, STEPS = 8, 4        # prefill 8, then 4 pinned decode steps


@pytest.fixture(scope="module")
def setup():
    spec = ModelSpec(vocab=VOCAB, layers=LAYERS, embed_dim=EMBED,
                     heads=HEADS, max_seq=MAX_SEQ)
    lm = spec.model()
    toks1 = jax.random.randint(jax.random.PRNGKey(0), (1, PLEN + STEPS),
                               0, VOCAB)
    toks = jnp.concatenate([toks1, toks1], 0)       # batch 2, same seq
    params = lm.init(jax.random.PRNGKey(1), toks)["params"]
    return spec, params, toks


def _paged_prefill(spec, params, toks, dtype):
    """Prefill both slots of a batch-2 paged pool; returns (pool, bt)."""
    b = toks.shape[0]
    pool = kvcache.create_pool(layers=spec.layers, num_pages=b * PPS,
                               heads=spec.heads, page=PAGE,
                               head_dim=spec.head_dim, dtype=dtype)
    alloc = kvcache.PageAllocator(pool.num_pages)
    bt = np.full((b, PPS), pool.num_pages, np.int32)
    n = -(-(PLEN + STEPS) // PAGE)
    prompt = np.zeros((16,), np.int32)
    prompt[:PLEN] = np.asarray(toks[0, :PLEN])
    for s in range(b):
        bt[s, :n] = alloc.alloc(n)
        _, _, pool = prefill(params, spec, jnp.asarray(prompt),
                             jnp.int32(PLEN), pool, jnp.asarray(bt[s]))
    return pool, jnp.asarray(bt)


def _dense_reference(spec, params, toks):
    """Per-step last-token logits from the dense-cache einsum decode —
    the training stack's decode path, run at the SAME batch."""
    dec = spec.model(decode=True, decode_max_len=MAX_SEQ, dropout=0.0,
                     decode_impl="einsum")
    _, vs = dec.apply({"params": params}, toks[:, :PLEN],
                      mutable=["cache"])
    cache, out = vs["cache"], []
    for p in range(PLEN, PLEN + STEPS):
        logits, vs = dec.apply({"params": params, "cache": cache},
                               toks[:, p:p + 1], pos_offset=p,
                               mutable=["cache"])
        cache = vs["cache"]
        out.append(logits[:, 0].astype(jnp.float32))
    return out


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_paged_decode_bitwise_vs_dense_cache(setup, dtype):
    """T consecutive paged decode steps == the dense-cache decode,
    bit for bit, at matched batch shapes — per dtype."""
    spec, params, toks = setup
    if dtype == "bfloat16":
        params = amp.cast_model(
            params, amp.resolve("O5", keep_batchnorm_fp32=False))
    kv_dtype = jnp.result_type(
        params["tok_emb"]["embedding"].dtype,
        params["block_0"]["attn"]["in_proj"]["kernel"].dtype)
    pool, bt = _paged_prefill(spec, params, toks, kv_dtype)
    refs = _dense_reference(spec, params, toks)
    b = toks.shape[0]
    active = jnp.ones((b,), bool)
    for i, p in enumerate(range(PLEN, PLEN + STEPS)):
        tokens = jnp.full((b,), int(toks[0, p]), jnp.int32)
        positions = jnp.full((b,), p, jnp.int32)
        logits, pool = decode_step(params, spec, pool, tokens,
                                   positions, bt, active)
        assert logits.dtype == jnp.float32
        np.testing.assert_array_equal(
            np.asarray(logits), np.asarray(refs[i]),
            err_msg=f"paged decode diverged from the dense-cache "
                    f"decode at position {p} ({dtype})")


def test_paged_decode_close_to_full_forward(setup):
    """Paged last-token logits vs the full-context flash forward at the
    repo's decode tolerance (2e-4 — same pin as test_gpt's dense decode
    vs full forward)."""
    spec, params, toks = setup
    lm = spec.model()
    pool, bt = _paged_prefill(spec, params, toks, jnp.float32)
    b = toks.shape[0]
    active = jnp.ones((b,), bool)
    for p in range(PLEN, PLEN + STEPS):
        tokens = jnp.full((b,), int(toks[0, p]), jnp.int32)
        positions = jnp.full((b,), p, jnp.int32)
        logits, pool = decode_step(params, spec, pool, tokens,
                                   positions, bt, active)
        full = lm.apply({"params": params}, toks[:, :p + 1])
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full[:, -1], np.float32),
            rtol=2e-4, atol=2e-4)


class TestPagedAttentionKernel:
    """paged_decode_attention directly: jnp vs Pallas (interpret on
    CPU), ragged lengths, dead slots."""

    def _inputs(self, seq_lens):
        b, h, d, pps = len(seq_lens), 4, 64, 4
        num_pages = b * pps
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(k1, (b, h, 1, d), jnp.float32)
        kp = jax.random.normal(k2, (num_pages, h, 16, d), jnp.float32)
        vp = jax.random.normal(k3, (num_pages, h, 16, d), jnp.float32)
        bt = jnp.arange(num_pages, dtype=jnp.int32).reshape(b, pps)
        return q, kp, vp, bt, jnp.asarray(seq_lens, jnp.int32)

    def test_pallas_matches_jnp(self):
        q, kp, vp, bt, sl = self._inputs([1, 17, 64])
        ref = decode.paged_decode_attention(q, kp, vp, bt, sl)
        prev = decode.set_backend("pallas")
        try:
            out = decode.paged_decode_attention(q, kp, vp, bt, sl)
        finally:
            decode.set_backend(prev)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("backend", ["jnp", "pallas"])
    def test_dead_slot_is_finite(self, backend):
        """seq_len == 0 must produce finite output (the all-masked
        softmax is guarded), never NaN into the shared batch."""
        q, kp, vp, bt, sl = self._inputs([0, 17, 64])
        prev = decode.set_backend(backend)
        try:
            out = decode.paged_decode_attention(q, kp, vp, bt, sl)
        finally:
            decode.set_backend(prev)
        assert bool(jnp.all(jnp.isfinite(out[0])))
        if backend == "jnp":
            assert bool(jnp.all(out[0] == 0))

    def test_rejects_multi_token_q(self):
        q, kp, vp, bt, sl = self._inputs([4])
        with pytest.raises(ValueError, match="1-token step"):
            decode.paged_decode_attention(
                jnp.concatenate([q, q], axis=2), kp, vp, bt, sl)

    def test_rejects_mismatched_pool(self):
        q, kp, vp, bt, sl = self._inputs([4])
        with pytest.raises(ValueError, match="does not match"):
            decode.paged_decode_attention(q, kp[:, :2], vp[:, :2],
                                          bt, sl)


class TestBackendSelect:
    """The xentropy-style backend contract: set_backend override wins,
    env value second, 'auto' -> jnp, unknown values raise loudly."""

    def test_default_is_jnp(self):
        assert decode.backend() == "jnp"

    def test_set_backend_roundtrip(self):
        prev = decode.set_backend("pallas")
        try:
            assert decode.backend() == "pallas"
        finally:
            decode.set_backend(prev)
        assert decode.backend() == "jnp"

    def test_set_backend_rejects_unknown(self):
        with pytest.raises(ValueError, match="must be one of"):
            decode.set_backend("cuda")

    def test_env_value(self, monkeypatch):
        monkeypatch.setattr(decode, "_FORCE", "pallas")
        assert decode.backend() == "pallas"
        monkeypatch.setattr(decode, "_FORCE", "auto")
        assert decode.backend() == "jnp"

    def test_env_unknown_raises(self, monkeypatch):
        monkeypatch.setattr(decode, "_FORCE", "rocm")
        with pytest.raises(ValueError, match="APEX_TPU_SERVE_DECODE"):
            decode.backend()

    def test_native_shapes(self):
        assert decode.paged_native_shapes(16, 64)
        assert decode.paged_native_shapes(32, 128)
        assert not decode.paged_native_shapes(10, 64)
        assert not decode.paged_native_shapes(16, 100)
