"""``python -m apex_tpu.serve`` CLI contract: exit 0 on a healthy
bench run (one JSON row on stdout, progress on stderr), exit 2 on
usage errors, exit 1 on bad input (missing snapshot dir); plus the
serve/* telemetry arc into the summarize section."""

import json

import jax
import jax.numpy as jnp
import pytest

from apex_tpu import amp, optimizers
from apex_tpu.resilience.snapshot import SnapshotManager
from apex_tpu.serve.cli import main
from apex_tpu.serve.model import ModelSpec

MODEL_MD = {"vocab": 31, "layers": 1, "embed_dim": 16, "heads": 2,
            "max_seq": 32, "mlp_ratio": 4, "moe": False,
            "relative_bias": False, "alibi": False}


@pytest.fixture(autouse=True)
def _reset_telemetry():
    """The CLI enables telemetry/trace process-wide for --telemetry
    runs (normally the process exits right after); in-process tests
    must not leak that into the rest of the suite."""
    yield
    from apex_tpu import telemetry, trace
    telemetry.disable()
    trace.disable()
    telemetry.get_collector().drain()


@pytest.fixture(scope="module")
def snap_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("cli_snap")
    spec = ModelSpec.from_dict(MODEL_MD)
    model = spec.model()
    p = model.init(jax.random.PRNGKey(0),
                   jnp.zeros((1, 8), jnp.int32))["params"]
    _, aopt = amp.initialize(None, optimizers.FusedAdam(lr=1e-3),
                             opt_level="O0", verbosity=0)
    mgr = SnapshotManager(str(d))
    assert mgr.save((p, aopt.init(p)), step=1,
                    extra={"opt_level": "O0", "model": MODEL_MD})
    return str(d)


def test_usage_error_is_exit_2(capsys):
    with pytest.raises(SystemExit) as e:
        main([])                      # no subcommand
    assert e.value.code == 2
    with pytest.raises(SystemExit) as e:
        main(["bench"])               # missing --snapshot-dir
    assert e.value.code == 2


def test_bad_snapshot_dir_is_exit_1(tmp_path, capsys):
    rc = main(["bench", "--snapshot-dir", str(tmp_path / "absent"),
               "--requests", "1"])
    assert rc == 1
    cap = capsys.readouterr()
    assert cap.out == ""              # nothing half-printed on stdout
    assert "--snapshot-dir" in cap.err


def test_healthy_run_json_contract(snap_dir, capsys, tmp_path):
    tel = str(tmp_path / "serve.jsonl")
    rc = main(["bench", "--snapshot-dir", snap_dir,
               "--requests", "6", "--prompt-len", "4", "--max-new", "3",
               "--max-batch", "2", "--page", "8", "--telemetry", tel])
    assert rc == 0
    cap = capsys.readouterr()
    lines = [l for l in cap.out.splitlines() if l.strip()]
    assert len(lines) == 1            # exactly one JSON row on stdout
    report = json.loads(lines[0])
    assert report["metric"] == "serve_tokens_per_s"
    assert report["value"] > 0
    st = report["steady"]
    assert st["requests"] == 6 and st["completed"] == 6
    assert st["tokens"] == 6 * 3
    for key in ("p50", "p99"):
        assert st["ttft_ms"][key] > 0
        assert st["intertoken_ms"][key] >= 0
    ov = report["overload"]
    assert ov["requests"] == 12
    assert ov["rejected"] > 0         # shedding really happened
    assert 0.0 <= ov["goodput"] <= 1.0
    assert "loaded step 1" in cap.err

    # the telemetry arc: the JSONL renders a serve summarize section
    from apex_tpu import telemetry
    s = telemetry.summarize(telemetry.read_jsonl(tel))
    srv = s["serve"]
    assert srv["completed"] == 6 + ov["completed"]
    assert srv["rejected"] == ov["rejected"]
    assert srv["rejected_by_reason"]["queue_full"] == ov["rejected"]
    assert srv["ttft_s"]["count"] >= 6
    assert srv["intertoken_s"]["p99"] >= 0
    assert srv["occupancy"]["max"] <= 1.0
    text = telemetry.format_summary(s)
    assert "serving (apex_tpu.serve):" in text
    assert "shed reasons: queue_full=" in text


def test_no_overload_skips_phase(snap_dir, capsys):
    rc = main(["bench", "--snapshot-dir", snap_dir,
               "--requests", "2", "--prompt-len", "4", "--max-new", "2",
               "--max-batch", "2", "--page", "8", "--no-overload"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip())
    assert report["overload"] is None
