"""apex_tpu.pyprof attribution profiler: Chrome-trace parsing + scope
join on the committed synthetic fixture (hermetic — no profiler run),
HLO-text parsing (scope metadata, dot/conv FLOPs), roofline
classification, the report/compare CLI exit-code contract, and a CPU
end-to-end capture→report pass on a tiny jitted step."""

import gzip
import json
import os
import shutil

import pytest

import jax
import jax.numpy as jnp

from apex_tpu import pyprof
from apex_tpu.pyprof import cli as pyprof_cli
from apex_tpu.pyprof import hlo as pyprof_hlo
from apex_tpu.pyprof import roofline as pyprof_roofline
from apex_tpu.pyprof.capture import (SIDECAR_NAME, compute_breakdown,
                                     subsystem_of)
from apex_tpu.pyprof.parse import load_trace, union_us

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "synthetic_trace.json")

# the scope-join map matching the fixture's hlo_op names (what a capture
# sidecar carries; built here by hand so no profiler run is needed)
FIXTURE_MAP = {
    "dot.1": {"scope": "block_0/attn", "flops": 524288.0, "bytes": 49152},
    "call.9": {"scope": "block_0/ln1", "flops": None, "bytes": 16384},
    "fusion.2": {"scope": "block_0/ln1", "flops": None, "bytes": 16384},
    "all-reduce.3": {"scope": "apex_ddp_allreduce", "flops": None,
                     "bytes": 8192},
    "all-reduce.4": {"scope": "apex_zero_reduce_scatter", "flops": None,
                     "bytes": 8192},
}


def _fixture_breakdown(**kw):
    tr = load_trace(FIXTURE)
    kw.setdefault("instr_map", FIXTURE_MAP)
    kw.setdefault("module", "jit_step")
    kw.setdefault("wall_s", 300e-6)
    kw.setdefault("cost_stats", {"flops": 4e6, "bytes_accessed": 1e6})
    kw.setdefault("peak_flops", 1e12)
    kw.setdefault("peak_bytes_per_s", 1e11)
    return compute_breakdown(tr, **kw)


# ---------------------------------------------------------------------------
# trace parsing
# ---------------------------------------------------------------------------

class TestParse:
    def test_union_us(self):
        assert union_us([(0, 10), (5, 15), (20, 30)]) == 25
        assert union_us([]) == 0.0
        assert union_us([(3, 3)]) == 0.0

    def test_load_fields(self):
        tr = load_trace(FIXTURE)
        assert len(tr.events) == 7
        ev = next(e for e in tr.events if e.name == "dot.1")
        assert ev.process == "/device:TPU:0"
        assert ev.thread == "XLA Ops"
        assert ev.on_device
        host = next(e for e in tr.events if "Pjit" in e.name)
        assert not host.on_device

    def test_kernel_events_nesting_and_runtime_frames(self):
        """The container call.9 (spans fusion.2) and the zero-duration
        thread-pool frame are excluded; the hlo_op population remains."""
        tr = load_trace(FIXTURE)
        names = sorted(e.name for e in tr.kernel_events())
        assert names == ["all-reduce.3", "all-reduce.4", "dot.1",
                         "fusion.2"]

    def test_window_and_busy(self):
        tr = load_trace(FIXTURE)
        assert tr.device_window_us() == (0.0, 250.0)
        assert tr.busy_us() == pytest.approx(200.0)


# ---------------------------------------------------------------------------
# scope join + breakdown (the fixture numbers are hand-derivable)
# ---------------------------------------------------------------------------

class TestBreakdown:
    def test_categories_sum_to_100(self):
        bd = _fixture_breakdown()
        total = sum(v["pct"] for v in bd["categories"].values())
        assert total == pytest.approx(100.0, abs=0.1)

    def test_category_split(self):
        """window 250us = compute [0,150] + exposed collective 50us
        (all-reduce.3; all-reduce.4 hides behind fusion.2) + idle 50us."""
        bd = _fixture_breakdown()
        cats = bd["categories"]
        assert cats["compute"]["pct"] == pytest.approx(60.0, abs=0.1)
        assert cats["collective"]["pct"] == pytest.approx(20.0, abs=0.1)
        assert cats["idle"]["pct"] == pytest.approx(20.0, abs=0.1)

    def test_overlap_efficiency_from_device_timestamps(self):
        """90us of collective, 40us hidden behind concurrent compute."""
        bd = _fixture_breakdown()
        ov = bd["overlap"]
        assert ov["collective_s"] == pytest.approx(90e-6)
        assert ov["hidden_s"] == pytest.approx(40e-6)
        assert ov["efficiency"] == pytest.approx(40.0 / 90.0, abs=1e-3)

    def test_subsystem_buckets(self):
        bd = _fixture_breakdown()
        subs = bd["subsystems"]
        assert subs["attention"]["us"] == pytest.approx(100.0)
        assert subs["layer_norm"]["us"] == pytest.approx(50.0)
        assert subs["collective/ddp"]["us"] == pytest.approx(50.0)
        assert subs["collective/zero"]["us"] == pytest.approx(40.0)
        # subsystem table accounts for every kernel microsecond
        assert sum(r["us"] for r in subs.values()) == pytest.approx(240.0)

    def test_dispatch_gap(self):
        bd = _fixture_breakdown()
        # wall 300us, busy 200us -> 33.3% of wall the device sat idle
        assert bd["dispatch_gap_pct"] == pytest.approx(33.33, abs=0.1)

    def test_roofline_verdicts(self):
        bd = _fixture_breakdown()
        # ridge = 1e12/1e11 = 10 flop/B; dot.1 intensity 10.67 -> compute
        assert bd["subsystems"]["attention"]["bound"] == "compute-bound"
        assert bd["subsystems"]["collective/ddp"]["bound"] == "network"
        rf = bd["roofline"]
        assert rf["classification"] == "memory-bound"        # 4 < 10
        assert rf["ridge_intensity"] == pytest.approx(10.0)

    def test_degraded_without_map(self):
        """No sidecar map: ops land by HLO-name category, nothing raises,
        collectives still split out of compute."""
        bd = _fixture_breakdown(instr_map={})
        cats = bd["categories"]
        assert sum(v["pct"] for v in cats.values()) == pytest.approx(
            100.0, abs=0.1)
        assert cats["collective"]["pct"] > 0

    def test_subsystem_rules(self):
        assert subsystem_of("block_0/attn") == "attention"
        assert subsystem_of("TransformerLM/block_1/mlp/fc1") == "mlp"
        assert subsystem_of("blk/ln2") == "layer_norm"
        assert subsystem_of("stage3/block1") == "conv"
        assert subsystem_of("cond/apex_optimizer_step") == "optimizer"
        assert subsystem_of("apex_ddp_allreduce", "all-reduce.1") \
            == "collective/ddp"
        assert subsystem_of("apex_zero_reduce_scatter",
                            "reduce-scatter.2") == "collective/zero"
        assert subsystem_of("", "all-reduce.7") == "collective/other"
        assert subsystem_of("tok_emb") == "embedding"
        assert subsystem_of("head") == "head"
        assert subsystem_of("loss") == "loss"
        assert subsystem_of("something_else") == "other"


# ---------------------------------------------------------------------------
# HLO text parsing
# ---------------------------------------------------------------------------

_HLO_TEXT = """\
HloModule jit_step, is_scheduled=true, entry_computation_layout={(f32[64,64]{1,0})->f32[64,64]{1,0}}

%fused_computation (param_0: f32[64,64]) -> f32[64,64] {
  %param_0 = f32[64,64]{1,0} parameter(0)
  ROOT %multiply.1 = f32[64,64]{1,0} multiply(f32[64,64]{1,0} %param_0, f32[64,64]{1,0} %param_0), metadata={op_name="jit(step)/jit(main)/ln1/mul"}
}

%region_0.9 (arg_tuple.10: (s32[], f32[64,64])) -> (s32[], f32[64,64]) {
  %arg_tuple.10 = (s32[], f32[64,64]{1,0}) parameter(0)
  %get-tuple-element.1 = f32[64,64]{1,0} get-tuple-element((s32[], f32[64,64]{1,0}) %arg_tuple.10), index=1
  %dot.5 = f32[64,64]{1,0} dot(f32[64,64]{1,0} %get-tuple-element.1, f32[64,64]{1,0} %get-tuple-element.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(step)/jit(main)/jvp(while)/body/dot_general"}
}

ENTRY %main.10 (Arg_0.1: f32[64,64], Arg_1.2: f32[64,64], Arg_2.3: f32[1,32,32,3], Arg_3.4: f32[3,3,3,8]) -> f32[64,64] {
  %Arg_0.1 = f32[64,64]{1,0} parameter(0), metadata={op_name="x"}
  %Arg_1.2 = f32[64,64]{1,0} parameter(1), metadata={op_name="w"}
  %Arg_2.3 = f32[1,32,32,3]{3,2,1,0} parameter(2)
  %Arg_3.4 = f32[3,3,3,8]{3,2,1,0} parameter(3)
  %dot.1 = f32[64,64]{1,0} dot(f32[64,64]{1,0} %Arg_0.1, f32[64,64]{1,0} %Arg_1.2), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(step)/jit(main)/transpose(jvp(attn))/dot_general" source_file="x.py" source_line=4}
  %convolution.5 = f32[1,32,32,8]{3,2,1,0} convolution(f32[1,32,32,3]{3,2,1,0} %Arg_2.3, f32[3,3,3,8]{3,2,1,0} %Arg_3.4), window={size=3x3 pad=1_1x1_1}, dim_labels=b01f_01io->b01f, metadata={op_name="jit(step)/jit(main)/stem/conv_general_dilated"}
  ROOT %fusion.2 = f32[64,64]{1,0} fusion(f32[64,64]{1,0} %dot.1), kind=kLoop, calls=%fused_computation, metadata={op_name="jit(step)/jit(main)/ln1/mul"}
}
"""


class TestHlo:
    def test_parse_module(self):
        mod = pyprof_hlo.parse_hlo_text(_HLO_TEXT)
        assert mod.name == "jit_step"
        assert mod.entry == "main.10"
        # nested-paren while-body header parsed (a tuple-typed carry)
        assert "region_0.9" in mod.computations
        assert "dot.5" in mod.instructions

    def test_dot_flops(self):
        mod = pyprof_hlo.parse_hlo_text(_HLO_TEXT)
        # 2 * 64*64 (out) * 64 (contraction)
        assert mod.instructions["dot.1"].flops == pytest.approx(524288.0)
        assert mod.instructions["dot.1"].bytes_accessed == 3 * 64 * 64 * 4

    def test_conv_flops(self):
        mod = pyprof_hlo.parse_hlo_text(_HLO_TEXT)
        # 2 * prod(out 1*32*32*8) * window 9 * in_features 3
        assert mod.instructions["convolution.5"].flops == pytest.approx(
            2.0 * 1 * 32 * 32 * 8 * 9 * 3)

    def test_fusion_flops_include_called_computation(self):
        mod = pyprof_hlo.parse_hlo_text(_HLO_TEXT)
        assert mod.instructions["fusion.2"].called == [
            "fused_computation"]
        # the fused body has no dot/conv -> no flops claim
        assert mod.flops_of("fusion.2") is None

    def test_clean_op_name(self):
        f = pyprof_hlo.clean_op_name
        assert f("jit(step)/jit(main)/transpose(jvp(attn))/dot_general") \
            == "attn/dot_general"
        assert f("jit(step)/jit(main)/jit(shmap_body)/"
                 "jvp(TransformerLM)/block_0/attn/while/body/add") \
            == "TransformerLM/block_0/attn/while/body/add"
        assert pyprof_hlo.scope_of(
            "jit(step)/jit(main)/transpose(jvp(attn))/dot_general") \
            == "attn"
        assert pyprof_hlo.scope_of("jit(step)/jit(main)/psum") == ""


class TestRoofline:
    def test_classify(self):
        c = pyprof_roofline.classify
        assert c(100.0, 1.0, ridge=10.0) == "compute-bound"
        assert c(1.0, 100.0, ridge=10.0) == "memory-bound"
        assert c(None, 100.0, ridge=10.0) == "memory-bound"
        assert c(None, None, ridge=10.0) == "unknown"
        assert c(100.0, 1.0, ridge=10.0, is_collective=True) == "network"

    def test_program_roofline(self):
        rf = pyprof_roofline.program_roofline(
            {"flops": 2e9, "bytes_accessed": 1e8},
            peak_flops=1e12, peak_bytes_per_s=1e11)
        assert rf["classification"] == "compute-bound"    # 20 >= 10
        assert rf["compute_floor_s"] == pytest.approx(2e-3)
        assert rf["memory_floor_s"] == pytest.approx(1e-3)
        assert rf["roofline_floor_s"] == pytest.approx(2e-3)

    def test_peak_bw_env_override(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PEAK_BW", "123.0")
        assert pyprof_roofline.device_peak_bytes_per_s() == 123.0


# ---------------------------------------------------------------------------
# CLI: report + the compare exit-code contract
# ---------------------------------------------------------------------------

def _make_logdir(tmp_path):
    """A capture-shaped logdir from the committed fixture: trace JSON +
    gz sidecar (exactly what capture() writes)."""
    ld = tmp_path / "logdir"
    ld.mkdir()
    shutil.copy(FIXTURE, ld / "fixture.trace.json")
    side = {
        "schema": 1, "module": "jit_step", "steps": 1, "wall_s": 300e-6,
        "peak_flops": 1e12, "peak_bytes_per_s": 1e11,
        "cost_stats": {"flops": 4e6, "bytes_accessed": 1e6},
        "instructions": FIXTURE_MAP,
    }
    with gzip.open(ld / SIDECAR_NAME, "wt") as f:
        json.dump(side, f)
    return str(ld)


class TestCli:
    def test_report_from_logdir(self, tmp_path, capsys):
        ld = _make_logdir(tmp_path)
        out_json = str(tmp_path / "bd.json")
        rc = pyprof_cli.main(["report", ld, "-o", out_json])
        assert rc == 0
        text = capsys.readouterr().out
        assert "attention" in text and "collective/ddp" in text
        bd = json.load(open(out_json))
        assert bd["categories"]["compute"]["pct"] == pytest.approx(
            60.0, abs=0.1)

    def test_report_json_flag(self, tmp_path, capsys):
        ld = _make_logdir(tmp_path)
        assert pyprof_cli.main(["report", ld, "--json"]) == 0
        bd = json.loads(capsys.readouterr().out)
        assert "subsystems" in bd

    def test_report_bad_input_exit_1(self, tmp_path, capsys):
        bad = tmp_path / "junk.json"
        bad.write_text("{not json")
        assert pyprof_cli.main(["report", str(bad)]) == 1

    def test_compare_identical_exit_0(self, tmp_path, capsys):
        ld = _make_logdir(tmp_path)
        out = str(tmp_path / "bd.json")
        pyprof_cli.main(["report", ld, "-o", out])
        capsys.readouterr()
        assert pyprof_cli.main(["compare", out, out]) == 0

    def test_compare_regression_exit_4(self, tmp_path, capsys):
        ld = _make_logdir(tmp_path)
        out = str(tmp_path / "bd.json")
        pyprof_cli.main(["report", ld, "-o", out])
        bd = json.load(open(out))
        bd["device"]["busy_s"] *= 1.25          # doctored 25% slower
        for c in bd["categories"].values():
            c["s"] *= 1.25
        worse = str(tmp_path / "worse.json")
        json.dump(bd, open(worse, "w"))
        assert pyprof_cli.main(
            ["compare", out, worse, "--max-regress", "10"]) \
            == pyprof_cli.EXIT_REGRESSION
        # within tolerance: a 25% regression passes a 30% gate
        capsys.readouterr()
        assert pyprof_cli.main(
            ["compare", out, worse, "--max-regress", "30"]) == 0

    def test_compare_bench_rows(self, tmp_path, capsys):
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps({"metric": "img_s", "value": 2599.0}))
        b.write_text(json.dumps({"metric": "img_s", "value": 2000.0}))
        assert pyprof_cli.main(["compare", str(a), str(b)]) \
            == pyprof_cli.EXIT_REGRESSION
        capsys.readouterr()
        # higher-is-better: an IMPROVEMENT is never a regression
        assert pyprof_cli.main(["compare", str(b), str(a)]) == 0

    def test_compare_bench_wrapper(self, tmp_path, capsys):
        """BENCH_r*.json trajectory rows ride a {parsed: {...}} wrapper."""
        a = tmp_path / "r1.json"
        a.write_text(json.dumps(
            {"n": 1, "parsed": {"metric": "img_s", "value": 2599.0}}))
        assert pyprof_cli.main(["compare", str(a), str(a)]) == 0

    def test_compare_mixed_kinds_exit_1(self, tmp_path, capsys):
        ld = _make_logdir(tmp_path)
        out = str(tmp_path / "bd.json")
        pyprof_cli.main(["report", ld, "-o", out])
        bench = tmp_path / "bench.json"
        bench.write_text(json.dumps({"metric": "img_s", "value": 1.0}))
        assert pyprof_cli.main(["compare", out, str(bench)]) == 1


# ---------------------------------------------------------------------------
# telemetry integration
# ---------------------------------------------------------------------------

class TestTelemetryProfileSection:
    def test_summarize_profile_section(self):
        from apex_tpu.telemetry.export import format_summary, summarize
        events = [
            {"name": "profile/compute_pct", "value": 60.0,
             "kind": "static"},
            {"name": "profile/collective_pct", "value": 20.0,
             "kind": "static"},
            {"name": "profile/idle_pct", "value": 20.0, "kind": "static"},
            {"name": "profile/dispatch_gap_pct", "value": 33.3,
             "kind": "static"},
            {"name": "profile/overlap_efficiency", "value": 0.44,
             "kind": "static"},
            {"name": "profile/scope/attention", "value": 100.0,
             "kind": "static",
             "meta": {"pct": 41.7, "bound": "compute-bound"}},
            {"name": "step/model_flops", "value": 1e9, "kind": "static"},
        ]
        s = summarize(events)
        assert s["profile"]["compute_pct"] == 60.0
        assert s["profile"]["scopes"]["attention"]["bound"] \
            == "compute-bound"
        # profile statics do NOT leak into the generic statics table
        assert "profile/compute_pct" not in (s.get("static") or {})
        assert "step/model_flops" in s["static"]
        text = format_summary(s)
        assert "profile (device timeline)" in text
        assert "attention" in text and "dispatch gap 33.3%" in text

    def test_record_breakdown_roundtrip(self):
        from apex_tpu import telemetry
        from apex_tpu.telemetry.export import summarize
        bd = _fixture_breakdown()
        with telemetry.capture() as col:
            pyprof.record_breakdown(bd)
            events = [e.to_dict() for e in col.drain()]
        s = summarize(events)
        assert s["profile"]["compute_pct"] == pytest.approx(60.0, abs=0.1)
        assert "attention" in s["profile"]["scopes"]

    def test_record_breakdown_disabled_is_noop(self):
        from apex_tpu import telemetry
        assert not telemetry.enabled()
        pyprof.record_breakdown(_fixture_breakdown())   # must not raise


# ---------------------------------------------------------------------------
# CPU end-to-end: capture -> breakdown -> offline report
# ---------------------------------------------------------------------------

class TestCaptureE2E:
    def test_capture_cpu_end_to_end(self, tmp_path):
        def f(x, w):
            with jax.named_scope("attn"):
                y = jnp.dot(x, w)
            with jax.named_scope("ln1"):
                z = jax.nn.relu(y) * 2.0
            return z.sum()

        g = jax.jit(jax.grad(f))
        x = jnp.ones((256, 256), jnp.float32)
        w = jnp.ones((256, 256), jnp.float32)
        ld = str(tmp_path / "prof")
        bd = pyprof.capture(g, x, w, steps=3, logdir=ld)

        # categories sum to ~100% of the device window
        total = sum(v["pct"] for v in bd["categories"].values())
        assert total == pytest.approx(100.0, abs=0.5)
        assert bd["device"]["busy_s"] > 0
        assert bd["device"]["kernel_events"] > 0
        # known scopes appear, joined through HLO metadata
        assert any("attn" in s for s in bd["scopes"]), bd["scopes"]
        assert any("ln1" in s for s in bd["scopes"]), bd["scopes"]
        assert "attention" in bd["subsystems"]
        assert "layer_norm" in bd["subsystems"]
        # subsystem table accounts for the summed kernel time
        kernel_us = sum(r["us"] for r in bd["subsystems"].values())
        tr = load_trace(ld)
        assert kernel_us == pytest.approx(
            sum(e.dur_us for e in tr.kernel_events()), rel=1e-3)
        # the grad dot dominates and is compute-bound at 256^3 vs the
        # CPU's nominal ridge
        assert bd["subsystems"]["attention"]["bound"] == "compute-bound"
        assert bd["dispatch_gap_pct"] is not None

        # offline rebuild from the logdir matches
        bd2 = pyprof.breakdown_from_logdir(ld)
        assert bd2["subsystems"].keys() == bd["subsystems"].keys()
        assert bd2["categories"]["compute"]["pct"] == pytest.approx(
            bd["categories"]["compute"]["pct"], abs=0.1)
        # and the text report renders the scopes
        text = pyprof.format_breakdown(bd2)
        assert "attn" in text and "roofline" in text
