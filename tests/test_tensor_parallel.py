"""Megatron-style tensor parallelism (parallel/tensor_parallel.py):
head-sharded attention + column/row-parallel MLP over a mesh axis must
reproduce the dense model exactly — forward, gradients, and a full
FusedAdam train step — including composed with a data axis on a 2-D
mesh. Additive capability (the reference has no tensor parallelism);
the scheme is the standard Megatron f/g two-collective block."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import optimizers, parallel
from apex_tpu.models import TransformerLM
from apex_tpu.models.gpt import next_token_loss
from apex_tpu.parallel import (lm_tp_pspecs, tp_shard_lm_params,
                               tp_unshard_lm_params)

# Integration tier (PR 1): this whole module rides `-m slow` — Megatron-TP dense-parity integration.
# Tier-1 (-m 'not slow') must fit the 870 s gate budget; the fast cross-
# sections of this stack stay in tier-1 via test_zero/test_parallel/
# test_param_groups/test_attention and the ci/gate.sh dryrun parts.
pytestmark = pytest.mark.slow

V, L, E, H, S, B = 64, 2, 64, 8, 32, 2
TP = 4


def _models():
    dense = TransformerLM(vocab_size=V, num_layers=L, embed_dim=E,
                          num_heads=H, max_seq=S)
    local = dense.clone(num_heads=H // TP, tensor_parallel_axis="model",
                        tensor_parallel_size=TP)
    return dense, local


def _data(key):
    return jax.random.randint(key, (B, S), 0, V)


def test_qkv_permute_roundtrip():
    k = jax.random.normal(jax.random.PRNGKey(0), (E, 3 * E))
    from apex_tpu.parallel.tensor_parallel import _permute_qkv
    back = _permute_qkv(_permute_qkv(k, TP), TP, inverse=True)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(k))
    # device p's chunk of the permuted kernel is [Q_p | K_p | V_p]
    perm = _permute_qkv(k, TP)
    w = 3 * E // TP
    p0 = perm[:, :w]
    np.testing.assert_array_equal(
        np.asarray(p0[:, : w // 3]), np.asarray(k[:, : E // TP]))      # Q_0
    np.testing.assert_array_equal(
        np.asarray(p0[:, w // 3: 2 * w // 3]),
        np.asarray(k[:, E: E + E // TP]))                              # K_0


def test_tp_shard_roundtrip():
    dense, _ = _models()
    params = dense.init(jax.random.PRNGKey(0), _data(
        jax.random.PRNGKey(1)))["params"]
    back = tp_unshard_lm_params(tp_shard_lm_params(params, TP), TP)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(params)[0],
            jax.tree_util.tree_flatten_with_path(back)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, err_msg=str(pa))


@pytest.fixture(scope="module")
def tp_mesh():
    return parallel.make_mesh((TP,), ("model",),
                              devices=jax.devices()[:TP])


def _tp_apply(local, mesh, params_tp, specs, tokens, grad=False):
    def per_device(p, toks):
        def loss_fn(pp):
            logits = local.apply({"params": pp}, toks)
            return next_token_loss(logits, toks)

        if grad:
            loss, grads = jax.value_and_grad(loss_fn)(p)
            return loss, grads
        return local.apply({"params": p}, toks)

    out_specs = (P(), specs) if grad else P()
    fn = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(specs, P()),
        out_specs=out_specs, check_vma=False))
    return fn(params_tp, tokens)


def test_tp_forward_matches_dense(tp_mesh):
    dense, local = _models()
    tokens = _data(jax.random.PRNGKey(1))
    params = dense.init(jax.random.PRNGKey(0), tokens)["params"]
    want = dense.apply({"params": params}, tokens)

    params_tp = tp_shard_lm_params(params, TP)
    specs = lm_tp_pspecs(params_tp)
    params_tp = jax.device_put(params_tp, jax.tree_util.tree_map(
        lambda sp: NamedSharding(tp_mesh, sp), specs))
    got = _tp_apply(local, tp_mesh, params_tp, specs, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_tp_grads_match_dense(tp_mesh):
    """Every param grad — including the sharded qkv/fc kernels — must
    equal the dense grad's corresponding shard (the f/g custom vjps:
    a plain psum would over-count replicated cotangents by TP)."""
    dense, local = _models()
    tokens = _data(jax.random.PRNGKey(2))
    params = dense.init(jax.random.PRNGKey(0), tokens)["params"]

    def dense_loss(p):
        return next_token_loss(dense.apply({"params": p}, tokens), tokens)

    want_loss, want_grads = jax.value_and_grad(dense_loss)(params)
    # compare in the TP layout: permute the dense grads the same way
    # (pure permutation — row-parallel biases are unscaled since
    # RowParallelDense adds them once after the g reduction)
    want_grads = tp_shard_lm_params(want_grads, TP)

    params_tp = tp_shard_lm_params(params, TP)
    specs = lm_tp_pspecs(params_tp)
    params_tp = jax.device_put(params_tp, jax.tree_util.tree_map(
        lambda sp: NamedSharding(tp_mesh, sp), specs))
    got_loss, got_grads = _tp_apply(local, tp_mesh, params_tp, specs,
                                    tokens, grad=True)

    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5)
    for (pa, g), (_, w) in zip(
            jax.tree_util.tree_flatten_with_path(got_grads)[0],
            jax.tree_util.tree_flatten_with_path(want_grads)[0]):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=str(pa))


def test_tp_train_step_2d_mesh_matches_dense():
    """2-D (data x model) mesh: per-device grads pmean over 'data' and
    stay local over 'model'; one FusedAdam step must track the dense
    single-device step on the same global batch."""
    d_dp = 2
    tp = TP // 2
    mesh2 = parallel.make_mesh((d_dp, tp), ("data", "model"),
                               devices=jax.devices()[:d_dp * tp])
    dense = TransformerLM(vocab_size=V, num_layers=L, embed_dim=E,
                          num_heads=H, max_seq=S)
    local = dense.clone(num_heads=H // tp, tensor_parallel_axis="model",
                        tensor_parallel_size=tp)

    tokens = _data(jax.random.PRNGKey(3))  # global batch B
    params = dense.init(jax.random.PRNGKey(0), tokens)["params"]

    def dense_loss(p):
        return next_token_loss(dense.apply({"params": p}, tokens), tokens)

    _, dgrads = jax.value_and_grad(dense_loss)(params)
    opt = optimizers.FusedAdam(lr=1e-3)
    want, _ = opt.step(dgrads, params, opt.init(params))
    want = tp_shard_lm_params(want, tp)

    params_tp = tp_shard_lm_params(params, tp)
    specs = lm_tp_pspecs(params_tp)
    params_tp = jax.device_put(params_tp, jax.tree_util.tree_map(
        lambda sp: NamedSharding(mesh2, sp), specs))

    def per_device(p, toks, st):
        def loss_fn(pp):
            logits = local.apply({"params": pp}, toks)
            return next_token_loss(logits, toks)

        grads = jax.grad(loss_fn)(p)
        grads = jax.lax.pmean(grads, "data")   # dp average, tp-local
        return opt.step(grads, p, st)

    # AdamState(step, exp_avg, exp_avg_sq): moments mirror the param
    # sharding leaf-for-leaf, the step scalar is replicated
    st = opt.init(params_tp)
    st_specs = type(st)(step=P(), exp_avg=specs, exp_avg_sq=specs)
    fn = jax.jit(shard_map(
        per_device, mesh=mesh2,
        in_specs=(specs, P("data"), st_specs),
        out_specs=(specs, st_specs), check_vma=False))
    got, _ = fn(params_tp, tokens, st)

    for (pa, g), (_, w) in zip(
            jax.tree_util.tree_flatten_with_path(got)[0],
            jax.tree_util.tree_flatten_with_path(want)[0]):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=3e-4, atol=3e-5,
                                   err_msg=str(pa))


def test_tp_dropout_rank_folded(tp_mesh):
    """Dropout under TP folds the rank into the rng: training-mode
    forward must run (no loud-fail), be finite, and actually drop
    (differ from the deterministic pass). Per-rank masks are
    independent draws — dense-identity is neither possible nor
    required for dropout."""
    dense = TransformerLM(vocab_size=V, num_layers=L, embed_dim=E,
                          num_heads=H, max_seq=S, dropout=0.3)
    local = dense.clone(num_heads=H // TP, tensor_parallel_axis="model",
                        tensor_parallel_size=TP)
    tokens = _data(jax.random.PRNGKey(4))
    params = dense.init(jax.random.PRNGKey(0), tokens)["params"]
    params_tp = tp_shard_lm_params(params, TP)
    specs = lm_tp_pspecs(params_tp)
    params_tp = jax.device_put(params_tp, jax.tree_util.tree_map(
        lambda sp: NamedSharding(tp_mesh, sp), specs))

    def per_device(p, toks, det):
        return local.apply({"params": p}, toks, deterministic=det,
                           dropout_rng=jax.random.PRNGKey(7))

    fn = jax.jit(shard_map(
        lambda p, t: per_device(p, t, False), mesh=tp_mesh,
        in_specs=(specs, P()), out_specs=P(), check_vma=False))
    fn_det = jax.jit(shard_map(
        lambda p, t: per_device(p, t, True), mesh=tp_mesh,
        in_specs=(specs, P()), out_specs=P(), check_vma=False))
    train = fn(params_tp, tokens)
    ev = fn_det(params_tp, tokens)
    assert np.isfinite(np.asarray(train)).all()
    assert not np.allclose(np.asarray(train), np.asarray(ev))

    # the FOLD itself: each rank must derive a distinct dropout rng —
    # the e2e smoke above cannot distinguish folded from unfolded masks
    # (identical-mask dropout also yields finite, different-from-eval
    # output), so check the helper both paths route through
    from apex_tpu.contrib.multihead_attn import _tp_dropout_rng

    def per_rank_key(_):
        return _tp_dropout_rng(jax.random.PRNGKey(7), "model")[None]

    keys = shard_map(per_rank_key, mesh=tp_mesh, in_specs=(P(),),
                     out_specs=P("model"), check_vma=False)(
        jnp.zeros(()))
    assert len({tuple(np.asarray(k)) for k in keys}) == TP
    # and it is a no-op outside TP / without an rng
    assert _tp_dropout_rng(None, "model") is None
    k0 = jax.random.PRNGKey(3)
    assert _tp_dropout_rng(k0, None) is k0
