"""The fused Pallas kernel tier (ISSUE 11): conv epilogue
(ops/conv_epilogue.py), softmax-cross-entropy (ops/pallas_xent.py wired
through contrib/xentropy.py), and multi-tensor flat-apply batching
(ops/multi_tensor.py backend="flat").

Every kernel's contract is pinned four ways, per the roadmap's kernel-PR
acceptance: numerics parity against the unfused reference (fp32/bf16,
with/without label smoothing and residual add), gradient parity through
the custom_vjp, jaxpr equality proving the OFF-switch traces the exact
pre-kernel program, and the tune off-policy resolving to the frozen
heuristics (rows/block_k None == explicit heuristic values).
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib import xentropy as xe
from apex_tpu.ops import conv_epilogue as ce
from apex_tpu.ops import multi_tensor as mt
from apex_tpu.ops import pallas_xent as px


def _norm_jaxpr(fn, *args) -> str:
    """jaxpr string with object addresses normalized (custom_vjp jaxprs
    embed bound-method reprs — the PR 8 precedent)."""
    return re.sub(r"0x[0-9a-f]+", "0x", str(jax.make_jaxpr(fn)(*args)))


@pytest.fixture
def pallas_xent_backend():
    prev = xe.set_backend("pallas")
    yield
    xe.set_backend(prev)


@pytest.fixture
def flat_mt_backend():
    prev = mt.set_backend("flat")
    yield
    mt.set_backend(prev)


# ---------------------------------------------------------------------------
# fused softmax cross-entropy
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_xent_kernel_parity(dtype, smoothing):
    n, k = 127, 512
    logits = (jax.random.normal(jax.random.PRNGKey(0), (n, k)) * 3
              ).astype(dtype)
    labels = jax.random.randint(jax.random.PRNGKey(1), (n,), 0, k)
    ref_l, ref_lse = xe._xent_fwd_impl(logits, labels, smoothing)
    losses, lse = px.xent_fwd(logits, labels, smoothing,
                              rows=64, block_k=256)
    np.testing.assert_allclose(np.asarray(losses), np.asarray(ref_l),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               rtol=1e-5, atol=1e-5)
    # bwd from the saved lse vs the reference rebuild
    g = jax.random.normal(jax.random.PRNGKey(2), (n,))
    x = logits.astype(jnp.float32)
    probs = jnp.exp(x - ref_lse[..., None])
    onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32)
    gref = ((probs - (1.0 - smoothing) * onehot - smoothing / k)
            * g[..., None]).astype(dtype)
    dx = px.xent_bwd(logits, labels, lse, g, smoothing,
                     rows=64, block_k=256)
    assert dx.dtype == jnp.dtype(dtype)
    np.testing.assert_allclose(np.asarray(dx, np.float32),
                               np.asarray(gref, np.float32),
                               rtol=1e-5, atol=1e-5)


def test_xent_custom_vjp_grad_parity(pallas_xent_backend):
    logits = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 512))
    targets = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 512)

    def loss(lg):
        return jnp.sum(xe.softmax_cross_entropy_loss(lg, targets, 0.1))

    l_pal, g_pal = jax.value_and_grad(loss)(logits)
    prev = xe.set_backend("jnp")
    try:
        l_ref, g_ref = jax.value_and_grad(loss)(logits)
    finally:
        xe.set_backend("pallas")   # fixture restores
    np.testing.assert_allclose(float(l_pal), float(l_ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g_pal), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)


def test_xent_half_to_float_dtype_contract():
    """The satellite fix: half_to_float=False returns losses in the
    LOGITS dtype; True keeps fp32; the backward returns cotangents in
    the logits' original dtype either way (the _xent_bwd cast audit)."""
    logits = jax.random.normal(jax.random.PRNGKey(0),
                               (9, 512)).astype(jnp.bfloat16)
    labels = jax.random.randint(jax.random.PRNGKey(1), (9,), 0, 512)
    l16 = xe.softmax_cross_entropy_loss(logits, labels, 0.1, False)
    l32 = xe.softmax_cross_entropy_loss(logits, labels, 0.1, True)
    assert l16.dtype == jnp.bfloat16
    assert l32.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(l16, np.float32),
                               np.asarray(l32), rtol=1e-2)
    # fp32 logits: fp32 losses regardless (and, pinned below, the exact
    # pre-fix program)
    lf = xe.softmax_cross_entropy_loss(logits.astype(jnp.float32), labels)
    assert lf.dtype == jnp.float32

    for htf in (False, True):
        g = jax.grad(lambda lg: jnp.sum(xe.softmax_cross_entropy_loss(
            lg, labels, 0.1, htf).astype(jnp.float32)))(logits)
        assert g.dtype == jnp.bfloat16, (htf, g.dtype)
    # the low-precision-loss path's bwd math still runs in fp32: its
    # grads match the fp32-loss path's within bf16 resolution
    g16 = jax.grad(lambda lg: jnp.sum(xe.softmax_cross_entropy_loss(
        lg, labels, 0.1, False).astype(jnp.float32)))(logits)
    g32 = jax.grad(lambda lg: jnp.sum(xe.softmax_cross_entropy_loss(
        lg, labels, 0.1, True)))(logits)
    np.testing.assert_allclose(np.asarray(g16, np.float32),
                               np.asarray(g32, np.float32), atol=1e-2)


def test_xent_off_switch_jaxpr_identical():
    """Backend default (env auto) traces the exact plain-jnp program —
    the fused kernel is provably inert when off."""
    logits = jnp.ones((4, 256), jnp.float32)
    labels = jnp.zeros((4,), jnp.int32)

    def f(lg):
        return jax.value_and_grad(
            lambda l: jnp.sum(xe.softmax_cross_entropy_loss(l, labels)))(lg)

    j_default = _norm_jaxpr(f, logits)
    prev = xe.set_backend("jnp")
    try:
        j_off = _norm_jaxpr(f, logits)
    finally:
        xe.set_backend(prev)
    assert j_default == j_off
    assert "pallas" not in j_default


def test_xent_tune_off_resolves_to_heuristic():
    from apex_tpu.tune import heuristics as h
    logits = jnp.ones((64, 512), jnp.bfloat16)
    labels = jnp.zeros((64,), jnp.int32)
    heur = h.xentropy_fwd({"k": 512, "dtype": "bfloat16"})
    assert _norm_jaxpr(lambda lg: px.xent_fwd(lg, labels, 0.1), logits) \
        == _norm_jaxpr(lambda lg: px.xent_fwd(
            lg, labels, 0.1, rows=heur["rows"],
            block_k=heur["block_k"]), logits)


def test_xent_unaligned_vocab_falls_back(pallas_xent_backend):
    """K % 128 != 0 (the resnet 1000-class head): the pallas backend
    silently degrades to the jnp math — same value, no error."""
    logits = jax.random.normal(jax.random.PRNGKey(0), (8, 1000))
    labels = jax.random.randint(jax.random.PRNGKey(1), (8,), 0, 1000)
    got = xe.softmax_cross_entropy_loss(logits, labels, 0.1)
    prev = xe.set_backend("jnp")
    try:
        want = xe.softmax_cross_entropy_loss(logits, labels, 0.1)
    finally:
        xe.set_backend("pallas")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_xent_block_k_divisor_clamp():
    # 384 = 3*128: preference 2048 is not a divisor — the kernel must
    # clamp to the largest 128-multiple divisor, not crash or mask
    assert px._pick_block_k(384, 2048) == 384
    # 50304 = 128*3*131: only 128 and 384 divide it under 2048
    bk = px._pick_block_k(50304, 2048)
    assert bk == 384
    assert 50304 % bk == 0 and bk % 128 == 0 and bk <= 2048
    assert px._pick_block_k(512, 512) == 512
    assert px._pick_block_k(2048, 1024) == 1024


def test_xent_gpt_loss_scope_parity(pallas_xent_backend):
    """The GPT loss scope (models.gpt.next_token_loss) runs the fused
    kernel when the backend is on, value-matching the plain path."""
    from apex_tpu.models import GPTTiny
    from apex_tpu.models.gpt import next_token_loss
    toks = jax.random.randint(jax.random.PRNGKey(0), (1, 16), 0, 128)
    m = GPTTiny(vocab_size=128, max_seq=16)
    params = m.init(jax.random.PRNGKey(1), toks)["params"]

    def loss(p):
        return next_token_loss(m.apply({"params": p}, toks), toks)

    l_pal, g_pal = jax.value_and_grad(loss)(params)
    prev = xe.set_backend("jnp")
    try:
        l_ref, g_ref = jax.value_and_grad(loss)(params)
    finally:
        xe.set_backend("pallas")
    np.testing.assert_allclose(float(l_pal), float(l_ref), rtol=1e-6)
    worst = max(jax.tree_util.tree_leaves(jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), g_pal, g_ref)))
    assert worst < 1e-5, worst


# ---------------------------------------------------------------------------
# fused conv epilogue
# ---------------------------------------------------------------------------

def _epi_ref(x, scale, shift, residual, relu):
    y = x.astype(jnp.float32) * scale + shift
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)


@pytest.mark.parametrize("c,dtype,with_res", [
    (256, jnp.float32, True), (256, jnp.bfloat16, False),
    (64, jnp.bfloat16, True),     # stem width: the lane-tiled view
    (128, jnp.float32, False),
])
def test_conv_epilogue_parity(c, dtype, with_res):
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, c)).astype(dtype)
    r = (jax.random.normal(jax.random.PRNGKey(1), x.shape).astype(dtype)
         if with_res else None)
    scale = jax.random.normal(jax.random.PRNGKey(2), (c,)) * 0.5 + 1.0
    shift = jax.random.normal(jax.random.PRNGKey(3), (c,)) * 0.1
    y = ce.bn_relu_apply(x, scale, shift, residual=r)
    want = _epi_ref(x, scale, shift, r, True)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(want, np.float32),
                               rtol=1e-5, atol=1e-5)
    assert y.dtype == x.dtype

    def loss(fn):
        def inner(x, s, b, *a):
            return jnp.sum(fn(x, s, b, *a).astype(jnp.float32) ** 2)
        return inner

    args = (x, scale, shift) + ((r,) if with_res else ())
    nargs = tuple(range(len(args)))
    g_ref = jax.grad(loss(lambda x, s, b, *a: _epi_ref(
        x, s, b, a[0] if a else None, True)), argnums=nargs)(*args)
    g_fus = jax.grad(loss(lambda x, s, b, *a: ce.bn_relu_apply(
        x, s, b, residual=a[0] if a else None)), argnums=nargs)(*args)
    for i, (a, b) in enumerate(zip(g_ref, g_fus)):
        assert a.dtype == b.dtype, i
        np.testing.assert_allclose(np.asarray(b, np.float32),
                                   np.asarray(a, np.float32),
                                   rtol=1e-3, atol=1e-3)


def test_conv_epilogue_relu_off():
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 128))
    scale = jnp.ones((128,)) * 2.0
    shift = jnp.ones((128,)) * -0.5
    y = ce.bn_relu_apply(x, scale, shift, relu=False)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(x * 2.0 - 0.5), rtol=1e-6)
    g = jax.grad(lambda x: jnp.sum(ce.bn_relu_apply(
        x, scale, shift, relu=False)))(x)
    np.testing.assert_allclose(np.asarray(g), np.full((16, 128), 2.0),
                               rtol=1e-6)


def test_conv_epilogue_unsupported_raises():
    x = jnp.ones((2, 3, 3, 48))   # 128 % 48 != 0
    with pytest.raises(ValueError, match="conv epilogue"):
        ce.bn_relu_apply(x, jnp.ones((48,)), jnp.zeros((48,)))


def test_conv_epilogue_tune_off_jaxpr_identical():
    x = jnp.ones((64, 256), jnp.float32)
    scale = jnp.ones((256,))
    shift = jnp.zeros((256,))
    frozen = ce._rows_per_block(256)
    assert _norm_jaxpr(lambda x: ce.bn_relu_apply(x, scale, shift), x) \
        == _norm_jaxpr(lambda x: ce.bn_relu_apply(
            x, scale, shift, rows=frozen), x)


def test_syncbn_epilogue_kwargs_unfused_identical():
    """SyncBatchNorm's new residual/relu kwargs with fused_epilogue=False
    trace the exact composed unfused ops (the off-switch twin)."""
    from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm
    import flax.linen as nn
    x = jnp.ones((4, 8, 8, 32), jnp.float32)
    r = jnp.ones_like(x) * 0.5
    bn = SyncBatchNorm(axis_name=None, use_running_average=False)
    variables = bn.init(jax.random.PRNGKey(0), x)

    def with_kwargs(x):
        y, _ = bn.apply(variables, x, residual=r, relu=True,
                        mutable=["batch_stats"])
        return y

    def composed(x):
        y, _ = bn.apply(variables, x, mutable=["batch_stats"])
        y = r + y
        return nn.relu(y)

    assert _norm_jaxpr(with_kwargs, x) == _norm_jaxpr(composed, x)


def test_resnet_fused_epilogue_parity():
    """Fused vs unfused ResNet18 on the SAME params: loss, grads, and
    batch_stats agree (identical param trees by construction)."""
    from apex_tpu import models
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 16, 3))
    m0 = models.ResNet18(num_classes=10)
    m1 = models.ResNet18(num_classes=10, fused_epilogue=True)
    v = m0.init(jax.random.PRNGKey(1), x, train=False)
    assert jax.tree_util.tree_structure(
        m1.init(jax.random.PRNGKey(1), x, train=False)) \
        == jax.tree_util.tree_structure(v)

    def loss_fn(m):
        def f(p):
            logits, upd = m.apply(
                {"params": p, "batch_stats": v["batch_stats"]}, x,
                train=True, mutable=["batch_stats"])
            return jnp.sum(logits ** 2), upd["batch_stats"]
        return f

    (l0, bs0), g0 = jax.value_and_grad(loss_fn(m0), has_aux=True)(
        v["params"])
    (l1, bs1), g1 = jax.value_and_grad(loss_fn(m1), has_aux=True)(
        v["params"])
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-4)
    rel = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b)))
        / (float(jnp.max(jnp.abs(a))) + 1e-9), g0, g1)
    # 3e-2: the effective-coefficient boundary (dscale = sum g*x, dshift
    # = sum g, recombined to dgamma outside) trades the centered
    # reduction's cancellation protection for the single fused pass —
    # a few 1e-2 relative on the zero-init exit-BN params is the
    # expected fp32 association difference, not a math error
    assert max(jax.tree_util.tree_leaves(rel)) < 3e-2
    bsd = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), bs0, bs1)
    assert max(jax.tree_util.tree_leaves(bsd)) < 1e-4


def test_resnet_default_off_switch():
    """The default model traces NO pallas call and is identical to an
    explicit fused_epilogue=False build."""
    from apex_tpu import models
    x = jnp.ones((1, 16, 16, 3))
    m_def = models.ResNet18(num_classes=4)
    m_off = models.ResNet18(num_classes=4, fused_epilogue=False)
    v = m_def.init(jax.random.PRNGKey(0), x, train=False)

    def fwd(m):
        def f(p):
            out, _ = m.apply(
                {"params": p, "batch_stats": v["batch_stats"]}, x,
                train=True, mutable=["batch_stats"])
            return out
        return f

    j_def = _norm_jaxpr(fwd(m_def), v["params"])
    assert j_def == _norm_jaxpr(fwd(m_off), v["params"])
    assert "pallas" not in j_def


# ---------------------------------------------------------------------------
# multi-tensor flat apply
# ---------------------------------------------------------------------------

def _mixed_tree():
    return {
        "a": jax.random.normal(jax.random.PRNGKey(0), (33, 7)),
        "b": jax.random.normal(jax.random.PRNGKey(1), (129,)),
        "c": jax.random.normal(jax.random.PRNGKey(2), (5,)
                               ).astype(jnp.bfloat16),
    }


def test_mt_flat_adam_bitwise_vs_jnp(flat_mt_backend):
    from apex_tpu import optimizers
    p = _mixed_tree()
    g = jax.tree_util.tree_map(lambda x: x * 0.1, p)
    opt = optimizers.FusedAdam(lr=1e-2, weight_decay=0.01)
    st = opt.init(p)
    p_flat, st_flat = opt.step(g, p, st)
    prev = mt.set_backend("jnp")
    try:
        p_jnp, st_jnp = opt.step(g, p, st)
    finally:
        mt.set_backend("flat")
    # same fp32 elementwise math, just bucketed: bitwise equal
    for a, b in zip(jax.tree_util.tree_leaves(p_flat),
                    jax.tree_util.tree_leaves(p_jnp)):
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))
    for a, b in zip(jax.tree_util.tree_leaves(st_flat.exp_avg),
                    jax.tree_util.tree_leaves(st_jnp.exp_avg)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_mt_flat_sgd_with_model_copy(flat_mt_backend):
    """The 4-list variant: flat path emits the low-precision model copy
    off the flat master update."""
    p = {"w": jax.random.normal(jax.random.PRNGKey(0), (16, 8))}
    g = jax.tree_util.tree_map(lambda x: x * 0.1, p)
    m = jax.tree_util.tree_map(jnp.zeros_like, p)
    tmpl = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), p)
    new_p, new_m, new_model = mt.multi_tensor_sgd(
        g, p, m, lr=0.1, momentum=0.9, first_run=True,
        model_out_template=tmpl)
    prev = mt.set_backend("jnp")
    try:
        ref_p, ref_m, ref_model = mt.multi_tensor_sgd(
            g, p, m, lr=0.1, momentum=0.9, first_run=True,
            model_out_template=tmpl)
    finally:
        mt.set_backend("flat")
    assert new_model["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(new_p["w"]),
                                  np.asarray(ref_p["w"]))
    np.testing.assert_array_equal(
        np.asarray(new_model["w"], np.float32),
        np.asarray(ref_model["w"], np.float32))


def test_mt_flat_scale_overflow(flat_mt_backend):
    tree = {"x": jnp.array([1.0, 2.0]), "y": jnp.array([jnp.inf, 0.0])}
    out, of = mt.multi_tensor_scale(tree, jnp.asarray(0.5))
    assert bool(of)
    np.testing.assert_array_equal(np.asarray(out["x"]),
                                  np.array([0.5, 1.0]))
    clean = {"x": jnp.array([1.0, 2.0])}
    _, of2 = mt.multi_tensor_scale(clean, jnp.asarray(0.5))
    assert not bool(of2)


def test_mt_backend_default_off_switch():
    """Default (env auto, tune off): backend resolves to jnp and the
    optimizer step jaxpr is identical to an explicit jnp build."""
    from apex_tpu import optimizers
    p = _mixed_tree()
    g = jax.tree_util.tree_map(lambda x: x * 0.1, p)
    opt = optimizers.FusedAdam(lr=1e-2)
    st = opt.init(p)
    assert mt.backend(g, p) == "jnp"

    def step(g, p, s):
        return opt.step(g, p, s)

    j_default = _norm_jaxpr(step, g, p, st)
    prev = mt.set_backend("jnp")
    try:
        j_off = _norm_jaxpr(step, g, p, st)
    finally:
        mt.set_backend(prev)
    assert j_default == j_off


def test_mt_flat_fp16_supported(flat_mt_backend):
    """flat is pure jnp — fp16 trees stay on it (only pallas demotes)."""
    p = {"w": jnp.ones((8,), jnp.float16)}
    assert mt.backend(p) == "flat"
    out, of = mt.multi_tensor_scale(p, jnp.asarray(2.0))
    assert out["w"].dtype == jnp.float16
    assert not bool(of)


def test_epilogue_out_dtype_keeps_wide_precision():
    """SyncBatchNorm(dtype=fp32) over a bf16 input: the fused kernel
    writes fp32 straight off its fp32 result — NOT rounded through the
    bf16 input dtype first (review fix)."""
    x = jax.random.normal(jax.random.PRNGKey(0),
                          (64, 128)).astype(jnp.bfloat16)
    scale = jnp.ones((128,)) * 1.37
    shift = jnp.ones((128,)) * 0.11
    y = ce.bn_relu_apply(x, scale, shift, out_dtype=jnp.float32)
    assert y.dtype == jnp.float32
    want = jnp.maximum(x.astype(jnp.float32) * scale + shift, 0.0)
    # exact fp32 apply — a bf16 round trip would differ at ~1e-2
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
    g = jax.grad(lambda x: jnp.sum(ce.bn_relu_apply(
        x, scale, shift, out_dtype=jnp.float32)))(x)
    assert g.dtype == jnp.bfloat16   # cotangent in the INPUT dtype


def test_invalid_backend_env_raises(monkeypatch):
    """Loud-failure doctrine: a typo'd opt-in env value raises instead
    of silently measuring the unfused path (review fix)."""
    monkeypatch.setattr(mt, "_FORCE", "Flat")
    with pytest.raises(ValueError, match="APEX_TPU_MT_BACKEND"):
        mt.backend({"w": jnp.ones((4,))})
    monkeypatch.setattr(xe, "_FORCE", "palas")
    with pytest.raises(ValueError, match="APEX_TPU_XENT_BACKEND"):
        xe.backend()
    with pytest.raises(ValueError):
        mt.set_backend("nope")
    with pytest.raises(ValueError):
        xe.set_backend("nope")


# ---------------------------------------------------------------------------
# tune registry / named-scope attribution
# ---------------------------------------------------------------------------

def test_new_opspecs_registered():
    from apex_tpu.tune import sweeps
    reg = sweeps.registry()
    for op in ("conv_epilogue", "xentropy_fwd", "xentropy_bwd",
               "mt_apply"):
        assert op in reg, op
        spec = reg[op]
        for key in spec.sweep_keys():
            cands = spec.candidates(key)
            assert cands[0] == spec.heuristic(key)   # heuristic first
            assert len(cands) >= 3


def test_mt_apply_backend_sanitized():
    from apex_tpu import tune
    assert tune.mt_apply_backend(n=1024, dtype="float32") == "jnp"


def test_fused_scopes_in_lowered_hlo():
    """The named_scope metadata every kernel must carry for pyprof
    attribution: apex_xentropy / apex_conv_epilogue / apex_mt_apply all
    land in the compiled module's op metadata."""
    labels = jnp.zeros((8,), jnp.int32)
    # COMPILED module text: scope paths live in per-instruction
    # metadata (op_name), which is what pyprof's hlo join reads
    hlo = jax.jit(lambda lg: px.xent_fwd(lg, labels, 0.1)).lower(
        jnp.ones((8, 256))).compile().as_text()
    assert "apex_xentropy" in hlo

    hlo = jax.jit(lambda x: ce.bn_relu_apply(
        x, jnp.ones((128,)), jnp.zeros((128,)))).lower(
        jnp.ones((8, 128))).compile().as_text()
    assert "apex_conv_epilogue" in hlo

    p = {"w": jnp.ones((256,))}
    prev = mt.set_backend("flat")
    try:
        hlo = jax.jit(lambda t: mt.multi_tensor_scale(
            t, jnp.asarray(0.5))).lower(p).compile().as_text()
    finally:
        mt.set_backend(prev)
    assert "apex_mt_apply" in hlo
