"""Test configuration: force an 8-device virtual CPU mesh so distributed tests
run without TPU hardware.

The reference tests all require real GPUs (SURVEY.md §4). Here the XLA CPU
backend with --xla_force_host_platform_device_count=8 provides a faithful
multi-device environment for every collective path.

Note: an environment sitecustomize hook may pre-register a remote TPU platform
and override ``jax_platforms`` via ``jax.config.update`` — so the env var alone
is not enough; we update the config back to "cpu" before any backend
initialization.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# Install the jax version-compat shims (jax.shard_map / lax.axis_size on
# older releases) BEFORE any test module runs its `from jax import
# shard_map` import. conftest is imported first, so this is the one place
# that guarantees the ordering for the whole suite.
import apex_tpu  # noqa: E402,F401

# markers (slow, apexlint) are registered in pyproject.toml
# [tool.pytest.ini_options] — the single source of truth
