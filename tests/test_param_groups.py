"""Param-group tests — the analog of the reference's per-group
hyperparameters (torch optimizer param_groups) and amp's post-init
``add_param_group`` support (apex/amp/_process_optimizer.py:411-487,
tests/L0/run_amp/test_add_param_group.py:159).

Groups here are path predicates + overrides (optimizers/base.py); these tests
pin: override resolution (first match wins, defaults for the rest), the
no-decay-on-bias/BN configuration, trajectory equivalence with manually split
optimizers, add_param_group + extend_init state carry-over, the amp
composition, and the ZeRO per-element form (incl. the 2-D subgroup mesh).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import amp, optimizers, parallel
from apex_tpu.contrib.optimizers import DistributedFusedAdam

NDEV = 8


def net_params(key, prefix=""):
    ks = jax.random.split(key, 4)
    return {f"{prefix}dense": {"kernel": jax.random.normal(ks[0], (16, 8)),
                               "bias": jax.random.normal(ks[1], (8,))},
            f"{prefix}bn": {"scale": jax.random.normal(ks[2], (8,)),
                            "bias": jax.random.normal(ks[3], (8,))}}


def make_grads(key, params):
    leaves, treedef = jax.tree_util.tree_flatten(params)
    ks = jax.random.split(key, len(leaves))
    return jax.tree_util.tree_unflatten(
        treedef, [jax.random.normal(k, l.shape) for k, l in zip(ks, leaves)])


NO_DECAY = r"(bias|bn)"


def test_group_assignment_first_match_wins():
    opt = optimizers.FusedAdam(lr=1e-3, param_groups=[
        {"filter": NO_DECAY, "weight_decay": 0.0},
        {"filter": r"dense", "lr": 5e-3},
    ])
    params = net_params(jax.random.PRNGKey(0))
    groups = opt.group_assignments(params)
    # leaves (sorted dict order): bn/bias, bn/scale, dense/bias, dense/kernel
    # group 0 (no-decay) takes bn/* and dense/bias; group 1 takes
    # dense/kernel; no defaults remain.
    by_overrides = {tuple(sorted(ov.items())): idxs for idxs, ov in groups}
    assert ((("weight_decay", 0.0),) in by_overrides
            and len(by_overrides[(("weight_decay", 0.0),)]) == 3)
    assert ((("lr", 5e-3),) in by_overrides
            and len(by_overrides[(("lr", 5e-3),)]) == 1)


@pytest.mark.parametrize("opt_name", ["adam", "sgd", "lamb", "novograd",
                                      "adagrad"])
def test_no_decay_group_trajectory(opt_name):
    """Grouped optimizer == running the same optimizer with wd=0 and
    checking the no-decay leaves follow the wd=0 trajectory while decayed
    leaves follow the wd>0 trajectory."""
    mk = {
        "adam": lambda **kw: optimizers.FusedAdam(lr=1e-2, **kw),
        "sgd": lambda **kw: optimizers.FusedSGD(lr=1e-2, momentum=0.9, **kw),
        "lamb": lambda **kw: optimizers.FusedLAMB(lr=1e-2, **kw),
        "novograd": lambda **kw: optimizers.FusedNovoGrad(lr=1e-2, **kw),
        "adagrad": lambda **kw: optimizers.FusedAdagrad(lr=1e-2, **kw),
    }[opt_name]
    params = net_params(jax.random.PRNGKey(1))
    grads = [make_grads(jax.random.PRNGKey(10 + i), params) for i in range(3)]

    grouped = mk(weight_decay=0.1, param_groups=[
        {"filter": NO_DECAY, "weight_decay": 0.0}])
    st = grouped.init(params)
    got = params
    for g in grads:
        got, st = grouped.step(g, got, st)

    for wd, pred in ((0.0, lambda path: "bias" in path or "bn" in path),
                     (0.1, lambda path: not ("bias" in path
                                             or "bn" in path))):
        ref = mk(weight_decay=wd)
        # LAMB couples groups through the global grad-norm clip: feed the
        # reference the same global norm by running it on the full tree.
        st_r = ref.init(params)
        want = params
        for g in grads:
            want, st_r = ref.step(g, want, st_r)
        for kp, leaf in jax.tree_util.tree_leaves_with_path(got):
            path = "/".join(str(getattr(k, "key", k)) for k in kp)
            if pred(path):
                want_leaf = want
                for k in kp:
                    want_leaf = want_leaf[getattr(k, "key", k)]
                np.testing.assert_allclose(
                    np.asarray(leaf), np.asarray(want_leaf),
                    rtol=2e-5, atol=2e-6, err_msg=f"{opt_name}:{path} wd={wd}")


def test_group_lr_override_jit():
    """Per-group lr override, traced under jit: the grouped step must be
    jittable and honor a different lr per group."""
    params = {"a": jnp.ones((32,)), "b": jnp.ones((32,))}
    g = {"a": jnp.ones((32,)), "b": jnp.ones((32,))}
    opt = optimizers.FusedSGD(lr=0.1, param_groups=[
        {"filter": r"^b$", "lr": 0.5}])
    st = opt.init(params)
    new_p, _ = jax.jit(opt.step)(g, params, st)
    np.testing.assert_allclose(np.asarray(new_p["a"]), 1.0 - 0.1, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(new_p["b"]), 1.0 - 0.5, rtol=1e-6)


def test_add_param_group_and_extend_init():
    """The test_add_param_group flow: train net1, add net2 as a new group
    with its own lr, continue on the union — net1's momentum must carry
    over (identical to an uninterrupted run on net1)."""
    p1 = net_params(jax.random.PRNGKey(2), prefix="m1_")
    opt = optimizers.FusedSGD(lr=0.1, momentum=0.9)
    st = opt.init(p1)
    ref = optimizers.FusedSGD(lr=0.1, momentum=0.9)
    st_ref = ref.init(p1)
    w1, w_ref = p1, p1
    for i in range(3):
        g = make_grads(jax.random.PRNGKey(20 + i), p1)
        w1, st = opt.step(g, w1, st)
        w_ref, st_ref = ref.step(g, w_ref, st_ref)

    # add group: model2 params at lr 0.01
    p2 = net_params(jax.random.PRNGKey(3), prefix="m2_")
    opt.add_param_group({"filter": r"^m2_", "lr": 0.01})
    union = {**w1, **p2}
    st = opt.extend_init(st, union)

    for i in range(3):
        g = {**make_grads(jax.random.PRNGKey(30 + i), w1),
             **make_grads(jax.random.PRNGKey(40 + i), p2)}
        union, st = opt.step(g, union, st)
        # uninterrupted net1 reference sees the same net1 grads
        g1 = {k: g[k] for k in w_ref}
        w_ref, st_ref = ref.step(g1, w_ref, st_ref)

    for k in w_ref:
        for kk in w_ref[k]:
            np.testing.assert_allclose(
                np.asarray(union[k][kk]), np.asarray(w_ref[k][kk]),
                rtol=1e-5, atol=1e-6,
                err_msg="net1 trajectory changed by add_param_group")
    # net2 actually trained (lr=0.01 applied)
    assert not np.allclose(np.asarray(union["m2_dense"]["kernel"]),
                           np.asarray(p2["m2_dense"]["kernel"]))


def test_amp_optimizer_with_param_groups():
    """AmpOptimizer(O5) composes with grouped FusedSGD: no-decay on
    bias/BN through master weights."""
    params32 = net_params(jax.random.PRNGKey(4))
    inner = optimizers.FusedSGD(lr=0.1, momentum=0.9, weight_decay=0.1,
                                param_groups=[
                                    {"filter": NO_DECAY, "weight_decay": 0.0}])
    _, aopt = amp.initialize(None, inner, opt_level="O5", verbosity=0)
    params = amp.cast_model(params32, amp.resolve("O5"))
    st = aopt.init(params)

    @jax.jit
    def step(g, p, s):
        return aopt.step(g, p, s)

    g = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, p.dtype), params)  # zero grads
    new_p, st, info = step(g, params, st)
    # zero grads + momentum 0: only weight decay moves params. bias/bn must
    # be bit-identical; dense kernel must have decayed.
    np.testing.assert_array_equal(
        np.asarray(new_p["bn"]["scale"], np.float32),
        np.asarray(params["bn"]["scale"], np.float32))
    assert not np.array_equal(
        np.asarray(new_p["dense"]["kernel"], np.float32),
        np.asarray(params["dense"]["kernel"], np.float32))


# ---------------------------------------------------------------------------
# ZeRO per-element param groups
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def mesh():
    return parallel.make_mesh(axis_names=("data",))


def run_zero(opt, mesh, params, grads_seq, in_axes_state=None):
    state = opt.init(params)
    specs = opt.state_pspec()
    step = jax.jit(shard_map(
        lambda g, p, s: opt.step(g, p, s), mesh=mesh,
        in_specs=(P(), P(), specs), out_specs=(P(), specs), check_vma=False))
    state = jax.device_put(state, jax.tree_util.tree_map(
        lambda sp: NamedSharding(mesh, sp), specs))
    for g in grads_seq:
        params, state = step(g, params, state)
    return params


def test_zero_adam_param_groups_match_dense(mesh):
    params = net_params(jax.random.PRNGKey(5))
    grads = [make_grads(jax.random.PRNGKey(50 + i), params) for i in range(3)]
    pg = [{"filter": NO_DECAY, "weight_decay": 0.0, "lr": 5e-3}]

    zopt = DistributedFusedAdam(lr=1e-2, weight_decay=0.1, axis_name="data",
                                shard_count=NDEV, param_groups=pg)
    got = run_zero(zopt, mesh, params, grads)

    dense = optimizers.FusedAdam(lr=1e-2, weight_decay=0.1, param_groups=pg)
    st = dense.init(params)
    want = params
    for g in grads:
        want, st = dense.step(g, want, st)
    for kp, leaf in jax.tree_util.tree_leaves_with_path(got):
        want_leaf = want
        for k in kp:
            want_leaf = want_leaf[getattr(k, "key", k)]
        np.testing.assert_allclose(np.asarray(leaf), np.asarray(want_leaf),
                                   rtol=2e-5, atol=2e-6)


def test_zero_add_param_group_invalidates_cache(mesh):
    """add_param_group after init must take effect (the packed
    group->tensor map is rebuilt, not served stale from _spec_cache)."""
    params = net_params(jax.random.PRNGKey(8))
    grads = [make_grads(jax.random.PRNGKey(80 + i), params) for i in range(2)]
    zopt = DistributedFusedAdam(lr=1e-2, weight_decay=0.5, axis_name="data",
                                shard_count=NDEV)
    _ = zopt.init(params)  # populates _spec_cache with no groups
    zopt.add_param_group({"filter": NO_DECAY, "weight_decay": 0.0})
    got = run_zero(zopt, mesh, params, grads)

    ref = DistributedFusedAdam(
        lr=1e-2, weight_decay=0.5, axis_name="data", shard_count=NDEV,
        param_groups=[{"filter": NO_DECAY, "weight_decay": 0.0}])
    want = run_zero(ref, mesh, params, grads)
    for kp, leaf in jax.tree_util.tree_leaves_with_path(got):
        want_leaf = want
        for k in kp:
            want_leaf = want_leaf[getattr(k, "key", k)]
        np.testing.assert_allclose(np.asarray(leaf), np.asarray(want_leaf),
                                   rtol=1e-6)


def test_zero_unsupported_group_override_raises():
    params = {"w": jnp.ones((64,)), "bias": jnp.ones((8,))}
    zopt = DistributedFusedAdam(lr=1e-2, param_groups=[
        {"filter": r"bias", "eps": 1e-1}], shard_count=NDEV)
    with pytest.raises(ValueError, match="lr.*weight_decay"):
        zopt.init(params)


def test_larc_respects_group_weight_decay():
    """LARC folds each leaf's GROUP decay into its ratio — a no-decay group
    must follow the wd=0 LARC trajectory exactly."""
    from apex_tpu.parallel import LARC

    params = {"w": jax.random.normal(jax.random.PRNGKey(9), (32,)),
              "bias": jax.random.normal(jax.random.PRNGKey(10), (8,))}
    g = make_grads(jax.random.PRNGKey(90), params)

    grouped = LARC(optimizers.FusedSGD(
        lr=0.1, weight_decay=0.5,
        param_groups=[{"filter": r"bias", "weight_decay": 0.0}]))
    st = grouped.init(params)
    got, _ = grouped.step(g, params, st)

    # bias must match a fully wd=0 LARC run; w must match a wd=0.5 run
    for wd, key in ((0.0, "bias"), (0.5, "w")):
        ref = LARC(optimizers.FusedSGD(lr=0.1, weight_decay=wd))
        want, _ = ref.step(g, params, ref.init(params))
        np.testing.assert_allclose(np.asarray(got[key]),
                                   np.asarray(want[key]), rtol=1e-6,
                                   err_msg=f"{key} wd={wd}")


def test_zero_shard_count_mismatch_raises(mesh):
    params = {"w": jnp.ones((64,))}
    zopt = DistributedFusedAdam(lr=0.1, axis_name="data", shard_count=4)
    state = zopt.init(params)
    specs = zopt.state_pspec()
    with pytest.raises(ValueError, match="shard_count"):
        jax.jit(shard_map(
            lambda g, p, s: zopt.step(g, p, s), mesh=mesh,
            in_specs=(P(), P(), specs), out_specs=(P(), specs),
            check_vma=False)).lower(
                {"w": jnp.ones((64,))}, params, state)


def test_zero_subgroup_mesh_matches_dense():
    """dwu_group_size analog: 2-D mesh (2 replica groups x 4-way shard) —
    state shards over 'data' within each group, grads allreduce across
    'replica'; trajectory must equal dense Adam on mean grads."""
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh2 = Mesh(devs, ("replica", "data"))
    params = net_params(jax.random.PRNGKey(6))
    grads = [make_grads(jax.random.PRNGKey(60 + i), params) for i in range(3)]

    zopt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, axis_name="data",
                                shard_count=4, group_axis="replica")
    state = zopt.init(params)
    specs = zopt.state_pspec()
    step = jax.jit(shard_map(
        lambda g, p, s: zopt.step(g, p, s), mesh=mesh2,
        in_specs=(P(), P(), specs), out_specs=(P(), specs), check_vma=False))
    state = jax.device_put(state, jax.tree_util.tree_map(
        lambda sp: NamedSharding(mesh2, sp), specs))
    got = params
    for g in grads:
        got, state = step(g, got, state)

    dense = optimizers.FusedAdam(lr=1e-2, weight_decay=0.01)
    st = dense.init(params)
    want = params
    for g in grads:
        want, st = dense.step(g, want, st)
    for kp, leaf in jax.tree_util.tree_leaves_with_path(got):
        want_leaf = want
        for k in kp:
            want_leaf = want_leaf[getattr(k, "key", k)]
        np.testing.assert_allclose(np.asarray(leaf), np.asarray(want_leaf),
                                   rtol=2e-5, atol=2e-6)


def test_larc_respects_group_lr():
    """LARC's clip divides the trust ratio by the lr the inner step will
    actually apply per leaf — a group lr override must follow the same
    trajectory as an ungrouped LARC run at that lr (r2 review fix)."""
    from apex_tpu.parallel import LARC

    params = {"w": jax.random.normal(jax.random.PRNGKey(11), (32,)),
              "embed": jax.random.normal(jax.random.PRNGKey(12), (16,))}
    g = make_grads(jax.random.PRNGKey(91), params)

    grouped = LARC(optimizers.FusedSGD(
        lr=0.1, param_groups=[{"filter": r"embed", "lr": 1.0}]))
    st = grouped.init(params)
    got, _ = grouped.step(g, params, st)

    for lr, key in ((1.0, "embed"), (0.1, "w")):
        ref = LARC(optimizers.FusedSGD(lr=lr))
        want, _ = ref.step(g, params, ref.init(params))
        np.testing.assert_allclose(np.asarray(got[key]),
                                   np.asarray(want[key]), rtol=1e-6,
                                   err_msg=f"{key} lr={lr}")


def test_zero_extend_init_raises():
    """ZeRO state is flat sharded buffers; the per-leaf extend_init
    carry-over cannot apply — must fail loudly, not zero the moments."""
    params = {"w": jnp.ones((64,))}
    zopt = DistributedFusedAdam(lr=0.1, axis_name="data")
    state = zopt.init(params)
    with pytest.raises(NotImplementedError, match="flat sharded"):
        zopt.extend_init(state, {"w": jnp.ones((64,)),
                                 "b": jnp.ones((8,))})
