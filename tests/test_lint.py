"""apex_tpu.lint — rule-by-rule fixtures (each bad snippet fires exactly
its one rule; its corrected twin is silent), suppression handling, output
formats, the mesh axis-validation runtime twins, and the repo-wide gate
(`pytest -m apexlint` runs just that last one — the same check the CI
gate runs as `python -m apex_tpu.lint apex_tpu/ --strict`)."""

import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.lint import check_entry, check_source
from apex_tpu.lint import main as lint_main
from apex_tpu.lint import run as lint_run
from apex_tpu.lint.report import Finding, exit_code, render
from apex_tpu.lint.rules import RULES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ast_ids(src):
    return sorted({f.rule_id
                   for f in check_source("fx.py", textwrap.dedent(src))})


# ---------------------------------------------------------------------------
# AST rules: bad fixture fires exactly one rule; corrected twin is clean
# ---------------------------------------------------------------------------

AST_CASES = [
    ("APX001", """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            if jnp.any(x > 0):
                return x
            return -x
     """, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.where(jnp.any(x > 0), x, -x)
     """),
    ("APX002", """
        import jax

        @jax.jit
        def f(x):
            return float(x) * 2.0
     """, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return x.astype(jnp.float32) * 2.0
     """),
    ("APX003", """
        import jax
        import random

        @jax.jit
        def f(x):
            return x * random.random()
     """, """
        import jax

        @jax.jit
        def f(x, key):
            return x * jax.random.uniform(key)
     """),
    ("APX004", """
        import jax

        def train_step(params, state, grads):
            return params, state

        step = jax.jit(train_step)
     """, """
        import jax

        def train_step(params, state, grads):
            return params, state

        step = jax.jit(train_step, donate_argnums=(0, 1))
     """),
    ("APX005", """
        import jax.numpy as jnp

        def fwd(x):
            return x.astype(jnp.bfloat16)
     """, """
        import jax.numpy as jnp
        from apex_tpu.amp import policy

        def fwd(x, props):
            return x.astype(props.compute_dtype)
     """),
    ("APX006", """
        import jax
        from apex_tpu import trainer

        def step(state, batch):
            out = state
            jax.block_until_ready(out)
            return out, 0.0

        tr = trainer.build(step, None, None)
     """, """
        import jax
        from apex_tpu import trainer

        def step(state, batch):
            return state, 0.0

        tr = trainer.build(step, None, None)
        tr.drain()
     """),
    ("APX007", """
        import jax

        def train_step(params, batch):
            return params

        for lr in (0.1, 0.01):
            step = jax.jit(train_step, donate_argnums=(0,))
            step(lr, 2.0)
     """, """
        import jax

        def train_step(params, batch):
            return params

        step = jax.jit(train_step, donate_argnums=(0,))
        for lr in (0.1, 0.01):
            step(lr, 2.0)
     """),
]


@pytest.mark.parametrize("rule,bad,good",
                         AST_CASES, ids=[c[0] for c in AST_CASES])
def test_ast_rule_fires_and_twin_is_silent(rule, bad, good):
    assert ast_ids(bad) == [rule]
    assert ast_ids(good) == []


def test_ast_traced_context_via_shard_map_and_pallas():
    # functions reached through shard_map / pallas_call (not only @jit
    # decorators) are traced contexts too
    src = """
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def step(x):
            if jnp.any(x > 0):
                return x
            return -x

        f = jax.shard_map(step, mesh=None, in_specs=(), out_specs=())

        def kernel(x_ref, o_ref):
            o_ref[:] = x_ref[:].item()

        g = pl.pallas_call(kernel, out_shape=None)
    """
    assert ast_ids(src) == ["APX001", "APX002"]


def test_ast_python_scalar_control_flow_is_fine():
    # Python-bool kwargs driving branches (the kernels' `if causal:`
    # idiom) must NOT fire APX001
    src = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x, causal=True):
            if causal:
                return x
            return -x
    """
    assert ast_ids(src) == []


def test_ast_global_statement_fires_apx003():
    src = """
        import jax

        _calls = 0

        @jax.jit
        def f(x):
            global _calls
            _calls += 1
            return x
    """
    assert ast_ids(src) == ["APX003"]


# ---------------------------------------------------------------------------
# APX006: host sync inside a compiled-step definition
# ---------------------------------------------------------------------------

def test_apx006_block_until_ready_in_jit_fn_fires():
    # block_until_ready isn't a concretization, so APX002 ignores it —
    # APX006 owns the host-sync hazard, in jit-traced steps too
    src = """
        import jax

        @jax.jit
        def step(state, batch):
            jax.block_until_ready(state)
            return state
    """
    assert ast_ids(src) == ["APX006"]


def test_apx006_item_in_built_step_fires():
    src = """
        from apex_tpu import trainer

        def step(state, batch):
            loss = (state * batch).sum()
            print(loss.item())
            return state, loss

        tr = trainer.build(step, None, None)
    """
    assert ast_ids(src) == ["APX006"]


def test_apx006_float_on_step_arg_in_built_step_fires():
    src = """
        from apex_tpu.trainer import build

        def step(state, batch):
            lr = float(batch)
            return state * lr, lr

        tr = build(step, None, None)
    """
    assert ast_ids(src) == ["APX006"]


def test_apx006_item_in_jit_fn_stays_apx002():
    # in a TRACED function the concretization is APX002's finding —
    # exactly one rule per hazard
    src = """
        import jax

        @jax.jit
        def f(x):
            return x.item()
    """
    assert ast_ids(src) == ["APX002"]


def test_apx006_host_loop_sync_outside_step_is_silent():
    src = """
        import jax
        from apex_tpu import trainer

        def step(state, batch):
            return state, 0.0

        tr = trainer.build(step, None, None)
        out = tr.step(None, None)
        jax.block_until_ready(out)
    """
    assert ast_ids(src) == []


def test_apx006_suppression(tmp_path):
    bad = ("import jax\n"
           "from apex_tpu import trainer\n"
           "def step(state, batch):\n"
           "    jax.block_until_ready(state)"
           "  # apexlint: disable=APX006 -- test fixture\n"
           "    return state, 0.0\n"
           "tr = trainer.build(step, None, None)\n")
    (tmp_path / "sup.py").write_text(bad)
    active, suppressed = lint_run([str(tmp_path / "sup.py")], jaxpr=False)
    assert not active
    assert [f.rule_id for f in suppressed] == ["APX006"]


# ---------------------------------------------------------------------------
# APX007: step re-jit in a loop / un-donated trainer.build call sites
# ---------------------------------------------------------------------------

def test_apx007_trainer_build_in_loop_fires():
    src = """
        from apex_tpu import trainer

        def step(state, batch):
            return state, 0.0

        for depth in (1, 2, 4):
            tr = trainer.build(step, None, None)
    """
    assert ast_ids(src) == ["APX007"]


def test_apx007_trainer_build_outside_loop_is_silent():
    src = """
        from apex_tpu import trainer

        def step(state, batch):
            return state, 0.0

        tr = trainer.build(step, None, None)
        for i in range(4):
            tr.step(None, None)
    """
    assert ast_ids(src) == []


def test_apx007_donate_false_keyword_fires():
    src = """
        from apex_tpu import trainer

        def step(state, batch):
            return state, 0.0

        tr = trainer.build(step, None, None, donate=False)
    """
    assert ast_ids(src) == ["APX007"]


def test_apx007_donate_false_in_trainer_config_fires():
    src = """
        from apex_tpu import trainer

        def step(state, batch):
            return state, 0.0

        tr = trainer.build(
            step, None, None,
            config=trainer.TrainerConfig(donate=False, in_flight=2))
    """
    assert ast_ids(src) == ["APX007"]


def test_apx007_bare_build_import_with_donate_false_fires():
    src = """
        from apex_tpu.trainer import build

        def step(state, batch):
            return state, 0.0

        tr = build(step, None, None, donate=False)
    """
    assert ast_ids(src) == ["APX007"]


def test_apx007_donated_build_is_silent():
    src = """
        from apex_tpu import trainer

        def step(state, batch):
            return state, 0.0

        tr = trainer.build(
            step, None, None,
            config=trainer.TrainerConfig(donate=True, in_flight=2))
    """
    assert ast_ids(src) == []


def test_apx007_unrelated_builder_objects_are_silent():
    # foreign .build() APIs (a protobuf Builder, etc.) must not fire —
    # only dotted paths routing through a `trainer` component count
    src = """
        def make(msg_builder):
            for i in range(3):
                msg_builder.build(i)
    """
    assert ast_ids(src) == []


def test_apx007_bare_build_in_loop_fires():
    # `from apex_tpu.trainer import build` used in a loop is the same
    # re-compile hazard as the dotted form
    src = """
        from apex_tpu.trainer import build

        def step(state, batch):
            return state, 0.0

        for depth in (1, 2):
            tr = build(step, None, None)
    """
    assert ast_ids(src) == ["APX007"]


def test_apx007_foreign_dotted_build_in_loop_is_silent():
    src = """
        def make(msg_builder):
            for i in range(3):
                msg_builder.build(i)
    """
    assert ast_ids(src) == []


def test_apx007_jit_in_while_loop_fires():
    src = """
        import jax

        def helper(x):
            return x

        def run():
            n = 0
            while n < 3:
                f = jax.jit(helper, donate_argnums=(0,))
                n += 1
    """
    assert ast_ids(src) == ["APX007"]


def test_apx007_jit_in_comprehension_is_silent():
    # building a list of differently-configured jits is a legitimate
    # pattern; comprehensions are not loop re-jits
    src = """
        import jax

        def helper(x):
            return x

        fns = [jax.jit(helper, static_argnums=(i,)) for i in range(2)]
    """
    assert ast_ids(src) == []


def test_apx007_suppression_honored(tmp_path):
    bad = ("import jax\n"
           "def helper(x):\n"
           "    return x\n"
           "for i in range(2):\n"
           "    f = jax.jit(helper)"
           "  # apexlint: disable=APX007 -- test fixture\n")
    (tmp_path / "sup.py").write_text(bad)
    active, suppressed = lint_run([str(tmp_path / "sup.py")], jaxpr=False)
    assert not active
    assert [f.rule_id for f in suppressed] == ["APX007"]


# ---------------------------------------------------------------------------
# jaxpr rules
# ---------------------------------------------------------------------------

def test_jaxpr_apx101_fp32_matmul_under_bf16_policy():
    p32 = jnp.ones((8, 8), jnp.float32)
    x16 = jnp.ones((4, 8), jnp.bfloat16)

    def bad(p, x):
        return x @ p            # p never saw the amp cast -> silent fp32

    def good(p, x):
        return x @ p.astype(jnp.bfloat16)

    ids = {f.rule_id for f in check_entry(bad, (p32, x16), opt_level="O5")}
    assert ids == {"APX101"}
    assert check_entry(good, (p32, x16), opt_level="O5") == []
    # fp32 is the POLICY at O0: the same program is clean there
    assert check_entry(bad, (p32, x16), opt_level="O0") == []


def test_jaxpr_apx101_explicit_fp32_island_is_intended():
    # both operands explicitly upcast from bf16 (fp32-softmax idiom):
    # that is the policy's own fp32 island, not a bypass
    x16 = jnp.ones((4, 8), jnp.bfloat16)

    def f(x):
        x32 = x.astype(jnp.float32)
        return x32 @ x32.T

    assert check_entry(f, (x16,), opt_level="O5") == []


def test_jaxpr_apx102_bf16_accumulation():
    # NB jnp.sum already upcasts float16/bfloat16 accumulators itself;
    # the hazard is raw lax reductions and scans that keep the carry low
    x16 = jnp.ones((128,), jnp.bfloat16)

    def bad(x):
        return jnp.cumsum(x)[-1]

    def good(x):
        return jnp.cumsum(x.astype(jnp.float32))[-1]

    ids = {f.rule_id for f in check_entry(bad, (x16,), opt_level="O5")}
    assert ids == {"APX102"}
    assert check_entry(good, (x16,), opt_level="O5") == []


def _smap(fn, mesh):
    from jax.sharding import PartitionSpec as P
    return jax.shard_map(fn, mesh=mesh, in_specs=(P(),),
                         out_specs=P(), check_vma=False)


def test_jaxpr_apx103_unknown_collective_axis():
    from jax.sharding import Mesh
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
    x = jnp.ones((4,))

    def bad(x):
        return jax.lax.psum(x, "dp")      # mesh names it "data"

    def good(x):
        return jax.lax.psum(x, "data")

    ids = {f.rule_id for f in check_entry(
        _smap(bad, mesh), (x,), mesh_axes=("data",))}
    assert ids == {"APX103"}
    assert check_entry(_smap(good, mesh), (x,),
                       mesh_axes=("data",)) == []


def test_jaxpr_apx104_inconsistent_axis_index_groups():
    from jax.sharding import Mesh
    n = 2
    assert len(jax.devices()) >= n    # conftest forces an 8-device mesh
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("data",))
    pairs = [list(range(n))]          # one group spanning the axis
    singles = [[i] for i in range(n)]  # per-device singleton groups
    x = jnp.ones((4,))

    def bad(x):
        a = jax.lax.psum(x, "data", axis_index_groups=pairs)
        b = jax.lax.psum(x, "data", axis_index_groups=singles)
        return a + b

    def good(x):
        # grouped + GLOBAL on one axis is the supported hierarchical
        # pattern (SyncBN subgroups + whole-axis grad psum): no finding
        a = jax.lax.psum(x, "data", axis_index_groups=pairs)
        b = jax.lax.psum(x * 2, "data", axis_index_groups=pairs)
        return a + b + jax.lax.psum(x, "data")

    findings = check_entry(_smap(bad, mesh), (x,), mesh_axes=("data",))
    assert {f.rule_id for f in findings} == {"APX104"}
    assert len(findings) == 1         # one finding per axis, not per eqn
    assert check_entry(_smap(good, mesh), (x,),
                       mesh_axes=("data",)) == []


def test_jaxpr_apx106_fp32_psum_under_reduce_dtype():
    from jax.sharding import Mesh
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
    x = jnp.ones((64, 64))            # 4096 elements: payload-sized

    def bad(x):
        # raw fp32 psum of a gradient-sized tree — bypasses the
        # configured compressed wire path
        return jax.lax.psum(x, "data")

    def good(x):
        from apex_tpu.parallel import allreduce_gradients
        return allreduce_gradients({"w": x}, "data",
                                   reduce_dtype="bf16")["w"]

    ids = {f.rule_id for f in check_entry(
        _smap(bad, mesh), (x,), mesh_axes=("data",),
        reduce_dtype="bfloat16")}
    assert ids == {"APX106"}
    # the compressed call site is clean under the same declaration
    assert check_entry(_smap(good, mesh), (x,), mesh_axes=("data",),
                       reduce_dtype="bfloat16") == []
    # no reduce_dtype declared: the rule is disarmed
    assert check_entry(_smap(bad, mesh), (x,),
                       mesh_axes=("data",)) == []


def test_jaxpr_apx106_scalar_psum_is_exempt():
    from jax.sharding import Mesh
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
    x = jnp.ones((64, 64))

    def norms(x):
        # scalar reductions (grad norms, loss pmean) legitimately ride
        # fp32 even on a compressed wire — payload threshold exempts them
        return jax.lax.psum(jnp.sum(x * x), "data")

    assert check_entry(_smap(norms, mesh), (x,), mesh_axes=("data",),
                       reduce_dtype="bfloat16") == []


def test_jaxpr_apx105_pallas_block_misalignment():
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:] * 2

    def call(block):
        return lambda x: pl.pallas_call(
            kernel, grid=(2,),
            in_specs=[pl.BlockSpec(block, lambda i: (i, 0))],
            out_specs=pl.BlockSpec(block, lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((8, 256), jnp.float32),
            interpret=True)(x)

    x = jnp.ones((8, 256))
    ids = {f.rule_id for f in check_entry(call((4, 100)), (x,))}
    assert ids == {"APX105"}
    assert check_entry(call((8, 128)), (x,)) == []


def test_ast_apx005_float8_literal_fires():
    # the fp8 tier's dtypes are policy-owned exactly like bf16/fp16:
    # a hardcoded float8 literal outside amp/lowp is a drift hazard
    src = """
        import jax.numpy as jnp

        def fwd(x):
            return x.astype(jnp.float8_e4m3fn)
     """
    assert ast_ids(src) == ["APX005"]
    src_e5m2 = """
        import jax.numpy as jnp

        def bwd(g):
            return g.astype("float8_e5m2")
     """
    assert ast_ids(src_e5m2) == ["APX005"]


def test_jaxpr_apx107_unscaled_fp8_dot_fires():
    x = jnp.ones((16, 32))
    w = jnp.ones((32, 8))

    def bad(x, w):
        # raw cast, no scale op reaches the operands: numerically
        # unanchored fp8 (anything past +-448 silently saturates)
        x8 = x.astype(jnp.float8_e4m3fn)
        w8 = w.astype(jnp.float8_e4m3fn)
        return jax.lax.dot_general(x8, w8, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)

    ids = {f.rule_id for f in check_entry(bad, (x, w))}
    assert ids == {"APX107"}


def test_jaxpr_apx107_scaled_fp8_dot_is_clean():
    from apex_tpu.lowp import fp8_matmul
    x = jnp.ones((16, 32))
    w = jnp.ones((32, 8))

    # the lowp entry point quantizes AT A SCALE: provenance reaches both
    # operands and the rule stays silent — forward and backward
    def good(x, w):
        return jnp.sum(fp8_matmul(x, w) ** 2)

    assert check_entry(good, (x, w)) == []
    assert check_entry(jax.grad(good), (x, w)) == []


def test_jaxpr_apx107_fake_quant_grad_is_clean():
    from apex_tpu.lowp import fake_quant
    x = jnp.ones((16, 16))

    def step(x):
        return jnp.sum(fake_quant(x, jnp.float32(2.0)) @ x)

    assert check_entry(step, (x,)) == []
    assert check_entry(jax.grad(step), (x,)) == []


def test_jaxpr_apx107_non_fp8_dot_unaffected():
    x16 = jnp.ones((8, 8), jnp.bfloat16)

    def f(x):
        return x @ x

    assert check_entry(f, (x16,)) == []


# ---------------------------------------------------------------------------
# suppressions / formats / CLI plumbing
# ---------------------------------------------------------------------------

def test_suppression_comment_silences_finding(tmp_path):
    bad = "import jax.numpy as jnp\ny = jnp.zeros((4,), jnp.bfloat16)\n"
    sup = ("import jax.numpy as jnp\n"
           "y = jnp.zeros((4,), jnp.bfloat16)"
           "  # apexlint: disable=APX005 -- test fixture\n")
    (tmp_path / "bad.py").write_text(bad)
    (tmp_path / "sup.py").write_text(sup)

    active, suppressed = lint_run([str(tmp_path / "bad.py")], jaxpr=False)
    assert [f.rule_id for f in active] == ["APX005"] and not suppressed

    active, suppressed = lint_run([str(tmp_path / "sup.py")], jaxpr=False)
    assert not active
    assert [f.rule_id for f in suppressed] == ["APX005"]


def test_clean_file_has_no_findings(tmp_path):
    clean = textwrap.dedent("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(params, grads):
            return jax.tree_util.tree_map(
                lambda p, g: p - 0.1 * g, params, grads)
    """)
    (tmp_path / "clean.py").write_text(clean)
    active, suppressed = lint_run([str(tmp_path / "clean.py")],
                                  jaxpr=False)
    assert not active and not suppressed


def test_github_format_and_exit_codes():
    err = Finding("APX101", "a.py", 3, "boom")
    warn = Finding("APX005", "a.py", 7, "meh")
    out = render([err, warn], [], fmt="github")
    assert "::error file=a.py,line=3" in out
    assert "::warning file=a.py,line=7" in out
    assert exit_code([warn]) == 0           # warnings pass by default
    assert exit_code([warn], strict=True) == 1
    assert exit_code([err]) == 1            # errors always fail


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in RULES:
        assert rid in out


# ---------------------------------------------------------------------------
# mesh axis validation — the runtime twin of APX103
# ---------------------------------------------------------------------------

def test_require_axis_names_offender():
    from jax.sharding import Mesh
    from apex_tpu.parallel import require_axis
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
    require_axis(mesh, "data")              # fine
    with pytest.raises(ValueError, match=r"'dp'.*\('data',\)"):
        require_axis(mesh, "dp")


def test_bound_axis_size_clear_error():
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.parallel import allreduce_gradients, bound_axis_size
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))

    def ok(x):
        return jnp.float32(bound_axis_size("data")) * x

    out = jax.shard_map(ok, mesh=mesh, in_specs=(P(),), out_specs=P(),
                        check_vma=False)(jnp.ones((2,)))
    assert out.tolist() == [1.0, 1.0]

    def bad(x):
        return allreduce_gradients(x, "bogus")

    with pytest.raises(ValueError, match="'bogus' is not bound"):
        jax.make_jaxpr(jax.shard_map(
            bad, mesh=mesh, in_specs=(P(),), out_specs=P(),
            check_vma=False))(jnp.ones((2,)))


def test_ddp_train_step_validates_mesh_axis():
    from jax.sharding import Mesh
    from apex_tpu import optimizers
    from apex_tpu.parallel import ddp_train_step
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
    with pytest.raises(ValueError, match="'dp' is not an axis"):
        ddp_train_step(lambda p, b: jnp.sum(p * b),
                       optimizers.FusedAdam(), mesh, axis_name="dp")


# ---------------------------------------------------------------------------
# the repo-wide gate (this is what `pytest -m apexlint` selects, and the
# same invocation ci/gate.sh runs)
# ---------------------------------------------------------------------------

@pytest.mark.apexlint
def test_repo_lint_clean():
    rc = lint_main([os.path.join(REPO, "apex_tpu"),
                    os.path.join(REPO, "__graft_entry__.py"),
                    "--strict", "--spmd"])
    assert rc == 0
