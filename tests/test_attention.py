"""Attention tests — port of the reference MHA parity suite
(apex/contrib/test/: fast impl vs default impl equality) plus ring-attention
correctness for the added sequence-parallel path."""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.ops.attention import (attention_reference, flash_attention,
                                    ring_self_attention,
                                    ulysses_self_attention)
from apex_tpu.contrib.multihead_attn import (SelfMultiheadAttn,
                                             EncdecMultiheadAttn,
                                             masked_softmax_dropout)


def qkv(key, b=2, h=4, s=128, d=64, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    mk = lambda k: jax.random.normal(k, (b, h, s, d), dtype)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [128, 256, 200])  # 200: padding path
def test_flash_matches_reference(causal, s):
    q, k, v = qkv(jax.random.PRNGKey(0), s=s)
    out_ref = attention_reference(q, k, v, causal=causal)
    out_flash = flash_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_cross_attention_lengths(causal):
    # sq != sk; causal uses the bottom-right-anchored diagonal
    # (col <= row + sk - sq), same as attention_reference
    q, _, _ = qkv(jax.random.PRNGKey(1), s=128)
    _, k, v = qkv(jax.random.PRNGKey(2), s=384)
    out_ref = attention_reference(q, k, v, causal=causal)
    out_flash = flash_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_grads_match_reference():
    q, k, v = qkv(jax.random.PRNGKey(3), s=128)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-4)


def test_flash_bf16():
    q, k, v = qkv(jax.random.PRNGKey(4), s=128, dtype=jnp.bfloat16)
    out_ref = attention_reference(q, k, v, causal=True)
    out_flash = flash_attention(q, k, v, True)
    assert out_flash.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out_flash, np.float32),
                               np.asarray(out_ref, np.float32),
                               rtol=3e-2, atol=3e-2)


# ---------------------------------------------------------------------------
# Ring attention
# ---------------------------------------------------------------------------

NDEV = 8


@pytest.fixture(scope="module")
def mesh():
    return parallel.make_mesh(axis_names=("seq",))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(mesh, causal):
    b, h, s, d = 2, 2, NDEV * 32, 32
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))

    want = attention_reference(q, k, v, causal=causal)

    def per_device(q_, k_, v_):
        return ring_self_attention(q_, k_, v_, "seq", causal=causal)

    got = jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(P(None, None, "seq", None),) * 3,
        out_specs=P(None, None, "seq", None), check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# Modules (fast vs default impl parity — the reference contrib test shape)
# ---------------------------------------------------------------------------

def test_self_mha_fast_vs_default():
    e, h = 64, 4
    x = jax.random.normal(jax.random.PRNGKey(6), (2, 128, e))
    m_fast = SelfMultiheadAttn(embed_dim=e, num_heads=h, impl="fast")
    m_def = SelfMultiheadAttn(embed_dim=e, num_heads=h, impl="default")
    params = m_fast.init(jax.random.PRNGKey(7), x)
    y1 = m_fast.apply(params, x)
    y2 = m_def.apply(params, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=2e-4,
                               atol=2e-4)


def test_self_mha_norm_add():
    e, h = 32, 2
    x = jax.random.normal(jax.random.PRNGKey(8), (2, 64, e))
    m = SelfMultiheadAttn(embed_dim=e, num_heads=h, include_norm_add=True,
                          impl="default")
    params = m.init(jax.random.PRNGKey(9), x)
    y = m.apply(params, x)
    assert "FusedLayerNorm_0" in params["params"]
    # residual: zeroing the attention output path must return x itself
    zeroed = jax.tree.map(jnp.zeros_like, params)
    y0 = m.apply(zeroed, x)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(x), atol=1e-6)


def test_self_mha_additive_mask():
    e, h, s = 32, 2, 16
    x = jax.random.normal(jax.random.PRNGKey(10), (1, s, e))
    m = SelfMultiheadAttn(embed_dim=e, num_heads=h, impl="default")
    params = m.init(jax.random.PRNGKey(11), x)
    # mask out the second half of keys
    mask = jnp.where(jnp.arange(s) < s // 2, 0.0, -1e30)[None, None, None, :]
    y = m.apply(params, x, attn_mask=mask)
    # equivalent: truncate keys — recompute manually via module on half seq?
    # instead check masked vs unmasked differ and masked==masked (determinism)
    y2 = m.apply(params, x, attn_mask=mask)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y2))
    y_unmasked = m.apply(params, x)
    assert not np.allclose(np.asarray(y), np.asarray(y_unmasked))


def test_encdec_mha():
    e, h = 32, 2
    q = jax.random.normal(jax.random.PRNGKey(12), (2, 24, e))
    kv = jax.random.normal(jax.random.PRNGKey(13), (2, 48, e))
    m = EncdecMultiheadAttn(embed_dim=e, num_heads=h, impl="default")
    params = m.init(jax.random.PRNGKey(14), q, kv)
    y = m.apply(params, q, kv)
    assert y.shape == (2, 24, e)
    m_fast = EncdecMultiheadAttn(embed_dim=e, num_heads=h, impl="fast")
    y_fast = m_fast.apply(params, q, kv)
    np.testing.assert_allclose(np.asarray(y_fast), np.asarray(y), rtol=2e-4,
                               atol=2e-4)


def test_masked_softmax_dropout_deterministic():
    s = jax.random.normal(jax.random.PRNGKey(15), (2, 4, 8, 8))
    p = masked_softmax_dropout(s)
    np.testing.assert_allclose(np.asarray(p.sum(-1)), 1.0, rtol=1e-5)
    rng = jax.random.PRNGKey(16)
    pd = masked_softmax_dropout(s, dropout_rate=0.5, rng=rng,
                                deterministic=False)
    assert float((np.asarray(pd) == 0).mean()) > 0.3


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(mesh, causal):
    """Ulysses all-to-all SP: same math as dense attention; heads must
    divide by the axis size (here 8 heads / 8 devices)."""
    b, h, s, d = 2, NDEV, NDEV * 16, 32
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))

    want = attention_reference(q, k, v, causal=causal)

    def per_device(q_, k_, v_):
        return ulysses_self_attention(q_, k_, v_, "seq", causal=causal)

    got = jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(P(None, None, "seq", None),) * 3,
        out_specs=P(None, None, "seq", None), check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_grads_match_dense(mesh):
    b, h, s, d = 1, NDEV, NDEV * 16, 32
    ks = jax.random.split(jax.random.PRNGKey(8), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))

    def dense_loss(q_, k_, v_):
        o = attention_reference(q_, k_, v_, causal=True)
        return jnp.sum(o * o)

    want = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)

    def per_device(q_, k_, v_):
        def loss(qq, kk, vv):
            o = ulysses_self_attention(qq, kk, vv, "seq", causal=True)
            # LOCAL loss term: the global loss is the implicit sum of the
            # per-device terms, and the all_to_all transposes route each
            # device's cotangents back to the shards they came from (the
            # same pattern as the ring-attention grad step in
            # __graft_entry__.dryrun_multichip).
            return jnp.sum(o * o)
        return jax.grad(loss, argnums=(0, 1, 2))(q_, k_, v_)

    spec = P(None, None, "seq", None)
    got = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(spec,) * 3,
        out_specs=(spec,) * 3, check_vma=False))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4)


def test_ulysses_head_count_check(mesh):
    q = jnp.ones((1, 3, NDEV * 8, 16))  # 3 heads not divisible by 8

    def per_device(q_):
        return ulysses_self_attention(q_, q_, q_, "seq")

    with pytest.raises(ValueError, match="num_heads"):
        jax.jit(shard_map(
            per_device, mesh=mesh,
            in_specs=(P(None, None, "seq", None),),
            out_specs=P(None, None, "seq", None), check_vma=False))(q)


@pytest.mark.slow  # full bwd parity matrix; fwd parity stays in tier-1
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sq,sk", [(128, 128), (200, 200), (128, 384),
                                   (96, 160)])
def test_flash_bwd_matches_reference(causal, sq, sk):
    """Pallas backward: dq/dk/dv parity with autodiff of the dense
    reference, incl. padded (non-multiple-of-128) and cross-length cases
    (causal cross-length uses the bottom-right-anchored diagonal)."""
    ks = jax.random.split(jax.random.PRNGKey(20), 3)
    q = jax.random.normal(ks[0], (2, 2, sq, 64))
    k = jax.random.normal(ks[1], (2, 2, sk, 64))
    v = jax.random.normal(ks[2], (2, 2, sk, 64))
    g = jax.random.normal(jax.random.PRNGKey(21), (2, 2, sq, 64))

    _, vjp_flash = jax.vjp(
        lambda a, b, c: flash_attention(a, b, c, causal), q, k, v)
    _, vjp_ref = jax.vjp(
        lambda a, b, c: attention_reference(a, b, c, causal=causal), q, k, v)
    for got, want in zip(vjp_flash(g), vjp_ref(g)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-4)


def test_flash_bwd_bf16():
    ks = jax.random.split(jax.random.PRNGKey(22), 3)
    mk = lambda kk, s: jax.random.normal(kk, (1, 2, s, 64), jnp.bfloat16)
    q, k, v = mk(ks[0], 128), mk(ks[1], 128), mk(ks[2], 128)
    g = jax.random.normal(jax.random.PRNGKey(23), (1, 2, 128, 64),
                          jnp.bfloat16)
    _, vjp_flash = jax.vjp(
        lambda a, b, c: flash_attention(a, b, c, True), q, k, v)
    _, vjp_ref = jax.vjp(
        lambda a, b, c: attention_reference(a, b, c, causal=True), q, k, v)
    for got, want in zip(vjp_flash(g), vjp_ref(g)):
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32),
                                   rtol=6e-2, atol=6e-2)


# ---------------------------------------------------------------------------
# fused dropout (reference fast MHA fuses dropout into softmax, dropout.h)
# ---------------------------------------------------------------------------

def test_flash_dropout_matches_reference_same_mask():
    """Flash fused dropout vs the jnp reference using the SAME counter
    mask — outputs and all three grads must agree."""
    ks = jax.random.split(jax.random.PRNGKey(30), 3)
    q = jax.random.normal(ks[0], (2, 2, 128, 64))
    k = jax.random.normal(ks[1], (2, 2, 128, 64))
    v = jax.random.normal(ks[2], (2, 2, 128, 64))
    g = jax.random.normal(jax.random.PRNGKey(31), (2, 2, 128, 64))
    rate, seed = 0.3, 1234

    o_f, vjp_f = jax.vjp(lambda a, b, c: flash_attention(
        a, b, c, True, dropout_rate=rate, dropout_seed=seed), q, k, v)
    o_r, vjp_r = jax.vjp(lambda a, b, c: attention_reference(
        a, b, c, causal=True, dropout_rate=rate, dropout_seed=seed),
        q, k, v)
    np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_r),
                               rtol=2e-4, atol=2e-4)
    for got, want in zip(vjp_f(g), vjp_r(g)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-4)


def test_flash_dropout_statistics():
    """Drop fraction ~ rate; different seeds give different patterns;
    same seed reproduces exactly."""
    ks = jax.random.split(jax.random.PRNGKey(32), 3)
    q, k, v = (jax.random.normal(kk, (1, 2, 256, 64)) for kk in ks)
    rate = 0.5
    o1 = flash_attention(q, k, v, False, dropout_rate=rate, dropout_seed=7)
    o2 = flash_attention(q, k, v, False, dropout_rate=rate, dropout_seed=7)
    o3 = flash_attention(q, k, v, False, dropout_rate=rate, dropout_seed=8)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    assert not np.allclose(np.asarray(o1), np.asarray(o3))
    # expectation preserved: mean of dropped ~ mean of undropped
    o0 = flash_attention(q, k, v, False)
    np.testing.assert_allclose(float(jnp.mean(o1)), float(jnp.mean(o0)),
                               atol=0.02)


def test_dropout_keep_mask_rate():
    from apex_tpu.ops.attention import dropout_keep_mask

    row = jax.lax.broadcasted_iota(jnp.int32, (512, 512), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (512, 512), 1)
    for rate in (0.1, 0.5, 0.9):
        keep = dropout_keep_mask(jnp.int32(99), jnp.int32(3), row, col,
                                 rate)
        frac = float(jnp.mean(keep.astype(jnp.float32)))
        assert abs(frac - (1.0 - rate)) < 0.01, (rate, frac)


def test_self_mha_fast_dropout_trains():
    """Module-level: fast path with dropout active produces a different
    (but finite) output per rng and matches eval mode when deterministic."""
    e, h = 64, 4
    x = jax.random.normal(jax.random.PRNGKey(33), (2, 128, e))
    m = SelfMultiheadAttn(embed_dim=e, num_heads=h, dropout=0.4,
                          impl="fast")
    params = m.init(jax.random.PRNGKey(34), x)
    y_det = m.apply(params, x, deterministic=True)
    y_tr1 = m.apply(params, x, deterministic=False,
                    dropout_rng=jax.random.PRNGKey(1))
    y_tr2 = m.apply(params, x, deterministic=False,
                    dropout_rng=jax.random.PRNGKey(2))
    assert np.isfinite(np.asarray(y_tr1)).all()
    assert not np.allclose(np.asarray(y_tr1), np.asarray(y_tr2))
    assert not np.allclose(np.asarray(y_tr1), np.asarray(y_det))


# ---------------------------------------------------------------------------
# Fused additive-mask / bias (reference *_bias_additive_mask kernels)
# ---------------------------------------------------------------------------

@pytest.mark.slow  # full bias-broadcast matrix (see tier-1 budget note)
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 4, 128, 128), (2, 1, 1, 128),
                                   (1, 4, 128, 128), (1, 1, 1, 128)])
def test_flash_bias_matches_reference(causal, shape):
    """Additive score bias fused into the flash kernels: fwd + grads match
    the dense reference for full, pad-mask, and broadcast bias shapes."""
    q, k, v = qkv(jax.random.PRNGKey(40), s=128)
    bias = jax.random.normal(jax.random.PRNGKey(41), shape) * 2.0
    bias = jnp.where(bias > 1.5, -3e4, bias)  # some fully-masked entries
    g = jax.random.normal(jax.random.PRNGKey(42), q.shape)

    out_ref = attention_reference(q, k, v, bias=bias, causal=causal)
    out_fl = flash_attention(q, k, v, causal, bias=bias)
    np.testing.assert_allclose(np.asarray(out_fl), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-4)

    _, vjp_fl = jax.vjp(
        lambda a, b, c: flash_attention(a, b, c, causal, bias=bias), q, k, v)
    _, vjp_ref = jax.vjp(
        lambda a, b, c: attention_reference(a, b, c, bias=bias,
                                            causal=causal), q, k, v)
    # atol 2e-3: f32 carries ~2e-3 exponent precision at the -3e4 mask
    # magnitude, so reconstructed probs near masked entries wobble slightly
    for got, want in zip(vjp_fl(g), vjp_ref(g)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=3e-3, atol=2e-3)


def test_flash_bias_ragged_sq_positive_bias_grads_finite():
    """Regression (r3 ADVICE): with sq NOT a block multiple and a large
    POSITIVE additive bias, the backward's padded query rows used to
    reconstruct p = exp(bias - 0) = inf from the 0.0-filled lse pad,
    NaN-ing the whole dk/dv block. Padded lse rows now fill with +1e30 so
    p is exactly 0 there; grads must be finite and match the reference."""
    sq = 200  # not a multiple of any block size
    ks = jax.random.split(jax.random.PRNGKey(50), 3)
    q = jax.random.normal(ks[0], (1, 2, sq, 64))
    k = jax.random.normal(ks[1], (1, 2, sq, 64))
    v = jax.random.normal(ks[2], (1, 2, sq, 64))
    g = jax.random.normal(jax.random.PRNGKey(51), q.shape)
    # additive bias well past the f32 exp overflow point (~88)
    bias = jnp.full((1, 1, sq, sq), 100.0)

    _, vjp_fl = jax.vjp(
        lambda a, b, c: flash_attention(a, b, c, bias=bias), q, k, v)
    _, vjp_ref = jax.vjp(
        lambda a, b, c: attention_reference(a, b, c, bias=bias), q, k, v)
    for got, want in zip(vjp_fl(g), vjp_ref(g)):
        assert np.isfinite(np.asarray(got)).all()
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=3e-3, atol=2e-3)


def test_flash_bwd_two_pass_fallback_matches_reference(monkeypatch):
    """PURE two-pass (dKdV then dQ) coverage at multi-block query
    geometry: budget 0 kills the fused plan and the unreachable segment
    length keeps the r5 segmented wrapper out (bias/dropout shapes
    still take this path at long lengths; segmentation has its own
    tests below)."""
    import apex_tpu.ops.attention as A

    monkeypatch.setattr(A, "_FUSED_BWD_DQ_SCRATCH_BYTES", 0)
    monkeypatch.setattr(A, "_segment_rows", lambda d: 1 << 30)
    ks = jax.random.split(jax.random.PRNGKey(52), 3)
    q = jax.random.normal(ks[0], (2, 2, 200, 64))
    k = jax.random.normal(ks[1], (2, 2, 200, 64))
    v = jax.random.normal(ks[2], (2, 2, 200, 64))
    g = jax.random.normal(jax.random.PRNGKey(53), q.shape)
    _, vjp_fl = jax.vjp(
        lambda a, b, c: flash_attention(a, b, c, True), q, k, v)
    _, vjp_ref = jax.vjp(
        lambda a, b, c: attention_reference(a, b, c, causal=True), q, k, v)
    for got, want in zip(vjp_fl(g), vjp_ref(g)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-4)


@pytest.mark.slow  # segmented-backward matrix (see tier-1 budget note)
@pytest.mark.parametrize("causal,sq,sk", [
    (True, 640, 640),     # 256-row segments, causal column trimming
    (False, 640, 640),    # non-causal: every segment sees all keys
    (True, 600, 600),     # ragged final segment + ragged blocks
    (True, 640, 896),     # cross-length, bottom-right diagonal
])
def test_flash_bwd_segmented_matches_reference(monkeypatch, causal, sq,
                                               sk):
    """>16k sequences run scratch-sized SEGMENTED fused sweeps (VERDICT
    r4 next #3). Shrink the scratch budget so 256-row segments engage at
    test size with each sub-call genuinely on the fused kernel, and
    check full grad parity incl. the causal key-window trimming."""
    import apex_tpu.ops.attention as A

    monkeypatch.setattr(A, "_FUSED_BWD_DQ_SCRATCH_BYTES", 256 * 128 * 4)
    assert A._segment_rows(64) == 256
    ks = jax.random.split(jax.random.PRNGKey(54), 3)
    q = jax.random.normal(ks[0], (2, 2, sq, 64))
    k = jax.random.normal(ks[1], (2, 2, sk, 64))
    v = jax.random.normal(ks[2], (2, 2, sk, 64))
    g = jax.random.normal(jax.random.PRNGKey(55), q.shape)
    _, vjp_fl = jax.vjp(
        lambda a, b, c: flash_attention(a, b, c, causal), q, k, v)
    _, vjp_ref = jax.vjp(
        lambda a, b, c: attention_reference(a, b, c, causal=causal),
        q, k, v)
    for got, want in zip(vjp_fl(g), vjp_ref(g)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-4)


def test_flash_bwd_segmented_sq_gt_sk_matches_unsegmented(monkeypatch):
    """sq > sk causal (leading rows fully masked): flash's convention
    zeroes dead rows where the jnp reference degenerates to uniform
    attention — so the segmented path (whose sk_eff<=0 skip mirrors the
    kernels' causal block skip) is held to the UNSEGMENTED flash
    backward, its actual semantic contract."""
    import apex_tpu.ops.attention as A

    ks = jax.random.split(jax.random.PRNGKey(56), 3)
    q = jax.random.normal(ks[0], (2, 2, 896, 64))
    k = jax.random.normal(ks[1], (2, 2, 640, 64))
    v = jax.random.normal(ks[2], (2, 2, 640, 64))
    g = jax.random.normal(jax.random.PRNGKey(57), q.shape)

    def grads():
        _, vjp = jax.vjp(
            lambda a, b, c: flash_attention(a, b, c, True), q, k, v)
        return vjp(g)

    want = grads()
    monkeypatch.setattr(A, "_FUSED_BWD_DQ_SCRATCH_BYTES", 256 * 128 * 4)
    got = grads()
    for a, b in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_flash_bias_clamps_huge_masks():
    """-1e9-style masks are clamped to -3e4 in-kernel (f32 lse precision);
    the result matches the reference with the clamped mask."""
    q, k, v = qkv(jax.random.PRNGKey(43), s=128)
    bias = jnp.where(jnp.arange(128) < 64, 0.0, -1e9)[None, None, None, :]
    want = attention_reference(q, k, v, bias=jnp.maximum(bias, -3e4))
    got = flash_attention(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_self_mha_masked_fast_path():
    """A masked SelfMultiheadAttn(impl='fast') must match impl='default'
    exactly (VERDICT r1 #5: masks no longer bail out of the flash path)."""
    e, h, s = 64, 4, 128
    x = jax.random.normal(jax.random.PRNGKey(44), (2, s, e))
    mask = jnp.where(jnp.arange(s) < s - 32, 0.0, -3e4)[None, None, None, :]
    m_fast = SelfMultiheadAttn(embed_dim=e, num_heads=h, impl="fast")
    m_def = SelfMultiheadAttn(embed_dim=e, num_heads=h, impl="default")
    params = m_fast.init(jax.random.PRNGKey(45), x)
    y1 = m_fast.apply(params, x, attn_mask=mask)
    y2 = m_def.apply(params, x, attn_mask=mask)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=2e-4,
                               atol=2e-4)
    # boolean masks (True = masked) behave like the additive -3e4 mask on
    # BOTH impls (r2 review: the default path must not add bool as +1.0)
    bmask = (jnp.arange(s) >= s - 32)[None, None, None, :]
    y3 = m_fast.apply(params, x, attn_mask=bmask)
    np.testing.assert_allclose(np.asarray(y3), np.asarray(y1), rtol=1e-5,
                               atol=1e-6)
    y4 = m_def.apply(params, x, attn_mask=bmask)
    np.testing.assert_allclose(np.asarray(y4), np.asarray(y2), rtol=1e-5,
                               atol=1e-6)


@pytest.mark.parametrize("b", [2, 4])  # b=4=h: the silent-misalignment case
def test_self_mha_rank3_mask_both_impls(b):
    """A rank-3 (b, sq, sk) attn_mask must mean the same thing on both
    impls: broadcast over HEADS (ADVICE r2: the default path added it raw,
    raising a broadcast error — or, when b == h, silently aligning the
    batch dim against the heads dim)."""
    e, h, s = 64, 4, 32
    x = jax.random.normal(jax.random.PRNGKey(60), (b, s, e))
    # per-BATCH additive mask: distinct rows so a b-vs-h mixup changes values
    mask = jnp.where(
        jnp.arange(s)[None, None, :] < (s - 8 * jnp.arange(1, b + 1))[:, None, None],
        0.0, -3e4)
    m_fast = SelfMultiheadAttn(embed_dim=e, num_heads=h, impl="fast")
    m_def = SelfMultiheadAttn(embed_dim=e, num_heads=h, impl="default")
    params = m_fast.init(jax.random.PRNGKey(61), x)
    y_fast = m_fast.apply(params, x, attn_mask=mask)
    y_def = m_def.apply(params, x, attn_mask=mask)
    np.testing.assert_allclose(np.asarray(y_fast), np.asarray(y_def),
                               rtol=2e-4, atol=2e-4)
    # and it must equal the explicit rank-4 head-broadcast form
    y_r4 = m_def.apply(params, x, attn_mask=mask[:, None])
    np.testing.assert_allclose(np.asarray(y_def), np.asarray(y_r4),
                               rtol=1e-6, atol=1e-7)


def test_encdec_mha_masked_fast_path():
    e, h = 32, 2
    q = jax.random.normal(jax.random.PRNGKey(46), (2, 24, e))
    kv = jax.random.normal(jax.random.PRNGKey(47), (2, 48, e))
    mask = jnp.where(jnp.arange(48) < 40, 0.0, -3e4)[None, None, None, :]
    m_def = EncdecMultiheadAttn(embed_dim=e, num_heads=h, impl="default")
    m_fast = EncdecMultiheadAttn(embed_dim=e, num_heads=h, impl="fast")
    params = m_def.init(jax.random.PRNGKey(48), q, kv)
    want = m_def.apply(params, q, kv, attn_mask=mask)
    got = m_fast.apply(params, q, kv, attn_mask=mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4,
                               atol=2e-4)


@pytest.mark.parametrize("scheme", ["ring", "ulysses"])
def test_seq_parallel_masked_matches_dense(mesh, scheme):
    """Masked sequence-parallel attention (key-padding bias with GLOBAL
    columns) matches dense masked attention."""
    b, h, s, d = 2, 8, NDEV * 16, 32
    ks = jax.random.split(jax.random.PRNGKey(50), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)
    bias = jnp.where(jnp.arange(s) < s - 48, 0.0, -3e4)[None, None, None, :]
    bias = jnp.broadcast_to(bias, (b, 1, 1, s))

    want = attention_reference(q, k, v, bias=bias)

    def per_device(q_, k_, v_):
        if scheme == "ring":
            return ring_self_attention(q_, k_, v_, "seq", bias=bias)
        return ulysses_self_attention(q_, k_, v_, "seq", bias=bias)

    got = jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(P(None, None, "seq", None),) * 3,
        out_specs=P(None, None, "seq", None), check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# Ring attention composed with the flash kernels (VERDICT r1 #6)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense(mesh, causal):
    """impl='flash' ring: Pallas chunks + global-lse ring backward must
    match dense attention in value AND grads on the 8-device mesh."""
    b, h, s, d = 1, 2, NDEV * 32, 32
    ks = jax.random.split(jax.random.PRNGKey(60), 4)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks[:3])
    g = jax.random.normal(ks[3], (b, h, s, d))

    want, vjp_ref = jax.vjp(
        lambda a, bb, c: attention_reference(a, bb, c, causal=causal),
        q, k, v)
    want_grads = vjp_ref(g)

    def per_device(q_, k_, v_, g_):
        out, vjp = jax.vjp(
            lambda a, bb, c: ring_self_attention(
                a, bb, c, "seq", causal=causal, impl="flash"), q_, k_, v_)
        return (out,) + vjp(g_)

    spec = P(None, None, "seq", None)
    got, *got_grads = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(spec,) * 4,
        out_specs=(spec,) * 4, check_vma=False))(q, k, v, g)

    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    for gg, ww in zip(got_grads, want_grads):
        np.testing.assert_allclose(np.asarray(gg), np.asarray(ww),
                                   rtol=3e-3, atol=5e-4)


def test_ring_flash_masked(mesh):
    """Ring flash with a key-padding bias (global columns)."""
    b, h, s, d = 1, 2, NDEV * 16, 32
    ks = jax.random.split(jax.random.PRNGKey(61), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)
    bias = jnp.where(jnp.arange(s) < s - 40, 0.0, -3e4)[None, None, None, :]
    bias = jnp.broadcast_to(bias, (b, 1, 1, s))

    want = attention_reference(q, k, v, bias=bias)

    def per_device(q_, k_, v_):
        return ring_self_attention(q_, k_, v_, "seq", bias=bias,
                                   impl="flash")

    spec = P(None, None, "seq", None)
    got = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(spec,) * 3,
        out_specs=spec, check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# Trainable (learned) score bias: dbias emission from the flash backward
# ---------------------------------------------------------------------------

@pytest.mark.slow  # dbias-emission matrix (see tier-1 budget note)
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 4, 128, 128), (1, 4, 1, 128),
                                   (2, 1, 128, 128), (1, 1, 1, 128)])
def test_flash_trainable_bias_matches_reference(causal, shape):
    """trainable_bias=True: the kernels' emitted dbias (reduced over the
    bias's broadcast dims) matches differentiating the dense reference;
    q/k/v grads are unchanged by the flag."""
    q, k, v = qkv(jax.random.PRNGKey(70), s=128)
    bias = jax.random.normal(jax.random.PRNGKey(71), shape)
    g = jax.random.normal(jax.random.PRNGKey(72), q.shape)

    _, vjp_fl = jax.vjp(
        lambda a, b, c, bb: flash_attention(
            a, b, c, causal, bias=bb, trainable_bias=True), q, k, v, bias)
    _, vjp_ref = jax.vjp(
        lambda a, b, c, bb: attention_reference(
            a, b, c, bias=bb, causal=causal), q, k, v, bias)
    for got, want in zip(vjp_fl(g), vjp_ref(g)):
        assert got.shape == want.shape
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=3e-3, atol=2e-3)


def test_flash_trainable_bias_ragged_cross_lengths():
    """dbias with sq != sk, neither a block multiple (padded rows AND
    ragged columns), causal bottom-right diagonal."""
    ks = jax.random.split(jax.random.PRNGKey(73), 3)
    sq, sk, d = 190, 250, 64
    q = jax.random.normal(ks[0], (1, 2, sq, d))
    k = jax.random.normal(ks[1], (1, 2, sk, d))
    v = jax.random.normal(ks[2], (1, 2, sk, d))
    bias = jax.random.normal(jax.random.PRNGKey(74), (1, 2, sq, sk))
    g = jax.random.normal(jax.random.PRNGKey(75), q.shape)

    _, vjp_fl = jax.vjp(
        lambda bb: flash_attention(q, k, v, True, bias=bb,
                                   trainable_bias=True), bias)
    _, vjp_ref = jax.vjp(
        lambda bb: attention_reference(q, k, v, bias=bb, causal=True),
        bias)
    np.testing.assert_allclose(np.asarray(vjp_fl(g)[0]),
                               np.asarray(vjp_ref(g)[0]),
                               rtol=3e-3, atol=2e-3)


def test_flash_trainable_bias_with_dropout():
    """dbias under fused dropout: ds picks up the same keep/rate factor
    as dP — parity vs the jnp reference using the SAME counter mask."""
    q, k, v = qkv(jax.random.PRNGKey(76), s=128)
    bias = jax.random.normal(jax.random.PRNGKey(77), (1, 4, 128, 128))
    g = jax.random.normal(jax.random.PRNGKey(78), q.shape)
    rate, seed = 0.3, 11

    _, vjp_fl = jax.vjp(
        lambda bb: flash_attention(q, k, v, True, dropout_rate=rate,
                                   dropout_seed=seed, bias=bb,
                                   trainable_bias=True), bias)
    _, vjp_ref = jax.vjp(
        lambda bb: attention_reference(q, k, v, causal=True,
                                       dropout_rate=rate,
                                       dropout_seed=seed, bias=bb), bias)
    np.testing.assert_allclose(np.asarray(vjp_fl(g)[0]),
                               np.asarray(vjp_ref(g)[0]),
                               rtol=3e-3, atol=2e-3)


def test_flash_trainable_bias_two_pass_fallback(monkeypatch):
    """The two-pass backward's kv kernel emits the same dbias when the
    fused kernel's dq scratch would blow VMEM."""
    import apex_tpu.ops.attention as A

    monkeypatch.setattr(A, "_FUSED_BWD_DQ_SCRATCH_BYTES", 0)
    q, k, v = qkv(jax.random.PRNGKey(79), s=200)
    bias = jax.random.normal(jax.random.PRNGKey(80), (2, 1, 200, 200))
    g = jax.random.normal(jax.random.PRNGKey(81), q.shape)
    _, vjp_fl = jax.vjp(
        lambda bb: flash_attention(q, k, v, True, bias=bb,
                                   trainable_bias=True), bias)
    _, vjp_ref = jax.vjp(
        lambda bb: attention_reference(q, k, v, bias=bb, causal=True),
        bias)
    np.testing.assert_allclose(np.asarray(vjp_fl(g)[0]),
                               np.asarray(vjp_ref(g)[0]),
                               rtol=3e-3, atol=2e-3)


def test_flash_constant_bias_still_zero_grad():
    """Default (trainable_bias=False) keeps the mask-is-data contract:
    zero bias cotangent."""
    q, k, v = qkv(jax.random.PRNGKey(82), s=128)
    bias = jax.random.normal(jax.random.PRNGKey(83), (1, 1, 128, 128))
    _, vjp_fl = jax.vjp(
        lambda bb: flash_attention(q, k, v, bias=bb), bias)
    db = vjp_fl(jnp.ones(q.shape))[0]
    assert float(jnp.max(jnp.abs(db))) == 0.0


def test_ring_trainable_bias_matches_dense(mesh):
    """Ring flash with a LEARNED bias replicated across the ring: each
    device's dbias is its query rows' contribution; the psum over the
    axis equals the dense reference's bias grad."""
    b, h, s, d = 1, 2, NDEV * 16, 32
    ks = jax.random.split(jax.random.PRNGKey(84), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)
    bias = jax.random.normal(jax.random.PRNGKey(85), (1, h, 1, s))
    g = jax.random.normal(jax.random.PRNGKey(86), q.shape)

    _, vjp_ref = jax.vjp(
        lambda bb: attention_reference(q, k, v, bias=bb, causal=True),
        bias)
    want = vjp_ref(g)[0]

    def per_device(q_, k_, v_, g_):
        def f(bb):
            return ring_self_attention(q_, k_, v_, "seq", causal=True,
                                       bias=bb, impl="flash",
                                       trainable_bias=True)
        _, vjp = jax.vjp(f, bias)
        return jax.lax.psum(vjp(g_)[0], "seq")

    spec = P(None, None, "seq", None)
    got = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(spec,) * 4,
        out_specs=P(), check_vma=False))(q, k, v, g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# Learned relative position bias (T5-style, consumes trainable_bias)
# ---------------------------------------------------------------------------

def test_relative_position_bucket_properties():
    from apex_tpu.contrib.multihead_attn import relative_position_bucket
    nb, md = 32, 128
    rel = jnp.arange(-300, 301)  # k_pos - q_pos
    bu = relative_position_bucket(rel, bidirectional=False,
                                  num_buckets=nb, max_distance=md)
    bu = np.asarray(bu)
    assert bu.min() >= 0 and bu.max() < nb
    # future keys (rel > 0) all collapse to bucket 0 (causal pairing)
    assert (bu[rel > 0] == 0).all()
    # exact buckets for small distances: distance d -> bucket d
    for d in range(nb // 2):
        assert bu[np.where(np.asarray(rel) == -d)[0][0]] == d
    # distances past max_distance share the last bucket
    assert bu[0] == nb - 1 and bu[np.asarray(rel) == -md + 1][0] <= nb - 1
    bb = np.asarray(relative_position_bucket(
        rel, bidirectional=True, num_buckets=nb, max_distance=md))
    # bidirectional: past in [0, nb/2), future in [nb/2, nb)
    assert bb[rel < 0].max() < nb // 2 <= bb[rel > 0].min()


@pytest.mark.parametrize("causal", [False, True])
def test_self_mha_relative_bias_fast_matches_default(causal):
    """The learned rel-pos bias trains identically through the flash
    kernels (trainable_bias dbias path) and the dense softmax: outputs
    and ALL grads — including the bias table's — match."""
    e, h, s = 64, 4, 96
    x = jax.random.normal(jax.random.PRNGKey(90), (2, s, e))

    def build(impl):
        return SelfMultiheadAttn(embed_dim=e, num_heads=h, causal=causal,
                                 relative_bias=True, impl=impl)

    params = build("fast").init(jax.random.PRNGKey(91), x)["params"]
    assert "rel_bias" in params

    outs, grads = {}, {}
    for impl in ("fast", "default"):
        m = build(impl)

        def loss(p, xx):
            return jnp.sum(m.apply({"params": p}, xx) ** 2)

        outs[impl] = m.apply({"params": params}, x)
        grads[impl] = jax.grad(loss)(params, x)

    np.testing.assert_allclose(np.asarray(outs["fast"]),
                               np.asarray(outs["default"]),
                               rtol=2e-4, atol=2e-4)
    flat_f, _ = jax.tree_util.tree_flatten_with_path(grads["fast"])
    flat_d, _ = jax.tree_util.tree_flatten_with_path(grads["default"])
    for (pf, gf), (_, gd) in zip(flat_f, flat_d):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=3e-3, atol=2e-3,
            err_msg=str(pf))
    table_grad = grads["fast"]["rel_bias"]["rel_bias"]
    assert float(jnp.max(jnp.abs(table_grad))) > 0


def test_self_mha_relative_bias_composes_with_mask():
    e, h, s = 32, 2, 64
    x = jax.random.normal(jax.random.PRNGKey(92), (1, s, e))
    mask = jnp.where(jnp.arange(s) < s - 10, 0.0, -3e4)[None, None, None]
    m = SelfMultiheadAttn(embed_dim=e, num_heads=h, relative_bias=True,
                          impl="fast")
    params = m.init(jax.random.PRNGKey(93), x)["params"]
    out = m.apply({"params": params}, x, attn_mask=mask)
    assert np.isfinite(np.asarray(out)).all()


def test_self_mha_relative_bias_rejects_ulysses():
    """Ring composes with relative_bias (r5); ulysses cannot — after
    its all-to-all only column biases apply to the head-subset/full-seq
    layout, so the module still fails loudly there."""
    m = SelfMultiheadAttn(embed_dim=32, num_heads=2, relative_bias=True,
                          seq_parallel="ulysses", axis_name="seq")
    x = jnp.zeros((1, 16, 32))
    with pytest.raises(NotImplementedError, match="ulysses"):
        m.init(jax.random.PRNGKey(0), x)


def test_ulysses_trainable_bias_matches_dense(mesh):
    """Ulysses with a learned column bias: the flag threads through the
    head-sliced dispatch; per-head biases grad via the slice transpose.
    Full-head bias (1, H, 1, S) -> each device's dbias covers its head
    subset (zeros elsewhere); psum over the axis re-assembles it."""
    b, h, s, d = 1, NDEV, NDEV * 16, 32
    ks = jax.random.split(jax.random.PRNGKey(87), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)
    bias = jax.random.normal(jax.random.PRNGKey(88), (1, h, 1, s))
    g = jax.random.normal(jax.random.PRNGKey(89), q.shape)

    _, vjp_ref = jax.vjp(
        lambda bb: attention_reference(q, k, v, bias=bb, causal=True),
        bias)
    want = vjp_ref(g)[0]

    def per_device(q_, k_, v_, g_):
        def f(bb):
            return ulysses_self_attention(q_, k_, v_, "seq", causal=True,
                                          bias=bb, impl="flash",
                                          trainable_bias=True)
        _, vjp = jax.vjp(f, bias)
        return jax.lax.psum(vjp(g_)[0], "seq")

    spec = P(None, None, "seq", None)
    got = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(spec,) * 4,
        out_specs=P(), check_vma=False))(q, k, v, g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-3, atol=2e-3)


def test_encdec_decode_cache_matches_full():
    """Enc-dec decode: the projected encoder K/V are cached on the
    first call; later 1-token steps with key=None match recomputing the
    full cross-attention."""
    e, h = 32, 4
    enc = jax.random.normal(jax.random.PRNGKey(94), (2, 10, e))
    dec_in = jax.random.normal(jax.random.PRNGKey(95), (2, 5, e))
    m = EncdecMultiheadAttn(embed_dim=e, num_heads=h)
    params = m.init(jax.random.PRNGKey(96), dec_in, enc)["params"]
    want = m.apply({"params": params}, dec_in, enc)

    md = EncdecMultiheadAttn(embed_dim=e, num_heads=h, decode=True)
    # first call fills the cache (and answers for its own queries)
    out0, vs = md.apply({"params": params}, dec_in[:, :1], enc,
                        mutable=["cache"])
    np.testing.assert_allclose(np.asarray(out0), np.asarray(want[:, :1]),
                               rtol=2e-4, atol=2e-4)
    cache = vs["cache"]
    for i in range(1, 5):
        out_i, vs = md.apply({"params": params, "cache": cache},
                             dec_in[:, i:i + 1], mutable=["cache"])
        cache = vs["cache"]
        np.testing.assert_allclose(
            np.asarray(out_i), np.asarray(want[:, i:i + 1]),
            rtol=2e-4, atol=2e-4, err_msg=f"step {i}")


def test_encdec_decode_requires_encoder_on_first_call():
    m = EncdecMultiheadAttn(embed_dim=16, num_heads=2, decode=True)
    x = jnp.zeros((1, 1, 16))
    with pytest.raises(ValueError, match="first call"):
        m.init(jax.random.PRNGKey(0), x)


def test_decode_attention_kernel_matches_einsum():
    """Fused decode kernel vs the masked einsum across fill levels,
    step widths, and a cache length that needs block padding."""
    from apex_tpu.ops.attention import decode_attention

    # L=200: non-128-multiple exercises the padding fallback; L=1920:
    # 128-multiple but not a power-of-two block multiple — the divisor
    # search must pick a block that divides it (640), never padding
    # (which would COPY both caches every step); d=64: native-d blocks
    for L, d in ((200, 128), (1920, 64)):
        ks = jax.random.split(jax.random.PRNGKey(97), 3)
        b, h = 2, 3
        kc = jax.random.normal(ks[0], (b, h, L, d))
        vc = jax.random.normal(ks[1], (b, h, L, d))
        for idx, sc in ((0, 1), (5, 1), (63, 8), (L - 3, 3), (0, 8)):
            q = jax.random.normal(jax.random.fold_in(ks[2], idx),
                                  (b, h, sc, d))
            got = decode_attention(q, kc, vc, idx)
            s = jnp.einsum("bhqd,bhkd->bhqk", q, kc,
                           preferred_element_type=jnp.float32) \
                / math.sqrt(d)
            col = jnp.arange(L)[None, :]
            row = idx + jnp.arange(sc)[:, None]
            s = jnp.where(col <= row, s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            want = jnp.einsum("bhqk,bhkd->bhqd", p, vc)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"L={L} idx={idx} sc={sc}")


def test_encdec_decode_rejects_stale_cache_swap():
    """Passing a fresh encoder stream once the cache is filled must
    raise, not silently attend the stale keys."""
    e, h = 16, 2
    enc = jax.random.normal(jax.random.PRNGKey(98), (1, 6, e))
    x = jnp.zeros((1, 1, e))
    m = EncdecMultiheadAttn(embed_dim=e, num_heads=h, decode=True)
    params = m.init(jax.random.PRNGKey(99), x, enc)["params"]
    _, vs = m.apply({"params": params}, x, enc, mutable=["cache"])
    with pytest.raises(ValueError, match="already filled"):
        m.apply({"params": params, "cache": vs["cache"]}, x, enc,
                mutable=["cache"])


def test_alibi_column_form_matches_full_penalty():
    """The (1, H, 1, sk) column bias equals the textbook -slope*(i-j)
    penalty under causal softmax (row shifts cancel), on flash AND
    reference paths; learned slopes differentiate through
    trainable_bias."""
    from apex_tpu.contrib.multihead_attn import alibi_bias, alibi_slopes

    b, h, s, d = 2, 4, 96, 32
    q, k, v = qkv(jax.random.PRNGKey(100), b=b, h=h, s=s, d=d)
    slopes = alibi_slopes(h)
    col = alibi_bias(h, s)
    # textbook full form: -m * (i - j) on the causal triangle
    i = jnp.arange(s)[:, None].astype(jnp.float32)
    j = jnp.arange(s)[None, :].astype(jnp.float32)
    full = (-slopes[:, None, None] * (i - j))[None]

    want = attention_reference(q, k, v, causal=True, bias=full)
    got_ref = attention_reference(q, k, v, causal=True, bias=col)
    got_fl = flash_attention(q, k, v, True, bias=col)
    np.testing.assert_allclose(np.asarray(got_ref), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(got_fl), np.asarray(want),
                               rtol=2e-4, atol=2e-4)

    def loss(sl):
        from apex_tpu.contrib.multihead_attn import alibi_bias as ab
        return jnp.sum(flash_attention(
            q, k, v, True, bias=ab(h, s, slopes=sl),
            trainable_bias=True) ** 2)

    g = jax.grad(loss)(slopes)
    assert g.shape == (h,) and float(jnp.max(jnp.abs(g))) > 0


def test_alibi_slopes_interleaved_non_pow2():
    """Non-power-of-two head counts follow the published interleaved
    recipe (closest lower power's geometric slopes + every other slope
    of the doubled sequence) so weights match externally-trained ALiBi
    checkpoints, e.g. BLOOM-style (ADVICE r4)."""
    from apex_tpu.contrib.multihead_attn import alibi_slopes

    got = np.asarray(alibi_slopes(12))
    geo8 = [2.0 ** (-8.0 * (i + 1) / 8) for i in range(8)]
    geo16 = [2.0 ** (-8.0 * (i + 1) / 16) for i in range(16)]
    want = np.asarray(geo8 + geo16[0::2][:4], np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # power-of-two counts keep the plain geometric sequence
    np.testing.assert_allclose(
        np.asarray(alibi_slopes(8)), np.asarray(geo8, np.float32),
        rtol=1e-6)


@pytest.mark.parametrize("learned", [False, True])
def test_self_mha_alibi_fast_matches_default(learned):
    """The module-level alibi option: fast (flash, trainable_bias dbias
    when learned) and default (dense softmax) paths agree on outputs
    and all grads; learned slopes appear as the "alibi_slopes" param
    and receive nonzero grad."""
    e, h, s = 64, 4, 96
    x = jax.random.normal(jax.random.PRNGKey(101), (2, s, e))

    def build(impl):
        return SelfMultiheadAttn(embed_dim=e, num_heads=h, causal=True,
                                 alibi=True, alibi_learned=learned,
                                 impl=impl)

    params = build("fast").init(jax.random.PRNGKey(102), x)["params"]
    assert ("alibi_slopes" in params) == learned

    outs, grads = {}, {}
    for impl in ("fast", "default"):
        m = build(impl)

        def loss(p, xx):
            return jnp.sum(m.apply({"params": p}, xx) ** 2)

        outs[impl] = m.apply({"params": params}, x)
        grads[impl] = jax.grad(loss)(params, x)

    np.testing.assert_allclose(np.asarray(outs["fast"]),
                               np.asarray(outs["default"]),
                               rtol=2e-4, atol=2e-4)
    flat_f, _ = jax.tree_util.tree_flatten_with_path(grads["fast"])
    flat_d, _ = jax.tree_util.tree_flatten_with_path(grads["default"])
    for (pf, gf), (_, gd) in zip(flat_f, flat_d):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=3e-3, atol=2e-3,
            err_msg=str(pf))
    if learned:
        sg = grads["fast"]["alibi_slopes"]
        assert float(jnp.max(jnp.abs(sg))) > 0


def test_self_mha_alibi_requires_causal():
    m = SelfMultiheadAttn(embed_dim=32, num_heads=2, alibi=True,
                          causal=False)
    with pytest.raises(ValueError, match="causal"):
        m.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 32)))


def test_ring_replicated_bias_flag_matches_manual_psum(mesh):
    """replicated_bias=True folds the cross-ring psum into the bias
    cotangent — identical to the manual-psum convention, correct by
    default for a ring-replicated learned bias (ADVICE r4)."""
    b, h, s, d = 1, 2, NDEV * 16, 32
    ks = jax.random.split(jax.random.PRNGKey(103), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)
    bias = jax.random.normal(jax.random.PRNGKey(104), (1, h, 1, s))
    g = jax.random.normal(jax.random.PRNGKey(105), q.shape)

    _, vjp_ref = jax.vjp(
        lambda bb: attention_reference(q, k, v, bias=bb, causal=True),
        bias)
    want = vjp_ref(g)[0]

    def per_device(q_, k_, v_, g_):
        def f(bb):
            return ring_self_attention(q_, k_, v_, "seq", causal=True,
                                       bias=bb, impl="flash",
                                       trainable_bias=True,
                                       replicated_bias=True)
        _, vjp = jax.vjp(f, bias)
        return vjp(g_)[0]        # no manual psum — the flag does it

    spec = P(None, None, "seq", None)
    got = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(spec,) * 4,
        out_specs=P(), check_vma=False))(q, k, v, g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-3, atol=2e-3)
