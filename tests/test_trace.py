"""apex_tpu.trace host-side span tracing: span API units (pairing,
nesting, threading, decorator, disabled no-op), producer wiring
(instrument_step dispatch/wait spans, PrefetchLoader wait_s +
blocked-wait span, SnapshotManager save/serialize/publish, tune
measurement), the disabled-tracing jaxpr-equality guarantee, the
summarize spans/wall-reconciliation sections, multi-process merge on the
COMMITTED two-process fixture with a known 1.75 s clock skew (offset
recovery + straggler attribution), and the unified host+device timeline
export."""

import json
import os
import threading
import time

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import telemetry, trace
from apex_tpu.telemetry.export import format_summary, summarize

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures")
P0 = os.path.join(FIXDIR, "trace_run-p0.jsonl")
P1 = os.path.join(FIXDIR, "trace_run-p1.jsonl")
DEVICE_TRACE = os.path.join(FIXDIR, "synthetic_trace.json")

# fixture ground truth (see the generator values in the files)
FIXTURE_SKEW = 1.75
FIXTURE_STEPS = 6


@pytest.fixture
def traced():
    """Fresh collector + tracing enabled; both restored afterwards."""
    with telemetry.capture() as col:
        trace.enable()
        try:
            yield col
        finally:
            trace.disable()


def _events(col):
    return [e.to_dict() for e in col.snapshot()]


# ---------------------------------------------------------------------------
# span API
# ---------------------------------------------------------------------------

class TestSpanAPI:
    def test_begin_end_pair(self, traced):
        with trace.span("data/wait", step=3):
            time.sleep(0.005)
        evs = _events(traced)
        assert len(evs) == 2
        b, e = evs
        assert b["name"] == e["name"] == "span/data/wait"
        assert b["kind"] == e["kind"] == "span"
        assert b["meta"]["ph"] == "B" and e["meta"]["ph"] == "E"
        assert b["meta"]["id"] == e["meta"]["id"]
        assert b["step"] == e["step"] == 3
        assert e["value"] >= 0.005
        assert e["meta"]["mono"] > b["meta"]["mono"]
        assert e["meta"]["thread"] == threading.current_thread().name

    def test_disabled_emits_nothing(self):
        with telemetry.capture() as col:
            assert not trace.enabled()
            with trace.span("data/wait"):
                pass
            trace.emit_span("step/dispatch", 0.0, 1.0)
            assert len(col) == 0

    def test_nesting_depth(self, traced):
        with trace.span("snapshot/save"):
            with trace.span("snapshot/serialize"):
                pass
        rows = trace.span_rows(_events(traced))
        by_name = {r["name"]: r for r in rows}
        assert by_name["span/snapshot/save"]["depth"] == 0
        assert by_name["span/snapshot/serialize"]["depth"] == 1

    def test_decorator_and_recursion(self, traced):
        calls = []

        @trace.span("tune/measure")
        def f(n):
            calls.append(n)
            if n:
                f(n - 1)

        f(2)
        rows = trace.span_rows(_events(traced))
        assert len(rows) == 3 and calls == [2, 1, 0]
        assert sorted(r["depth"] for r in rows) == [0, 1, 2]

    def test_thread_awareness(self, traced):
        def work():
            with trace.span("data/produce"):
                time.sleep(0.002)

        ts = [threading.Thread(target=work, name=f"w{i}")
              for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        rows = trace.span_rows(_events(traced))
        assert len(rows) == 2
        assert {r["thread"] for r in rows} == {"w0", "w1"}
        assert len({r["tid"] for r in rows}) == 2
        # each thread's depth is tracked independently
        assert all(r["depth"] == 0 for r in rows)

    def test_flag_flip_mid_span_stays_balanced(self):
        with telemetry.capture() as col:
            trace.enable()
            try:
                s = trace.span("data/wait")
                s.__enter__()
                trace.disable()
                # a span that BEGAN still ends: the begin/end pairing in
                # the file stays balanced across a mid-span disable
                s.__exit__(None, None, None)
                # the reverse: entered disabled -> nothing is emitted,
                # and the per-thread stack stays consistent
                s2 = trace.span("tune/measure")
                s2.__enter__()
                trace.enable()
                s2.__exit__(None, None, None)
                with trace.span("data/produce"):
                    pass
            finally:
                trace.disable()
            evs = _events(col)
            rows = trace.span_rows(evs)
            assert [r["name"] for r in rows] == ["span/data/wait",
                                                "span/data/produce"]
            begins = sum(1 for e in evs if e["meta"]["ph"] == "B")
            ends = sum(1 for e in evs if e["meta"]["ph"] == "E")
            assert begins == ends == 2
            assert rows[-1]["depth"] == 0

    def test_family_of(self):
        assert trace.family_of("span/data/wait") == "data/wait"
        assert trace.family_of("step/dispatch") == "step/dispatch"
        assert trace.family_of("span/snapshot/serialize/extra") \
            == "snapshot/serialize"
        assert trace.family_of("span/custom") == "custom"

    def test_emit_span_late_emission_keeps_wall_ts(self, traced):
        """emit_span may run long after the interval it records (the
        dispatch span is emitted after block_until_ready) — the wall ts
        must derive from the mono brackets, not the emission time, or
        every merge clock anchor would be displaced by the device wait
        (biasing recovered offsets by exactly the straggler signal)."""
        t0 = time.perf_counter()
        w0 = time.time()
        time.sleep(0.05)                      # emission lags the span
        trace.emit_span("step/dispatch", t0, t0 + 0.01, step=0)
        r = trace.span_rows(_events(traced))[0]
        begin_wall = r["ts"] - r["dur_s"]
        assert begin_wall == pytest.approx(w0, abs=0.02)
        assert begin_wall < w0 + 0.04         # NOT displaced by the lag

    def test_emit_span_and_family_totals(self, traced):
        trace.emit_span("step/dispatch", 10.0, 10.5, step=0)
        trace.emit_span("step/dispatch", 11.0, 11.25, step=1)
        trace.emit_span("data/wait", 10.5, 10.6)
        evs = _events(traced)
        totals = trace.family_totals(evs)
        assert totals["step/dispatch"] == pytest.approx(0.75)
        assert totals["data/wait"] == pytest.approx(0.1)
        assert trace.family_totals(evs, exclude=("data/wait",)) == \
            {"step/dispatch": pytest.approx(0.75)}
        rows = trace.span_rows(evs)
        r = next(r for r in rows if r["step"] == 1)
        assert r["begin_mono"] == pytest.approx(11.0)
        assert r["end_mono"] == pytest.approx(11.25)


# ---------------------------------------------------------------------------
# producer wiring
# ---------------------------------------------------------------------------

class TestProducers:
    def test_instrument_step_spans(self, traced):
        step = telemetry.instrument_step(jax.jit(lambda x: x + 1.0),
                                         measure_flops=False)
        x = jnp.zeros(())
        step(x)
        step(x)
        rows = trace.span_rows(_events(traced))
        fams = {r["family"] for r in rows}
        assert {"step/dispatch", "step/device_wait"} <= fams
        disp = sorted(r["step"] for r in rows
                      if r["family"] == "step/dispatch")
        assert disp == [0, 1]

    def test_prefetch_wait_s_and_span(self, traced):
        from apex_tpu.runtime import PrefetchLoader

        def slow_source():
            for i in range(3):
                time.sleep(0.02)
                yield i

        loader = PrefetchLoader(slow_source(), depth=2)
        items = list(loader)
        assert items == [0, 1, 2]
        st = loader.stats()
        assert st["wait_s"] > 0.0          # the consumer really blocked
        assert st["starvations"] >= 1
        rows = trace.span_rows(_events(traced))
        fams = [r["family"] for r in rows]
        assert "data/wait" in fams
        assert "data/produce" in fams
        # the wait spans roughly account for the stats counter
        waited = sum(r["dur_s"] for r in rows
                     if r["family"] == "data/wait")
        assert waited <= st["wait_s"] + 1e-3

    def test_prefetch_wait_s_without_tracing(self):
        from apex_tpu.runtime import PrefetchLoader
        loader = PrefetchLoader(iter(range(4)), depth=2)
        assert list(loader) == [0, 1, 2, 3]
        assert "wait_s" in loader.stats()

    def test_snapshot_spans_sync(self, traced, tmp_path):
        from apex_tpu.resilience import SnapshotManager
        mgr = SnapshotManager(str(tmp_path / "snap"), keep_last=2)
        mgr.save({"w": np.ones((4,), np.float32)}, step=1)
        rows = trace.span_rows(_events(traced))
        fams = {r["family"] for r in rows}
        assert {"snapshot/save", "snapshot/serialize",
                "snapshot/publish"} <= fams
        save = next(r for r in rows if r["family"] == "snapshot/save")
        assert save["step"] == 1
        # sync: serialize nests inside the caller-side save span
        ser = next(r for r in rows
                   if r["family"] == "snapshot/serialize")
        assert ser["depth"] == 0 or ser["thread"] == save["thread"]

    def test_snapshot_spans_async_thread(self, traced, tmp_path):
        from apex_tpu.resilience import SnapshotManager
        mgr = SnapshotManager(str(tmp_path / "snap"), keep_last=2,
                              async_mode=True)
        mgr.save({"w": np.ones((4,), np.float32)}, step=2)
        assert mgr.wait()
        rows = trace.span_rows(_events(traced))
        save = next(r for r in rows if r["family"] == "snapshot/save")
        ser = next(r for r in rows
                   if r["family"] == "snapshot/serialize")
        # serialize runs on the background writer thread, save on ours
        assert ser["thread"] == "apex-snapshot"
        assert save["thread"] == threading.current_thread().name

    def test_tune_measure_span(self, traced):
        from apex_tpu.tune import measure
        x = jnp.ones((8,))
        measure.time_fn(lambda: x * 2.0, warmup=0, repeats=1)
        rows = trace.span_rows(_events(traced))
        assert any(r["family"] == "tune/measure" for r in rows)

    def test_callback_record_span(self, traced):
        @jax.jit
        def step(x):
            telemetry.record("train/loss", x)
            return x + 1.0

        step(jnp.zeros(()))
        jax.effects_barrier()
        rows = trace.span_rows(_events(traced))
        assert any(r["family"] == "callback/record" for r in rows)


# ---------------------------------------------------------------------------
# disabled tracing changes nothing in traced programs
# ---------------------------------------------------------------------------

class TestJaxprEquality:
    def _step_factory(self):
        # fresh closure per trace: jax.make_jaxpr caches by function
        # object, which would make same-object comparisons trivially pass
        def step(x, w):
            telemetry.record("train/loss", jnp.mean(x))
            return x @ w

        return step

    def test_all_disabled_traces_no_callbacks(self):
        assert not telemetry.enabled() and not trace.enabled()
        x = jnp.ones((4, 4))
        jaxpr = str(jax.make_jaxpr(self._step_factory())(x, x))
        assert "debug_callback" not in jaxpr

    def test_trace_flag_never_changes_the_program(self):
        """Spans are host-side only: even with telemetry's callbacks
        traced in, flipping the trace flag yields a bit-identical
        program (the span wrapping lives inside the host callback)."""
        import re
        x = jnp.ones((4, 4))
        with telemetry.capture():
            assert not trace.enabled()
            off = str(jax.make_jaxpr(self._step_factory())(x, x))
            trace.enable()
            try:
                on = str(jax.make_jaxpr(self._step_factory())(x, x))
            finally:
                trace.disable()
        # the debug_callback eqn prints its host closure's id — an
        # incidental per-object address, not program structure
        addr = re.compile(r"0x[0-9a-f]+")
        assert addr.sub("0x", on) == addr.sub("0x", off)


# ---------------------------------------------------------------------------
# summarize: spans section + wall reconciliation
# ---------------------------------------------------------------------------

def _mk_span(name, dur, *, step=None, mono=0.0, tid=1, ph="E",
             thread="MainThread", depth=0, process=None):
    meta = {"ph": ph, "id": 1, "tid": tid, "thread": thread,
            "depth": depth, "mono": mono}
    if process is not None:
        meta["process"] = process
    return {"name": f"span/{name}", "value": dur, "ts": mono,
            "step": step, "kind": "span", "meta": meta}


class TestSummarizeSections:
    def _recon_events(self, with_profile=True):
        evs = []
        for i in range(3):
            evs.append({"name": "step/time_s", "value": 0.100,
                        "ts": float(i), "step": i, "kind": "point"})
            evs.append(_mk_span("step/dispatch", 0.010, step=i))
            evs.append(_mk_span("step/device_wait", 0.088, step=i))
            evs.append(_mk_span("data/wait", 0.002, step=i))
            # concurrent-by-design families: visible in the spans
            # section, never billed as wall components
            evs.append(_mk_span("data/produce", 0.050, step=i))
            evs.append(_mk_span("callback/record", 0.001))
            # stack-nested span (depth 1): its parent already carries
            # this time — spans table yes, wall component no
            evs.append(_mk_span("tune/measure", 0.005, step=i, depth=1))
        if with_profile:
            evs.append({"name": "profile/device_busy_s_per_step",
                        "value": 0.080, "kind": "static", "ts": 0.0})
            evs.append({"name": "profile/dispatch_gap_pct",
                        "value": 20.0, "kind": "static", "ts": 0.0})
        return evs

    def test_spans_section(self):
        s = summarize(self._recon_events())
        sp = s["spans"]
        assert sp["data/produce"]["count"] == 3
        assert sp["data/produce"]["total_s"] == pytest.approx(0.150)
        assert sp["step/dispatch"]["mean"] == pytest.approx(0.010)

    def test_reconciliation_exact(self):
        """wall 100 ms = busy 80 + dispatch 10 + blocked_on_device 8 +
        data/wait 2 + residual 0."""
        s = summarize(self._recon_events())
        rc = s["reconciliation"]
        assert rc["busy_source"] == "profile"
        assert rc["device_busy_s"] == pytest.approx(0.080)
        comps = rc["components"]
        assert comps["step/dispatch"] == pytest.approx(0.010)
        assert comps["blocked_on_device"] == pytest.approx(0.008)
        assert comps["data/wait"] == pytest.approx(0.002)
        assert "data/produce" not in comps
        assert "callback/record" not in comps
        assert "tune/measure" not in comps     # depth-1: parent's time
        assert s["spans"]["tune/measure"]["count"] == 3
        assert rc["gap_s"] == pytest.approx(0.020)
        assert rc["residual_s"] == pytest.approx(0.0, abs=1e-12)
        assert rc["profile_dispatch_gap_pct"] == 20.0
        # the acceptance contract: >= 80% of the gap is named
        assert abs(rc["residual_pct"]) <= 20.0
        text = format_summary(s)
        assert "wall reconciliation" in text
        assert "blocked_on_device" in text

    def test_reconciliation_proxy_without_profile(self):
        s = summarize(self._recon_events(with_profile=False))
        rc = s["reconciliation"]
        assert rc["busy_source"].startswith("step/device_wait")
        assert rc["device_busy_s"] == pytest.approx(0.088)
        assert "blocked_on_device" not in rc["components"]
        # residual = 100 - 88 - 10 - 2 = 0
        assert rc["residual_s"] == pytest.approx(0.0, abs=1e-12)

    def test_reconciliation_not_inflated_by_process_count(self):
        """Merged 2-process stream, identical behavior: each process's
        data/wait is 20 ms/step — the component must read 20 ms, not
        the 40 ms a total/distinct-steps division would fabricate."""
        events = []
        for proc in ("p0", "p1"):
            for i in range(3):
                events.append({"name": "step/time_s", "value": 0.100,
                               "ts": float(i), "step": i,
                               "kind": "point",
                               "meta": {"process": proc}})
                events.append(_mk_span("step/dispatch", 0.010, step=i,
                                       process=proc))
                events.append(_mk_span("step/device_wait", 0.088,
                                       step=i, process=proc))
                events.append(_mk_span("data/wait", 0.020, step=i,
                                       process=proc))
        s = summarize(events)
        rc = s["reconciliation"]
        assert rc["components"]["data/wait"] == pytest.approx(0.020)
        assert rc["components"]["step/dispatch"] == pytest.approx(0.010)

    def test_family_totals_window(self):
        evs = [_mk_span("tune/measure", 2.0, mono=5.0),     # pre-loop
               _mk_span("data/wait", 0.5, mono=11.0)]       # in-loop
        totals = trace.family_totals(evs, window=(10.0, 20.0))
        assert totals == {"data/wait": pytest.approx(0.5)}
        assert "tune/measure" in trace.family_totals(evs)

    def test_no_spans_no_sections(self):
        s = summarize([{"name": "step/time_s", "value": 0.1, "ts": 0.0,
                        "step": 0, "kind": "point"}])
        assert "spans" not in s and "reconciliation" not in s


# ---------------------------------------------------------------------------
# multi-process merge: the committed skewed fixture
# ---------------------------------------------------------------------------

class TestMergeFixture:
    def test_offset_recovered_within_tolerance(self):
        from apex_tpu.telemetry.merge import merge_files
        merged, offsets = merge_files([P0, P1])
        assert offsets["p0"]["offset_s"] == 0.0
        assert offsets["p1"]["anchors"] == FIXTURE_STEPS
        assert offsets["p1"]["offset_s"] == pytest.approx(
            FIXTURE_SKEW, abs=0.01)

    def test_merged_events_tagged_and_aligned(self):
        from apex_tpu.telemetry.merge import merge_files
        merged, offsets = merge_files([P0, P1])
        procs = {(e.get("meta") or {}).get("process") for e in merged
                 if e["name"] != "merge/offset"}
        assert procs == {"p0", "p1"}
        # after alignment both processes' step-0 dispatch begins agree
        # to within the fixture's per-step jitter
        from apex_tpu.telemetry.merge import step_anchors
        a0 = step_anchors([e for e in merged
                           if e["meta"].get("process") == "p0"])
        a1 = step_anchors([e for e in merged
                           if e["meta"].get("process") == "p1"])
        for s in range(FIXTURE_STEPS):
            assert a1[s] - a0[s] == pytest.approx(0.0, abs=0.005)

    def test_straggler_names_slow_process(self):
        from apex_tpu.telemetry.merge import merge_files
        merged, _ = merge_files([P0, P1])
        s = summarize(merged)
        st = s["stragglers"]
        assert st["worst"]["process"] == "p1"
        assert st["worst"]["steps_worst"] == FIXTURE_STEPS
        # skew = 125 - median(95, 125) = 15 ms per step
        assert st["skew_s"]["mean"] == pytest.approx(0.015, abs=1e-6)
        # the excess is attributed to the input wait, by name
        attr = st["attribution"]
        assert attr and attr[0]["family"] == "data/wait"
        assert attr[0]["excess_s_per_step"] == pytest.approx(
            0.014, abs=1e-3)
        text = format_summary(s)
        assert "stragglers (2 processes" in text
        assert "worst: p1" in text
        assert "data/wait" in text

    def test_merge_cli(self, tmp_path, capsys):
        from apex_tpu.telemetry import cli
        out = str(tmp_path / "merged.jsonl")
        assert cli.main(["merge", P0, P1, "-o", out]) == 0
        printed = capsys.readouterr().out
        assert "clock offset" in printed
        from apex_tpu.telemetry.export import read_jsonl
        merged = read_jsonl(out)
        assert any(e["name"] == "merge/offset" for e in merged)
        # summarize CLI renders the straggler section on the merged file
        assert cli.main(["summarize", out]) == 0
        assert "stragglers" in capsys.readouterr().out

    def test_merge_cli_rerun_truncates_output(self, tmp_path, capsys):
        """Re-running merge into the same -o must REPLACE the file —
        write_jsonl appends by contract, and a doubled merged stream
        would double-count every series in the next summarize."""
        from apex_tpu.telemetry import cli
        from apex_tpu.telemetry.export import read_jsonl
        out = str(tmp_path / "merged.jsonl")
        assert cli.main(["merge", P0, P1, "-o", out]) == 0
        n1 = len(read_jsonl(out))
        assert cli.main(["merge", P0, P1, "-o", out]) == 0
        assert len(read_jsonl(out)) == n1

    def test_process_label_anchored_marker(self):
        """The p<N> marker must be separator-delimited and the LAST one
        wins — a bare search would label exp2-run-p0 as p2."""
        from apex_tpu.telemetry.merge import process_label
        assert process_label("run-p3.jsonl", 9) == "p3"
        assert process_label("exp2-run-p0.jsonl", 9) == "p0"
        assert process_label("exp2-run-p1.jsonl", 9) == "p1"
        assert process_label("p7.jsonl", 9) == "p7"
        assert process_label("plain.jsonl", 4) == "p4"

    def test_attribution_rates_survive_uneven_step_counts(self):
        """A process that recorded MORE steps must not read as a
        straggler just because its whole-run family totals are bigger —
        rates are per process-own step count."""
        events = []
        # p0: 3 steps; p1: 6 steps — identical per-step behavior
        for proc, steps in (("p0", 3), ("p1", 6)):
            for i in range(steps):
                events.append({"name": "step/time_s", "value": 0.1,
                               "ts": float(i), "step": i,
                               "kind": "point",
                               "meta": {"process": proc}})
                events.append(_mk_span("data/produce", 0.05, step=i,
                                       process=proc))
        s = summarize(events)
        st = s["stragglers"]
        # identical step times: no per-family excess fabricated for p1
        assert all(a["excess_s_per_step"] < 1e-9
                   for a in st.get("attribution", []))

    def test_fallback_anchor_uses_one_series(self):
        """Without spans, anchors come from ONE /time_s series
        (step/time_s preferred) — never whichever name appears first in
        the file, which would mismatch across differently-interleaved
        process files."""
        from apex_tpu.telemetry.merge import step_anchors

        def ev(name, step, ts, value):
            return {"name": name, "step": step, "ts": ts,
                    "value": value, "kind": "point"}

        # eval/time_s interleaved FIRST at every step
        events = []
        for i in range(3):
            events.append(ev("eval/time_s", i, 100.0 + i, 0.5))
            events.append(ev("step/time_s", i, 10.0 + i, 0.1))
        anchors = step_anchors(events)
        assert anchors == {i: pytest.approx(9.9 + i) for i in range(3)}

    def test_no_shared_anchors_warns_not_crashes(self):
        from apex_tpu.telemetry.merge import merge_streams
        merged, offsets = merge_streams([
            ("p0", [{"name": "x", "value": 1.0, "ts": 0.0,
                     "kind": "point"}]),
            ("p1", [{"name": "x", "value": 1.0, "ts": 5.0,
                     "kind": "point"}]),
        ])
        assert offsets["p1"]["anchors"] == 0
        assert offsets["p1"]["offset_s"] == 0.0


# ---------------------------------------------------------------------------
# unified host+device timeline
# ---------------------------------------------------------------------------

class TestTimeline:
    def _host_rows(self):
        # device fixture window: [0, 250] us. Anchor: profile/step 0
        # begins at mono 5.0 s -> aligned to the window start.
        return [
            {"name": "span/data/wait", "family": "data/wait",
             "dur_s": 100e-6, "begin_mono": 4.9999, "end_mono": 5.0,
             "ts": 0.0, "step": None, "tid": 7, "thread": "MainThread",
             "depth": 0, "process": None},
            {"name": "span/profile/step", "family": "profile/step",
             "dur_s": 250e-6, "begin_mono": 5.0, "end_mono": 5.00025,
             "ts": 0.0, "step": 0, "tid": 7, "thread": "MainThread",
             "depth": 0, "process": None},
        ]

    def test_build_timeline_lanes_and_anchor(self):
        from apex_tpu.pyprof import build_timeline
        from apex_tpu.pyprof.parse import load_trace
        tl = build_timeline(load_trace(DEVICE_TRACE), self._host_rows())
        evs = tl["traceEvents"]
        procs = {e["args"]["name"] for e in evs
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        assert procs == {"host", "device"}
        host = [e for e in evs if e.get("ph") == "X" and e["pid"] == 1]
        dev = [e for e in evs if e.get("ph") == "X" and e["pid"] == 2]
        assert len(host) == 2 and len(dev) == 4
        # the anchor: profile/step 0 lands exactly at the first kernel
        anchor = next(e for e in host if e["name"] == "profile/step")
        assert anchor["ts"] == pytest.approx(min(e["ts"] for e in dev))
        # everything re-zeroed at the earliest event (the data/wait)
        assert min(e["ts"] for e in evs if e.get("ph") == "X") == 0.0
        # valid JSON end to end
        assert json.loads(json.dumps(tl))["displayTimeUnit"] == "ms"

    def test_timeline_from_logdir_with_spans_file(self, tmp_path):
        import gzip
        import shutil
        from apex_tpu.pyprof import timeline_from_logdir
        from apex_tpu.pyprof.capture import SIDECAR_NAME
        ld = tmp_path / "logdir"
        ld.mkdir()
        shutil.copy(DEVICE_TRACE, ld / "fixture.trace.json")
        with gzip.open(ld / SIDECAR_NAME, "wt") as f:
            json.dump({"schema": 1, "module": "jit_step",
                       "host_spans": self._host_rows()}, f)
        # a spans JSONL adds spans from outside the capture window
        run = tmp_path / "run.jsonl"
        with open(run, "w") as f:
            f.write(json.dumps(_mk_span(
                "snapshot/save", 0.001, mono=5.001)) + "\n")
        tl = timeline_from_logdir(str(ld), spans_path=str(run))
        host_names = {e["name"] for e in tl["traceEvents"]
                      if e.get("ph") == "X" and e["pid"] == 1}
        assert host_names == {"data/wait", "profile/step",
                              "snapshot/save"}

    def test_timeline_without_spans_raises(self, tmp_path):
        import gzip
        import shutil
        from apex_tpu.pyprof import timeline_from_logdir
        from apex_tpu.pyprof.capture import SIDECAR_NAME
        ld = tmp_path / "logdir"
        ld.mkdir()
        shutil.copy(DEVICE_TRACE, ld / "fixture.trace.json")
        with gzip.open(ld / SIDECAR_NAME, "wt") as f:
            json.dump({"schema": 1, "module": "jit_step"}, f)
        with pytest.raises(ValueError, match="no host spans"):
            timeline_from_logdir(str(ld))

    def test_cli_timeline_flag(self, tmp_path, capsys):
        import gzip
        import shutil
        from apex_tpu.pyprof import cli as pyprof_cli
        from apex_tpu.pyprof.capture import SIDECAR_NAME
        ld = tmp_path / "logdir"
        ld.mkdir()
        shutil.copy(DEVICE_TRACE, ld / "fixture.trace.json")
        with gzip.open(ld / SIDECAR_NAME, "wt") as f:
            json.dump({"schema": 1, "module": "jit_step",
                       "host_spans": self._host_rows()}, f)
        out = str(tmp_path / "out.trace.json")
        assert pyprof_cli.main(["report", str(ld),
                                "--timeline", out]) == 0
        assert "timeline:" in capsys.readouterr().out
        tl = json.load(open(out))
        assert tl["traceEvents"]
