"""Multi-tensor kernel parity tests — port of the reference L0 kernel tests
(tests/L0/run_amp/test_multi_tensor_scale.py:129, test_multi_tensor_axpby.py:186,
test_multi_tensor_l2norm.py:90): sweep tensor-list sizes and dtype combos,
assert math vs a plain reference and check the overflow flag contract."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import ops
from apex_tpu.ops import pallas_mt


def make_tree(key, sizes, dtype):
    ks = jax.random.split(key, len(sizes))
    return {f"t{i}": jax.random.normal(k, (s,), jnp.float32).astype(dtype)
            for i, (k, s) in enumerate(zip(ks, sizes))}


SIZES = [[7], [33, 1], [1024, 16, 555], [2048 * 32 + 1, 3]]
DTYPES = [jnp.float32, jnp.bfloat16, jnp.float16]


@pytest.mark.parametrize("sizes", SIZES)
@pytest.mark.parametrize("dtype", DTYPES)
def test_scale(sizes, dtype):
    tree = make_tree(jax.random.PRNGKey(0), sizes, dtype)
    out, overflow = ops.multi_tensor_scale(tree, 4.0)
    assert not bool(overflow)
    for k in tree:
        ref = (tree[k].astype(jnp.float32) * 4.0).astype(dtype)
        np.testing.assert_allclose(np.asarray(out[k], np.float32),
                                   np.asarray(ref, np.float32), rtol=1e-6)


@pytest.mark.parametrize("bad", [float("inf"), float("nan")])
def test_scale_overflow(bad):
    tree = make_tree(jax.random.PRNGKey(1), [64, 128], jnp.float32)
    tree["t1"] = tree["t1"].at[17].set(bad)
    _, overflow = ops.multi_tensor_scale(tree, 2.0)
    assert bool(overflow)


@pytest.mark.parametrize("sizes", SIZES)
def test_axpby(sizes):
    k1, k2 = jax.random.split(jax.random.PRNGKey(2))
    x = make_tree(k1, sizes, jnp.float32)
    y = make_tree(k2, sizes, jnp.float32)
    out, overflow = ops.multi_tensor_axpby(2.0, x, -3.0, y)
    assert not bool(overflow)
    for k in x:
        np.testing.assert_allclose(np.asarray(out[k]),
                                   2.0 * np.asarray(x[k]) - 3.0 * np.asarray(y[k]),
                                   rtol=1e-5)


def test_axpby_overflow_either_arg():
    sizes = [256, 9]
    k1, k2 = jax.random.split(jax.random.PRNGKey(3))
    for which in (0, 1):
        x = make_tree(k1, sizes, jnp.float32)
        y = make_tree(k2, sizes, jnp.float32)
        if which == 0:
            x["t0"] = x["t0"].at[0].set(float("nan"))
        else:
            y["t1"] = y["t1"].at[3].set(float("inf"))
        _, overflow = ops.multi_tensor_axpby(1.0, x, 1.0, y)
        assert bool(overflow)


@pytest.mark.parametrize("sizes", SIZES)
@pytest.mark.parametrize("per_tensor", [False, True])
def test_l2norm(sizes, per_tensor):
    tree = make_tree(jax.random.PRNGKey(4), sizes, jnp.float32)
    gnorm, per = ops.multi_tensor_l2norm(tree, per_tensor=per_tensor)
    flat = np.concatenate([np.asarray(v).ravel() for v in tree.values()])
    np.testing.assert_allclose(float(gnorm), np.linalg.norm(flat), rtol=1e-5)
    if per_tensor:
        for k in tree:
            np.testing.assert_allclose(float(per[k]),
                                       np.linalg.norm(np.asarray(tree[k])),
                                       rtol=1e-5)


def test_mixed_dtype_tree():
    tree = {"a": jnp.ones((100,), jnp.bfloat16),
            "b": jnp.full((50,), 2.0, jnp.float32)}
    out, overflow = ops.multi_tensor_scale(tree, 0.5)
    assert out["a"].dtype == jnp.bfloat16
    assert out["b"].dtype == jnp.float32
    assert not bool(overflow)
    np.testing.assert_allclose(np.asarray(out["b"]), 1.0)


# ---------------------------------------------------------------------------
# Pallas kernels in interpret mode (CPU) vs the jnp path
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [100, 128 * 512, 128 * 512 * 2 + 77])
def test_pallas_scale_flat(n):
    x = jax.random.normal(jax.random.PRNGKey(5), (n,), jnp.float32)
    y, of = pallas_mt.scale_flat(x, 3.0)
    assert not bool(of)
    np.testing.assert_allclose(np.asarray(y), 3.0 * np.asarray(x), rtol=1e-6)
    x = x.at[n // 2].set(float("nan"))
    _, of = pallas_mt.scale_flat(x, 3.0)
    assert bool(of)


def test_pallas_axpby_flat():
    n = 128 * 600 + 13
    k1, k2 = jax.random.split(jax.random.PRNGKey(6))
    x = jax.random.normal(k1, (n,), jnp.float32)
    y = jax.random.normal(k2, (n,), jnp.float32)
    out, of = pallas_mt.axpby_flat(1.5, x, -0.5, y)
    assert not bool(of)
    np.testing.assert_allclose(np.asarray(out),
                               1.5 * np.asarray(x) - 0.5 * np.asarray(y),
                               rtol=1e-5, atol=1e-6)


def test_pallas_l2norm_flat():
    n = 128 * 1024 + 7
    x = jax.random.normal(jax.random.PRNGKey(7), (n,), jnp.float32)
    got = pallas_mt.l2norm_sq_flat(x)
    np.testing.assert_allclose(float(got), float(np.sum(np.asarray(x) ** 2)),
                               rtol=1e-5)


def test_pallas_adam_flat_matches_jnp():
    n = 128 * 512 + 999
    keys = jax.random.split(jax.random.PRNGKey(8), 4)
    g = jax.random.normal(keys[0], (n,), jnp.float32)
    p = jax.random.normal(keys[1], (n,), jnp.float32)
    m = jax.random.normal(keys[2], (n,), jnp.float32) * 0.1
    v = jnp.abs(jax.random.normal(keys[3], (n,), jnp.float32)) * 0.01
    kw = dict(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
              bc1=1.0 - 0.9 ** 3, bc2=1.0 - 0.999 ** 3,
              adam_w_mode=True, weight_decay=0.01)
    p2, m2, v2 = pallas_mt.adam_flat(g, p, m, v, **kw)
    # jnp reference
    m_ref = 0.9 * m + 0.1 * g
    v_ref = 0.999 * v + 0.001 * g * g
    upd = (m_ref / kw["bc1"]) / (jnp.sqrt(v_ref / kw["bc2"]) + 1e-8) + 0.01 * p
    p_ref = p - 1e-3 * upd
    np.testing.assert_allclose(np.asarray(p2), np.asarray(p_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(m2), np.asarray(m_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(v2), np.asarray(v_ref),
                               rtol=1e-5, atol=1e-6)


def test_bucket_roundtrip():
    tree = {"w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
            "b": jnp.ones((5,), jnp.float32),
            "h": jnp.zeros((2, 2), jnp.bfloat16)}
    bks, spec = ops.tree_flatten_buckets(tree)
    back = ops.tree_unflatten_buckets(bks, spec)
    for k in tree:
        assert back[k].dtype == tree[k].dtype
        np.testing.assert_array_equal(np.asarray(back[k], np.float32),
                                      np.asarray(tree[k], np.float32))
