"""Multi-tensor kernel parity tests — port of the reference L0 kernel tests
(tests/L0/run_amp/test_multi_tensor_scale.py:129, test_multi_tensor_axpby.py:186,
test_multi_tensor_l2norm.py:90): sweep tensor-list sizes and dtype combos,
assert math vs a plain reference and check the overflow flag contract."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import ops
from apex_tpu.ops import pallas_mt


def make_tree(key, sizes, dtype):
    ks = jax.random.split(key, len(sizes))
    return {f"t{i}": jax.random.normal(k, (s,), jnp.float32).astype(dtype)
            for i, (k, s) in enumerate(zip(ks, sizes))}


SIZES = [[7], [33, 1], [1024, 16, 555], [2048 * 32 + 1, 3]]
DTYPES = [jnp.float32, jnp.bfloat16, jnp.float16]


@pytest.mark.parametrize("sizes", SIZES)
@pytest.mark.parametrize("dtype", DTYPES)
def test_scale(sizes, dtype):
    tree = make_tree(jax.random.PRNGKey(0), sizes, dtype)
    out, overflow = ops.multi_tensor_scale(tree, 4.0)
    assert not bool(overflow)
    for k in tree:
        ref = (tree[k].astype(jnp.float32) * 4.0).astype(dtype)
        np.testing.assert_allclose(np.asarray(out[k], np.float32),
                                   np.asarray(ref, np.float32), rtol=1e-6)


@pytest.mark.parametrize("bad", [float("inf"), float("nan")])
def test_scale_overflow(bad):
    tree = make_tree(jax.random.PRNGKey(1), [64, 128], jnp.float32)
    tree["t1"] = tree["t1"].at[17].set(bad)
    _, overflow = ops.multi_tensor_scale(tree, 2.0)
    assert bool(overflow)


@pytest.mark.parametrize("sizes", SIZES)
def test_axpby(sizes):
    k1, k2 = jax.random.split(jax.random.PRNGKey(2))
    x = make_tree(k1, sizes, jnp.float32)
    y = make_tree(k2, sizes, jnp.float32)
    out, overflow = ops.multi_tensor_axpby(2.0, x, -3.0, y)
    assert not bool(overflow)
    for k in x:
        np.testing.assert_allclose(np.asarray(out[k]),
                                   2.0 * np.asarray(x[k]) - 3.0 * np.asarray(y[k]),
                                   rtol=1e-5)


def test_axpby_overflow_either_arg():
    sizes = [256, 9]
    k1, k2 = jax.random.split(jax.random.PRNGKey(3))
    for which in (0, 1):
        x = make_tree(k1, sizes, jnp.float32)
        y = make_tree(k2, sizes, jnp.float32)
        if which == 0:
            x["t0"] = x["t0"].at[0].set(float("nan"))
        else:
            y["t1"] = y["t1"].at[3].set(float("inf"))
        _, overflow = ops.multi_tensor_axpby(1.0, x, 1.0, y)
        assert bool(overflow)


@pytest.mark.parametrize("sizes", SIZES)
@pytest.mark.parametrize("per_tensor", [False, True])
def test_l2norm(sizes, per_tensor):
    tree = make_tree(jax.random.PRNGKey(4), sizes, jnp.float32)
    gnorm, per = ops.multi_tensor_l2norm(tree, per_tensor=per_tensor)
    flat = np.concatenate([np.asarray(v).ravel() for v in tree.values()])
    np.testing.assert_allclose(float(gnorm), np.linalg.norm(flat), rtol=1e-5)
    if per_tensor:
        for k in tree:
            np.testing.assert_allclose(float(per[k]),
                                       np.linalg.norm(np.asarray(tree[k])),
                                       rtol=1e-5)


def test_mixed_dtype_tree():
    tree = {"a": jnp.ones((100,), jnp.bfloat16),
            "b": jnp.full((50,), 2.0, jnp.float32)}
    out, overflow = ops.multi_tensor_scale(tree, 0.5)
    assert out["a"].dtype == jnp.bfloat16
    assert out["b"].dtype == jnp.float32
    assert not bool(overflow)
    np.testing.assert_allclose(np.asarray(out["b"]), 1.0)


# ---------------------------------------------------------------------------
# Pallas kernels in interpret mode (CPU) vs the jnp path
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [100, 128 * 512, 128 * 512 * 2 + 77])
def test_pallas_scale_flat(n):
    x = jax.random.normal(jax.random.PRNGKey(5), (n,), jnp.float32)
    y, of = pallas_mt.scale_flat(x, 3.0)
    assert not bool(of)
    np.testing.assert_allclose(np.asarray(y), 3.0 * np.asarray(x), rtol=1e-6)
    x = x.at[n // 2].set(float("nan"))
    _, of = pallas_mt.scale_flat(x, 3.0)
    assert bool(of)


def test_pallas_axpby_flat():
    n = 128 * 600 + 13
    k1, k2 = jax.random.split(jax.random.PRNGKey(6))
    x = jax.random.normal(k1, (n,), jnp.float32)
    y = jax.random.normal(k2, (n,), jnp.float32)
    out, of = pallas_mt.axpby_flat(1.5, x, -0.5, y)
    assert not bool(of)
    np.testing.assert_allclose(np.asarray(out),
                               1.5 * np.asarray(x) - 0.5 * np.asarray(y),
                               rtol=1e-5, atol=1e-6)


def test_pallas_l2norm_flat():
    n = 128 * 1024 + 7
    x = jax.random.normal(jax.random.PRNGKey(7), (n,), jnp.float32)
    got = pallas_mt.l2norm_sq_flat(x)
    np.testing.assert_allclose(float(got), float(np.sum(np.asarray(x) ** 2)),
                               rtol=1e-5)


def test_pallas_adam_flat_matches_jnp():
    n = 128 * 512 + 999
    keys = jax.random.split(jax.random.PRNGKey(8), 4)
    g = jax.random.normal(keys[0], (n,), jnp.float32)
    p = jax.random.normal(keys[1], (n,), jnp.float32)
    m = jax.random.normal(keys[2], (n,), jnp.float32) * 0.1
    v = jnp.abs(jax.random.normal(keys[3], (n,), jnp.float32)) * 0.01
    kw = dict(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
              bc1=1.0 - 0.9 ** 3, bc2=1.0 - 0.999 ** 3,
              adam_w_mode=True, weight_decay=0.01)
    p2, m2, v2 = pallas_mt.adam_flat(g, p, m, v, **kw)
    # jnp reference
    m_ref = 0.9 * m + 0.1 * g
    v_ref = 0.999 * v + 0.001 * g * g
    upd = (m_ref / kw["bc1"]) / (jnp.sqrt(v_ref / kw["bc2"]) + 1e-8) + 0.01 * p
    p_ref = p - 1e-3 * upd
    np.testing.assert_allclose(np.asarray(p2), np.asarray(p_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(m2), np.asarray(m_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(v2), np.asarray(v_ref),
                               rtol=1e-5, atol=1e-6)


MIXED_SHAPES = [(7,), (300, 5), (128,), (2049,), (64, 129)]


def mixed_trees(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4 * len(MIXED_SHAPES))
    mk = lambda o: {f"t{j}": jax.random.normal(
        ks[o * len(MIXED_SHAPES) + j], s, jnp.float32)
        for j, s in enumerate(MIXED_SHAPES)}
    g, p = mk(0), mk(1)
    m = jax.tree_util.tree_map(lambda x: x * 0.1, mk(2))
    v = jax.tree_util.tree_map(lambda x: jnp.abs(x) * 0.01, mk(3))
    return g, p, m, v


def assert_trees_close(a, b, rtol=1e-5, atol=1e-6):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x, np.float32),
                                   np.asarray(y, np.float32),
                                   rtol=rtol, atol=atol)


def test_pallas_aligned_bucket_roundtrip():
    from apex_tpu.ops import buckets
    g, _, _, _ = mixed_trees()
    leaves = list(g.values())
    flat, spec = buckets.flatten_tensors(leaves, align=128)
    assert all(o % 128 == 0 for o in spec.offsets)
    back = buckets.unflatten_tensors(flat, spec)
    for orig, got in zip(leaves, back):
        np.testing.assert_array_equal(np.asarray(orig), np.asarray(got))


def test_pallas_l2norm_per_tensor_seg():
    g, _, _, _ = mixed_trees()
    gnorm, per = pallas_mt.l2norm_tree_per_tensor(g)
    flat = np.concatenate([np.asarray(v).ravel() for v in g.values()])
    np.testing.assert_allclose(float(gnorm), np.linalg.norm(flat), rtol=1e-5)
    for k in g:
        np.testing.assert_allclose(float(per[k]),
                                   np.linalg.norm(np.asarray(g[k])),
                                   rtol=1e-5)


@pytest.mark.parametrize("momentum,dampening,nesterov,wd_after,first", [
    (0.9, 0.0, False, False, False),
    (0.9, 0.1, False, True, True),
    (0.9, 0.0, True, False, False),
    (0.0, 0.0, False, False, False),
])
def test_pallas_sgd_tree_matches_jnp(momentum, dampening, nesterov, wd_after,
                                     first):
    from apex_tpu.ops import multi_tensor as mt
    g, p, m, _ = mixed_trees(1)
    kw = dict(lr=0.1, weight_decay=0.01, momentum=momentum,
              dampening=dampening, nesterov=nesterov,
              wd_after_momentum=wd_after, scale=0.5)
    got_p, got_m = pallas_mt.sgd_tree(g, p, m, first=first, **kw)
    ref_p, ref_m = mt.multi_tensor_sgd(g, p, m, first_run=first, **kw)
    assert_trees_close(got_p, ref_p)
    assert_trees_close(got_m, ref_m)


def test_pallas_sgd_model_copy_output():
    from apex_tpu.ops import multi_tensor as mt
    g, p, m, _ = mixed_trees(2)
    template = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), p)
    got_p, got_m, got_model = pallas_mt.sgd_tree(
        g, p, m, lr=0.1, weight_decay=0.0, momentum=0.9, dampening=0.0,
        nesterov=False, wd_after_momentum=False, first=False,
        model_out_template=template)
    for k in p:
        assert got_model[k].dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got_model[k], np.float32),
            np.asarray(got_p[k].astype(jnp.bfloat16), np.float32))


def test_pallas_adagrad_tree_matches_jnp():
    from apex_tpu.ops import multi_tensor as mt
    g, p, _, h = mixed_trees(3)
    kw = dict(weight_decay=0.01)
    got_p, got_h = pallas_mt.adagrad_tree(g, p, h, lr=0.1, eps=1e-10, **kw)
    ref_p, ref_h = mt.multi_tensor_adagrad(g, p, h, lr=0.1, epsilon=1e-10,
                                           **kw)
    assert_trees_close(got_p, ref_p)
    assert_trees_close(got_h, ref_h)


@pytest.mark.parametrize("use_ratio", [True, False])
def test_pallas_lamb_tree_matches_jnp(use_ratio):
    from apex_tpu.ops import multi_tensor as mt
    g, p, m, v = mixed_trees(4)
    wd = 0.01 if use_ratio else 0.0
    got_p, got_m, got_v = pallas_mt.lamb_tree(
        g, p, m, v, lr=0.01, beta1=0.9, beta2=0.999, beta3=0.1, eps=1e-6,
        bc1=1 - 0.9 ** 3, bc2=1 - 0.999 ** 3, adam_w_mode=True,
        weight_decay=wd, inv_clip=1.0, use_ratio=use_ratio)
    ref_p, ref_m, ref_v = mt.multi_tensor_lamb(
        g, p, m, v, lr=0.01, beta1=0.9, beta2=0.999, eps=1e-6, step=3,
        weight_decay=wd, use_nvlamb=use_ratio and wd == 0.0,
        max_grad_norm=0.0, global_grad_norm=jnp.asarray(0.0))
    assert_trees_close(got_p, ref_p, rtol=1e-4)
    assert_trees_close(got_m, ref_m, rtol=1e-4)
    assert_trees_close(got_v, ref_v, rtol=1e-4, atol=1e-7)


@pytest.mark.parametrize("first,init_zero", [(False, False), (True, False),
                                             (True, True)])
def test_pallas_novograd_tree_matches_jnp(first, init_zero):
    from apex_tpu.ops import multi_tensor as mt
    g, p, m, _ = mixed_trees(5)
    vs = jax.tree_util.tree_map(lambda x: jnp.asarray(0.5, jnp.float32), g)
    got_p, got_m, got_v = pallas_mt.novograd_tree(
        g, p, m, vs, lr=0.01, beta1=0.95, beta2=0.98, beta3=0.05, eps=1e-8,
        bc1=1 - 0.95 ** 3, bc2=1 - 0.98 ** 3, weight_decay=0.01,
        init_zero=init_zero, first=first)
    ref_p, ref_m, ref_v = mt.multi_tensor_novograd(
        g, p, m, vs, lr=0.01, beta1=0.95, beta2=0.98, eps=1e-8, step=3,
        weight_decay=0.01, bias_correction=True, grad_averaging=True,
        init_zero=init_zero, first=first)
    assert_trees_close(got_p, ref_p, rtol=1e-4)
    assert_trees_close(got_m, ref_m, rtol=1e-4)
    for k in g:
        np.testing.assert_allclose(float(got_v[k]), float(ref_v[k]),
                                   rtol=1e-5)


def test_check_overflow():
    g, _, _, _ = mixed_trees(6)
    assert not bool(ops.multi_tensor_check_overflow(g))
    g["t1"] = g["t1"].at[0, 0].set(float("inf"))
    assert bool(ops.multi_tensor_check_overflow(g))


def test_bucket_roundtrip():
    tree = {"w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
            "b": jnp.ones((5,), jnp.float32),
            "h": jnp.zeros((2, 2), jnp.bfloat16)}
    bks, spec = ops.tree_flatten_buckets(tree)
    back = ops.tree_unflatten_buckets(bks, spec)
    for k in tree:
        assert back[k].dtype == tree[k].dtype
        np.testing.assert_array_equal(np.asarray(back[k], np.float32),
                                      np.asarray(tree[k], np.float32))
