"""Fused layer tests — ports of the reference layer-parity suites:
FusedLayerNorm vs plain layer norm (tests/L0/run_fused_layer_norm/
test_fused_layer_norm.py:42), fused MLP vs a Linear stack incl. grad check
(tests/L0/run_mlp/test_mlp.py:223), xentropy vs reference math + label
smoothing (apex/contrib/test/ label-smoothing tests)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import normalization
from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
from apex_tpu.mlp import MLP, mlp_function
from apex_tpu.ops import pallas_layer_norm as plln


# ---------------------------------------------------------------------------
# LayerNorm
# ---------------------------------------------------------------------------

def _ref_layer_norm(x, w, b, eps=1e-5):
    x64 = np.asarray(x, np.float64)
    mu = x64.mean(-1, keepdims=True)
    var = x64.var(-1, keepdims=True)
    return (x64 - mu) / np.sqrt(var + eps) * np.asarray(w) + np.asarray(b)


@pytest.mark.parametrize("shape", [(4, 256), (2, 3, 128), (5, 384)])
def test_layer_norm_forward(shape):
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, shape, jnp.float32) * 3 + 1
    d = shape[-1]
    w = jax.random.normal(jax.random.PRNGKey(1), (d,)) + 1.0
    b = jax.random.normal(jax.random.PRNGKey(2), (d,))
    y = normalization.layer_norm(x, w, b)
    np.testing.assert_allclose(np.asarray(y), _ref_layer_norm(x, w, b),
                               rtol=1e-4, atol=1e-4)


def test_layer_norm_pallas_matches_jnp():
    # force the pallas path (interpret mode on CPU) vs the jnp fallback
    x = jax.random.normal(jax.random.PRNGKey(3), (48, 256), jnp.float32)
    w = jnp.ones((256,)) * 1.3
    b = jnp.zeros((256,)) + 0.1
    y_pallas = plln.ln_fwd(x, w, b, 1e-5)[0]
    y_ref = _ref_layer_norm(x, w, b)
    np.testing.assert_allclose(np.asarray(y_pallas), y_ref, rtol=1e-4,
                               atol=1e-4)


def test_layer_norm_pallas_grads():
    x = jax.random.normal(jax.random.PRNGKey(4), (24, 128), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(5), (128,)) + 1.0
    b = jnp.zeros((128,))

    from apex_tpu.normalization.fused_layer_norm import _layer_norm_pallas

    def f_pallas(x, w, b):
        return jnp.sum(jnp.sin(_layer_norm_pallas(x, w, b, 1e-5)))

    def f_ref(x, w, b):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean((x - mu) ** 2, axis=-1, keepdims=True)
        y = (x - mu) * jax.lax.rsqrt(var + 1e-5) * w + b
        return jnp.sum(jnp.sin(y))

    gx1, gw1, gb1 = jax.grad(f_pallas, argnums=(0, 1, 2))(x, w, b)
    gx2, gw2, gb2 = jax.grad(f_ref, argnums=(0, 1, 2))(x, w, b)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2), rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2), rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb1), np.asarray(gb2), rtol=1e-3,
                               atol=1e-4)


def test_fused_layer_norm_module():
    m = normalization.FusedLayerNorm(normalized_shape=64)
    x = jax.random.normal(jax.random.PRNGKey(6), (8, 64))
    params = m.init(jax.random.PRNGKey(7), x)
    y = m.apply(params, x)
    np.testing.assert_allclose(np.asarray(y.mean(-1)), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y.std(-1)), 1.0, atol=1e-2)


def test_fused_rms_norm_module():
    m = normalization.FusedRMSNorm(normalized_shape=64)
    x = jax.random.normal(jax.random.PRNGKey(8), (8, 64)) * 5
    params = m.init(jax.random.PRNGKey(9), x)
    y = m.apply(params, x)
    rms = np.sqrt(np.mean(np.asarray(y) ** 2, -1))
    np.testing.assert_allclose(rms, 1.0, atol=1e-2)


# ---------------------------------------------------------------------------
# xentropy
# ---------------------------------------------------------------------------

def test_xentropy_matches_reference_math():
    logits = jax.random.normal(jax.random.PRNGKey(10), (32, 100)) * 4
    labels = jax.random.randint(jax.random.PRNGKey(11), (32,), 0, 100)
    losses = softmax_cross_entropy_loss(logits, labels, 0.0)
    # reference: -log softmax picked
    x = np.asarray(logits, np.float64)
    lse = np.log(np.exp(x - x.max(-1, keepdims=True)).sum(-1)) + x.max(-1)
    want = lse - x[np.arange(32), np.asarray(labels)]
    np.testing.assert_allclose(np.asarray(losses), want, rtol=1e-5,
                               atol=1e-5)


def test_xentropy_label_smoothing():
    logits = jax.random.normal(jax.random.PRNGKey(12), (16, 50))
    labels = jax.random.randint(jax.random.PRNGKey(13), (16,), 0, 50)
    s = 0.1
    losses = softmax_cross_entropy_loss(logits, labels, s)
    x = np.asarray(logits, np.float64)
    lse = np.log(np.exp(x - x.max(-1, keepdims=True)).sum(-1)) + x.max(-1)
    picked = x[np.arange(16), np.asarray(labels)]
    want = lse - (1 - s) * picked - s * x.mean(-1)
    np.testing.assert_allclose(np.asarray(losses), want, rtol=1e-5,
                               atol=1e-5)


def test_xentropy_grad_matches_autodiff():
    logits = jax.random.normal(jax.random.PRNGKey(14), (8, 30))
    labels = jax.random.randint(jax.random.PRNGKey(15), (8,), 0, 30)

    def fused(lg):
        return jnp.mean(softmax_cross_entropy_loss(lg, labels, 0.1))

    def plain(lg):
        lp = jax.nn.log_softmax(lg)
        onehot = jax.nn.one_hot(labels, 30)
        soft = 0.9 * onehot + 0.1 / 30
        return jnp.mean(-jnp.sum(soft * lg, -1)
                        + jax.nn.logsumexp(lg, -1))

    g1 = jax.grad(fused)(logits)
    g2 = jax.grad(plain)(logits)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-4,
                               atol=1e-6)


# ---------------------------------------------------------------------------
# MLP
# ---------------------------------------------------------------------------

def test_mlp_matches_dense_stack():
    import flax.linen as nn

    m = MLP(mlp_sizes=(16, 32, 8), activation="relu")
    x = jax.random.normal(jax.random.PRNGKey(16), (4, 16))
    params = m.init(jax.random.PRNGKey(17), x)
    y = m.apply(params, x)

    w0 = params["params"]["weight_0"]
    b0 = params["params"]["bias_0"]
    w1 = params["params"]["weight_1"]
    b1 = params["params"]["bias_1"]
    want = jnp.maximum(x @ w0.T + b0, 0) @ w1.T + b1
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), rtol=1e-5,
                               atol=1e-6)


def test_mlp_gradcheck():
    # reference test_mlp.py:223 runs torch gradcheck; here: fp64 finite
    # differences vs reverse-mode AD
    with jax.enable_x64():
        m = MLP(mlp_sizes=(8, 16, 4), activation="sigmoid")
        x = jax.random.normal(jax.random.PRNGKey(18), (3, 8), jnp.float64)
        params = m.init(jax.random.PRNGKey(19), x)
        params = jax.tree.map(lambda p: p.astype(jnp.float64), params)

        def f(p):
            return jnp.sum(m.apply(p, x) ** 2)

        from jax.test_util import check_grads
        check_grads(f, (params,), order=1, modes=["rev"], atol=1e-5,
                    rtol=1e-5)


def test_mlp_no_bias():
    m = MLP(mlp_sizes=(8, 4), bias=False)
    x = jnp.ones((2, 8))
    params = m.init(jax.random.PRNGKey(20), x)
    assert "bias_0" not in params["params"]


# ---------------------------------------------------------------------------
# fused channel moments (Pallas BN-stats kernel, reference welford.cu)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rows,c", [(64, 128), (1000, 256), (8, 128),
                                    (64, 64), (128, 32)])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_sum_sumsq_matches_jnp(rows, c, dtype):
    from apex_tpu.ops.pallas_moments import fused_sum_sumsq

    x = jax.random.normal(jax.random.PRNGKey(0), (rows, c), dtype)
    s, ss = jax.jit(fused_sum_sumsq)(x)
    x32 = np.asarray(x, np.float32)
    np.testing.assert_allclose(np.asarray(s), x32.sum(0), rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(ss), (x32 * x32).sum(0),
                               rtol=1e-4, atol=1e-4)


def test_fused_sum_sumsq_grads():
    from apex_tpu.ops.pallas_moments import fused_sum_sumsq

    x = jax.random.normal(jax.random.PRNGKey(1), (96, 128))

    def f(x_):
        s, ss = fused_sum_sumsq(x_)
        return jnp.sum(s * 3.0) + jnp.sum(ss * 0.5)

    got = jax.grad(f)(x)
    want = 3.0 + 2.0 * 0.5 * x  # d/dx [3*sum + 0.5*sumsq]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_local_syncbn_matches_flax_batchnorm():
    """SyncBatchNorm with axis_name=None (the local fused path that now
    backs the ResNet models) must match flax nn.BatchNorm in train mode."""
    import flax.linen as nn
    from apex_tpu.parallel import SyncBatchNorm

    x = jax.random.normal(jax.random.PRNGKey(2), (4, 8, 8, 128))

    ours = SyncBatchNorm(axis_name=None, use_running_average=False)
    ref = nn.BatchNorm(use_running_average=False, momentum=0.9,
                       epsilon=1e-5)
    vo = ours.init(jax.random.PRNGKey(3), x)
    vr = ref.init(jax.random.PRNGKey(3), x)
    yo, _ = ours.apply(vo, x, mutable=["batch_stats"])
    yr, _ = ref.apply(vr, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(yo), np.asarray(yr), rtol=1e-4,
                               atol=1e-5)


def test_local_syncbn_scale_init():
    import flax.linen as nn
    from apex_tpu.parallel import SyncBatchNorm

    x = jax.random.normal(jax.random.PRNGKey(4), (2, 4, 4, 128))
    m = SyncBatchNorm(axis_name=None, use_running_average=False,
                      scale_init=nn.initializers.zeros)
    v = m.init(jax.random.PRNGKey(5), x)
    np.testing.assert_array_equal(np.asarray(v["params"]["scale"]), 0.0)


def test_resnet_s2d_stem_matches_conv7():
    """stem='space_to_depth' with conv7_to_s2d_kernel-mapped weights must
    reproduce the 7x7/2 stem exactly (the TPU MLPerf input transform is a
    re-parameterization, not a different function — VERDICT r2 #2)."""
    import jax
    import jax.numpy as jnp
    from apex_tpu.models import ResNet18
    from apex_tpu.models.resnet import conv7_to_s2d_kernel, space_to_depth

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 64, 3))
    m7 = ResNet18(num_classes=10)
    ms = ResNet18(num_classes=10, stem="space_to_depth")
    v7 = m7.init(jax.random.PRNGKey(1), x, train=False)

    params_s2d = dict(v7["params"])
    params_s2d["conv_init"] = {
        "kernel": conv7_to_s2d_kernel(v7["params"]["conv_init"]["kernel"])}
    y7 = m7.apply({"params": v7["params"],
                   "batch_stats": v7["batch_stats"]}, x, train=False)
    ys = ms.apply({"params": params_s2d,
                   "batch_stats": v7["batch_stats"]}, x, train=False)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(y7),
                               rtol=1e-4, atol=1e-4)

    # the transform itself: block (i, j) of pixel (2p+i, 2q+j) lands at
    # depth (i*2 + j)*C + c
    s2d = space_to_depth(x, 2)
    np.testing.assert_array_equal(np.asarray(s2d[:, 3, 5, 3:6]),
                                  np.asarray(x[:, 6, 11, :]))
