"""serve.load_model tests: the SnapshotManager round-trip, the
validation ORDER (layout fingerprint and model-spec rejection both fire
before any payload materializes), the params-only fallback, and the
opt-in quantize/prune transforms with their parity bounds."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp, optimizers, serve
from apex_tpu.resilience.snapshot import SnapshotManager
from apex_tpu.serve.model import ModelSpec
from apex_tpu.serve.quant import (dequantize_int8, per_channel_int8,
                                  quantize_params)

MODEL_MD = {"vocab": 61, "layers": 2, "embed_dim": 32, "heads": 4,
            "max_seq": 64, "mlp_ratio": 4, "moe": False,
            "relative_bias": False, "alibi": False}


def _train_lm_state(opt_level="O0"):
    """The exact (params, opt_state) structure train_lm snapshots —
    fp32 flax init, amp model cast, amp-wrapped FusedAdam state over
    the cast params (mirrors serve.loader._template)."""
    spec = ModelSpec.from_dict(MODEL_MD)
    model = spec.model()
    p32 = model.init(jax.random.PRNGKey(0),
                     jnp.zeros((1, 16), jnp.int32))["params"]
    p = amp.cast_model(p32, amp.resolve(opt_level,
                                        keep_batchnorm_fp32=False))
    _, aopt = amp.initialize(None, optimizers.FusedAdam(lr=1e-3),
                             opt_level=opt_level, verbosity=0)
    return spec, p, aopt.init(p)


def _save(tmp_path, state, *, extra=None, layout=None, step=5):
    mgr = SnapshotManager(str(tmp_path))
    assert mgr.save(state, step=step, layout=layout, extra=extra)
    return str(tmp_path)


@pytest.fixture(scope="module")
def snap(tmp_path_factory):
    d = tmp_path_factory.mktemp("snap")
    spec, p, opt = _train_lm_state()
    _save(d, (p, opt), extra={"opt_level": "O0", "model": MODEL_MD})
    return str(d), p


def test_roundtrip(snap):
    d, p = snap
    loaded = serve.load_model(d)
    assert loaded.step == 5
    assert loaded.spec.vocab == 61 and loaded.spec.max_seq == 64
    assert loaded.quant is None and loaded.pruned is False
    for a, b in zip(jax.tree_util.tree_leaves(loaded.params),
                    jax.tree_util.tree_leaves(p)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_params_only_snapshot(tmp_path):
    """The serve-side re-publish format (params, no optimizer state)
    restores through the fallback template."""
    spec, p, _ = _train_lm_state()
    d = _save(tmp_path, p,
              extra={"opt_level": "O0", "model": MODEL_MD})
    loaded = serve.load_model(d)
    np.testing.assert_array_equal(
        np.asarray(jax.tree_util.tree_leaves(loaded.params)[0]),
        np.asarray(jax.tree_util.tree_leaves(p)[0]))


def test_missing_snapshot_dir(tmp_path):
    with pytest.raises(ValueError, match="--snapshot-dir"):
        serve.load_model(str(tmp_path / "nope"))


def test_missing_model_extra(tmp_path):
    """A manifest without extra['model'] (pre-serving trainer) fails
    with the pass-spec hint — and an explicit spec= unblocks it."""
    spec, p, opt = _train_lm_state()
    d = _save(tmp_path, (p, opt), extra={"opt_level": "O0"})
    with pytest.raises(ValueError, match="pass spec="):
        serve.load_model(d)
    loaded = serve.load_model(d, spec=spec)
    assert loaded.spec.vocab == 61


def test_layout_mismatch_before_materialization(tmp_path):
    """A wrong expected layout fails on the manifest alone — zero
    array bytes touched (the manifest read is the only I/O)."""
    spec, p, opt = _train_lm_state()
    d = _save(tmp_path, (p, opt), layout={"world": 8},
              extra={"opt_level": "O0", "model": MODEL_MD})
    with pytest.raises(ValueError, match="layout"):
        serve.load_model(d, layout={"world": 4})
    # matching fingerprint loads
    assert serve.load_model(d, layout={"world": 8}).step == 5


def test_rejects_unsupported_features(tmp_path):
    """Trained-in MoE is rejected at spec construction — still before
    materialization."""
    spec, p, opt = _train_lm_state()
    md = dict(MODEL_MD, moe=True)
    d = _save(tmp_path, (p, opt),
              extra={"opt_level": "O0", "model": md})
    with pytest.raises((ValueError, NotImplementedError), match="[Mm]o[Ee]"):
        serve.load_model(d)


class TestQuantization:
    def test_bf16_is_the_amp_cast(self, snap):
        d, p = snap
        loaded = serve.load_model(d, quantize="bf16")
        ref = amp.cast_model(p, amp.resolve(
            "O5", keep_batchnorm_fp32=False))
        for a, b in zip(jax.tree_util.tree_leaves(loaded.params),
                        jax.tree_util.tree_leaves(ref)):
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(np.asarray(a, np.float32),
                                          np.asarray(b, np.float32))
        assert loaded.quant.mode == "bf16"
        assert loaded.quant.quantized_leaves > 0
        assert loaded.quant.quant_bytes < loaded.quant.dense_bytes

    def test_int8_error_bound(self, snap):
        """Per-channel symmetric round-to-nearest: every kernel element
        within scale/2 of its dense value; non-kernel leaves bitwise."""
        d, p = snap
        loaded = serve.load_model(d, quantize="int8")

        def check(path, dense, got):
            keys = [getattr(k, "key", None) for k in path]
            if keys[-1] == "kernel" and dense.ndim >= 2:
                _, scale = per_channel_int8(dense)
                err = jnp.abs(dense.astype(jnp.float32)
                              - got.astype(jnp.float32))
                assert bool(jnp.all(err <= scale * 0.5 + 1e-7))
            else:
                np.testing.assert_array_equal(np.asarray(dense),
                                              np.asarray(got))

        flat_d = jax.tree_util.tree_leaves_with_path(p)
        flat_g = jax.tree_util.tree_leaves(loaded.params)
        for (path, dense), got in zip(flat_d, flat_g):
            check(path, dense, got)
        assert loaded.quant.mode == "int8"
        assert loaded.quant.max_abs_err >= 0

    def test_int8_roundtrip_primitive(self):
        w = jax.random.normal(jax.random.PRNGKey(7), (16, 8))
        q, scale = per_channel_int8(w)
        assert q.dtype == jnp.int8
        dq = dequantize_int8(q, scale, jnp.float32)
        assert bool(jnp.all(jnp.abs(w - dq) <= scale * 0.5 + 1e-7))
        # zero channel: scale 1, exact zeros
        wz = w.at[:, 3].set(0.0)
        qz, sz = per_channel_int8(wz)
        assert float(sz[3]) == 1.0
        assert bool(jnp.all(qz[:, 3] == 0))

    def test_unknown_mode_raises(self, snap):
        with pytest.raises(ValueError, match="one of"):
            quantize_params({}, "fp4")


def test_prune_for_serving_loads(snap):
    """prune=True applies one-shot 2:4 pruning: every masked kernel
    group of 4 along the last axis keeps at most 2 nonzeros; unmasked
    leaves are bitwise-untouched."""
    d, p = snap
    loaded = serve.load_model(d, prune=True)
    assert loaded.pruned is True
    changed = 0
    flat_d = jax.tree_util.tree_leaves_with_path(p)
    flat_g = jax.tree_util.tree_leaves(loaded.params)
    for (path, dense), got in zip(flat_d, flat_g):
        if np.array_equal(np.asarray(dense), np.asarray(got)):
            continue
        changed += 1
        w = np.asarray(got, np.float32).reshape(-1)
        k = (w.size // 4) * 4
        groups = (w[:k] != 0).reshape(-1, 4)
        assert (groups.sum(axis=1) <= 2).all()
    assert changed > 0
