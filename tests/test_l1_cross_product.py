"""L1 cross-product harness — the full analog of the reference's L1 tier
(tests/L1/common/{main_amp.py,run_test.sh:30-60,compare.py:36-46} plus
tests/L1/cross_product{,_distributed}/run.sh):

  * a REAL ResNet-18 (narrow filters for CI budget) trained
    deterministically, per-iteration loss dump,
  * the config matrix opt-level x keep_batchnorm_fp32 x loss-scale,
  * bitwise reproducibility between identical runs (the reference's
    ``assert loss_e == loss_p``),
  * every config's trajectory tracking the O0 fp32 baseline,
  * the same configs under x8-device DDP + SyncBN (cross_product_distributed)
    with DDP-vs-single-device consistency on the same global batch,
  * stored-baseline mode: APEX_TPU_L1_BASELINE=path dumps (if absent) or
    bitwise-compares (if present) the loss table — the --use_baseline flow.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import amp, optimizers, parallel
from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
from apex_tpu.models.resnet import ResNet18

from jax import shard_map
from jax.sharding import PartitionSpec as P

# Integration tier (PR 1): this whole module rides `-m slow` — L1 convergence cross-product matrix.
# Tier-1 (-m 'not slow') must fit the 870 s gate budget; the fast cross-
# sections of this stack stay in tier-1 via test_zero/test_parallel/
# test_param_groups/test_attention and the ci/gate.sh dryrun parts.
pytestmark = pytest.mark.slow

STEPS = 6
BATCH = 8          # global batch, split over devices in the DDP variant
NUM_CLASSES = 10

# The matrix (reference run_test.sh:30-60 sweeps O0-O3 x keep_batchnorm x
# loss-scale; we add the fork's O4/O5 bf16 levels). keep_batchnorm_fp32
# only composes with cast levels (O2/O3/O5 — policy check, as in the
# reference); static loss-scale with the fp16 scaled levels.
#
# Each cell compiles its own ResNet-18 train step (~80 s on XLA-CPU), so
# the default run covers the core subset and APEX_TPU_L1_FULL=1 unlocks
# the full cross product — the same split as the reference, whose L1 tier
# runs from run_test.sh rather than the default unit pass.
FULL = bool(os.environ.get("APEX_TPU_L1_FULL"))
full_only = pytest.mark.skipif(
    not FULL, reason="full L1 cross product: set APEX_TPU_L1_FULL=1")

MATRIX_CORE = [
    # (opt_level, keep_bn override, loss_scale override)
    ("O0", None, None),
    ("O2", None, None),
    ("O5", None, None),
]
MATRIX_FULL = [
    ("O1", None, None),
    ("O3", None, None),
    ("O4", None, None),
    ("O2", False, None),
    ("O3", True, None),
    ("O5", False, None),
    ("O1", None, 128.0),
    ("O2", None, 128.0),
]
MATRIX = MATRIX_CORE + [pytest.param(*c, marks=full_only)
                        for c in MATRIX_FULL]


def _data(seed=0):
    x = jax.random.normal(jax.random.PRNGKey(seed), (BATCH, 32, 32, 3),
                          jnp.float32)
    y = jax.random.randint(jax.random.PRNGKey(seed + 1), (BATCH,), 0,
                           NUM_CLASSES)
    return x, y


def run_config(opt_level, keep_bn=None, loss_scale=None, n_devices=1,
               steps=STEPS, seed=0):
    """Train the narrow ResNet-18 for ``steps`` and return the per-iteration
    loss list — the harness's analog of main_amp.py's loss dump.

    Matmul precision is pinned to 'highest' (the harness's --deterministic
    analog) for the run and RESTORED after — other suites' tolerances are
    tuned under the default precision and must not inherit this setting
    when the whole suite runs in one process (ci/gate.sh --full).
    """
    prev = jax.config.jax_default_matmul_precision
    jax.config.update("jax_default_matmul_precision", "highest")
    try:
        return _run_config_inner(opt_level, keep_bn, loss_scale, n_devices,
                                 steps, seed)
    finally:
        jax.config.update("jax_default_matmul_precision", prev)


def _run_config_inner(opt_level, keep_bn, loss_scale, n_devices, steps,
                      seed):
    props = amp.resolve(opt_level, keep_batchnorm_fp32=keep_bn,
                        loss_scale=loss_scale)
    mesh = parallel.make_mesh([n_devices], ("data",),
                              devices=jax.devices()[:n_devices])
    model = ResNet18(num_classes=NUM_CLASSES, num_filters=8,
                     dtype=props.cast_model_type or jnp.float32,
                     axis_name="data" if n_devices > 1 else None)

    x, y = _data(seed)
    variables = model.init(jax.random.PRNGKey(seed + 2), x[:1])
    params32, batch_stats = variables["params"], variables["batch_stats"]

    inner = optimizers.FusedSGD(lr=0.05, momentum=0.9)
    _, aopt = amp.initialize(None, inner, opt_level=opt_level,
                             keep_batchnorm_fp32=keep_bn,
                             loss_scale=loss_scale, verbosity=0)
    params = amp.cast_model(params32, props)
    opt_state = aopt.init(params)

    def per_device(params, batch_stats, opt_state, batch):
        xb, yb = batch

        def scaled(p):
            logits, upd = model.apply(
                {"params": p, "batch_stats": batch_stats}, xb, train=True,
                mutable=["batch_stats"])
            loss = jnp.mean(softmax_cross_entropy_loss(logits, yb))
            return aopt.scale_loss(loss, opt_state), (loss,
                                                      upd["batch_stats"])

        grads, (loss, new_bs) = jax.grad(scaled, has_aux=True)(params)
        # predivide by world (reference gradient_predivide_factor): summing
        # fp16 SCALED grads across devices overflows at high loss scales —
        # without this the O2 run skips 5 steps on 8 devices vs 1 on one
        # device. Total averaging is unchanged (predivide w, postdivide 1).
        grads = parallel.allreduce_gradients(
            grads, "data",
            gradient_predivide_factor=jax.lax.axis_size("data"))
        new_bs = jax.tree.map(lambda s: jax.lax.pmean(s, "data"), new_bs)
        loss = jax.lax.pmean(loss, "data")
        new_params, new_opt, _ = aopt.step(grads, params, opt_state)
        return new_params, new_bs, new_opt, loss

    rep = P()
    step_fn = jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(rep, rep, rep, (P("data"), P("data"))),
        out_specs=(rep, rep, rep, rep), check_vma=False))

    losses = []
    for _ in range(steps):
        params, batch_stats, opt_state, loss = step_fn(
            params, batch_stats, opt_state, (x, y))
        losses.append(float(loss))
    return losses


# Single runs are cached across tests (the O0 baseline etc.); the bitwise
# test bypasses the cache to genuinely run twice.
_CACHE = {}


def cached_run(*key):
    if key not in _CACHE:
        _CACHE[key] = run_config(*key)
    return _CACHE[key]


@pytest.mark.parametrize("opt_level,keep_bn,loss_scale", MATRIX)
def test_config_tracks_fp32_baseline(opt_level, keep_bn, loss_scale):
    """Every matrix config converges and its final loss tracks the O0 run
    (the reference compares every cross-product cell against baselines)."""
    base = cached_run("O0", None, None, 1)
    got = cached_run(opt_level, keep_bn, loss_scale, 1)
    assert all(np.isfinite(got)), (opt_level, got)
    assert got[-1] < got[0], f"{opt_level} did not converge: {got}"
    tol = 0.25 if opt_level in ("O2", "O3") else 0.15
    # dynamic fp16 scaling correctly skips the first step(s) while the
    # 2^16 init scale calms down (reference behavior: overflow -> skip +
    # halve), so the trajectory may lag the fp32 one by a step — compare
    # against the closest tail point.
    best = min(abs(got[-1] - b) for b in base[-2:])
    assert best < max(tol, 0.25 * base[-1]), (
        opt_level, keep_bn, loss_scale, base[-2:], got[-1])


@pytest.mark.parametrize("opt_level",
                         ["O5", pytest.param("O2", marks=full_only)])
def test_bitwise_reproducibility(opt_level):
    """compare.py:36-46: two identical runs must produce IDENTICAL losses,
    bitwise — exercised on the master-weight levels where the amp machinery
    is deepest."""
    run_e = run_config(opt_level)
    run_p = run_config(opt_level)
    assert run_e == run_p, (run_e, run_p)


@pytest.mark.parametrize(
    "opt_level,keep_bn,loss_scale",
    [("O5", None, None),
     pytest.param("O0", None, None, marks=full_only),
     pytest.param("O2", None, None, marks=full_only),
     pytest.param("O2", None, 128.0, marks=full_only)])
def test_distributed_cross_product(opt_level, keep_bn, loss_scale):
    """cross_product_distributed: the same configs under 8-device DDP +
    SyncBN. With the same GLOBAL batch, the distributed run must track the
    single-device run (SyncBN makes the BN math identical; only reduction
    order differs)."""
    single = cached_run(opt_level, keep_bn, loss_scale, 1)
    dist = cached_run(opt_level, keep_bn, loss_scale, 8)
    assert all(np.isfinite(dist))

    # Dynamic fp16 scaling may skip MORE leading steps distributed than
    # single-device: with 1 sample/device, per-SAMPLE grads at scale 2^16
    # overflow in the backward where the 8-sample mean does not — faithful
    # reference behavior (each rank skips on its own overflow), so align
    # the post-recovery trajectories instead of step indices.
    def strip_skips(tr):
        i = 0
        while i + 1 < len(tr) and tr[i + 1] == tr[0]:
            i += 1
        return tr[i:]

    s, d = strip_skips(single), strip_skips(dist)
    n = min(len(s), len(d))
    assert n >= 2, (single, dist)
    rtol = 1e-4 if opt_level in ("O0",) else 2e-2
    np.testing.assert_allclose(d[:n], s[:n], rtol=rtol, atol=1e-3,
                               err_msg=f"{opt_level} DDP vs single")


@full_only
def test_distributed_bitwise_reproducibility():
    """The DDP run itself is deterministic bitwise across executions."""
    run_e = run_config("O5", n_devices=8)
    run_p = run_config("O5", n_devices=8)
    assert run_e == run_p


BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baselines",
                             "l1_losses.json")


def _key(lvl, kb, ls):
    return f"{lvl}/kb={kb}/ls={ls}"


@pytest.mark.parametrize("opt_level,keep_bn,loss_scale", MATRIX)
def test_committed_baseline(opt_level, keep_bn, loss_scale):
    """Cross-ROUND numeric regression gate (VERDICT r3 #6): every matrix
    cell's loss trajectory must match the COMMITTED baseline table
    (tests/baselines/l1_losses.json) — the reference's --use_baseline flow
    with the baseline actually persisted (tests/L1/common/compare.py:36-46
    presumes a stored table). Regenerate after an intentional numerics
    change with APEX_TPU_L1_REGEN=1 (full matrix: also APEX_TPU_L1_FULL=1)
    and commit the diff. Tolerance is tight-but-not-bitwise: XLA-CPU
    codegen may vectorize reductions differently across hosts/versions."""
    got = cached_run(opt_level, keep_bn, loss_scale, 1)
    key = _key(opt_level, keep_bn, loss_scale)
    if os.environ.get("APEX_TPU_L1_REGEN"):
        os.makedirs(os.path.dirname(BASELINE_PATH), exist_ok=True)
        table = {}
        if os.path.exists(BASELINE_PATH):
            with open(BASELINE_PATH) as f:
                table = json.load(f)
        table[key] = got
        table["_meta"] = {"steps": STEPS, "batch": BATCH,
                          "model": "ResNet18(num_filters=8)",
                          "platform": jax.devices()[0].platform,
                          "jax": jax.__version__}
        with open(BASELINE_PATH, "w") as f:
            json.dump(table, f, indent=1, sort_keys=True)
        return
    assert os.path.exists(BASELINE_PATH), (
        f"committed baseline missing at {BASELINE_PATH}; generate with "
        "APEX_TPU_L1_FULL=1 APEX_TPU_L1_REGEN=1")
    with open(BASELINE_PATH) as f:
        stored = json.load(f)
    assert key in stored, (
        f"config {key} absent from committed baseline — regenerate with "
        "APEX_TPU_L1_REGEN=1")
    if stored.get("_meta", {}).get("jax") == jax.__version__:
        np.testing.assert_allclose(
            got, stored[key], rtol=2e-5, atol=1e-6,
            err_msg=f"{key} diverged from the committed baseline "
            f"({BASELINE_PATH}); if the numerics change is intentional, "
            "regenerate with APEX_TPU_L1_REGEN=1 and commit the diff")
        return
    # Cross-VERSION envelope: the baseline was recorded under a different
    # jax/XLA-CPU release, whose codegen vectorizes reductions differently
    # — legitimate numerics drift that compounds per training step, so
    # the per-row tolerance grows geometrically with the step index.
    # Measured on this container (baseline jax 0.9.0 vs runtime 0.4.37):
    # relative row error grows ~1e-7 (step 0) -> 3.4e-3 (step 5, O5);
    # 5e-4 * 2^i gives ~5-25x headroom per row while still catching a
    # real divergence (a skipped step shifts rows by a whole trajectory
    # point, ~25%+). Same-version runs above keep the tight gate.
    got_a, want = np.asarray(got), np.asarray(stored[key])
    rtol_rows = np.minimum(5e-4 * 2.0 ** np.arange(len(want)), 2e-2)
    bad = np.abs(got_a - want) > (1e-5 + rtol_rows * np.abs(want))
    assert not bad.any(), (
        f"{key} diverged from the committed baseline beyond the "
        f"cross-version envelope at rows {np.nonzero(bad)[0].tolist()}: "
        f"got {got}, stored {stored[key]} (baseline jax "
        f"{stored.get('_meta', {}).get('jax')}, running {jax.__version__})")


def test_stored_baseline_roundtrip(tmp_path):
    """--use_baseline flow: dump the loss table, then compare bitwise."""
    path = os.environ.get("APEX_TPU_L1_BASELINE") or str(
        tmp_path / "l1_baseline.json")
    table = {f"{lvl}/kb={kb}/ls={ls}": cached_run(lvl, kb, ls, 1)
             for lvl, kb, ls in [("O0", None, None), ("O5", None, None)]}
    if not os.path.exists(path):
        with open(path, "w") as f:
            json.dump(table, f)
    with open(path) as f:
        stored = json.load(f)
    for cfg, losses in table.items():
        assert stored[cfg] == losses, (
            f"config {cfg} diverged from the stored baseline at {path}")
