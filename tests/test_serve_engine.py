"""Continuous-batching engine tests: greedy streams vs the dense
``models.gpt.generate`` reference, in-flight-window inertness (depth
must not change tokens), admission shedding (queue_full / too_large /
deadline), eos truncation, goodput accounting, and page recycling."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt import generate
from apex_tpu.serve.admission import (AdmissionController, DEADLINE,
                                      QUEUE_FULL, TOO_LARGE)
from apex_tpu.serve.engine import Engine
from apex_tpu.serve.loader import LoadedModel
from apex_tpu.serve.model import ModelSpec

VOCAB = 61


@pytest.fixture(scope="module")
def loaded():
    spec = ModelSpec(vocab=VOCAB, layers=2, embed_dim=32, heads=4,
                     max_seq=64)
    lm = spec.model()
    params = lm.init(jax.random.PRNGKey(3),
                     jnp.zeros((1, 8), jnp.int32))["params"]
    return LoadedModel(model=lm, params=params, spec=spec, step=0,
                       generation=0, manifest={}, directory="<mem>")


def _prompts(n, length=6):
    return [[int(t) for t in np.asarray(jax.random.randint(
        jax.random.PRNGKey(i), (length,), 0, VOCAB))] for i in range(n)]


def _greedy_refs(loaded, prompts, max_new):
    refs = []
    for pr in prompts:
        out = generate(loaded.model, loaded.params,
                       jnp.asarray(pr)[None], max_new)
        refs.append([int(t) for t in np.asarray(out[0, len(pr):])])
    return refs


def test_continuous_batching_matches_generate(loaded):
    """6 requests through 2 slots (forced retire/admit churn) produce
    exactly the greedy streams of the dense-cache generate()."""
    prompts = _prompts(6)
    refs = _greedy_refs(loaded, prompts, 5)
    eng = Engine(loaded, max_batch=2, page=8, max_context=16,
                 max_prompt=8, in_flight=2)
    reqs = [eng.request(pr, 5) for pr in prompts]
    eng.run(reqs)
    for r, ref in zip(reqs, refs):
        assert r.state == "done"
        assert r.tokens == ref, f"rid {r.rid}: {r.tokens} != {ref}"
        assert r.ttft_s is not None and r.ttft_s >= 0
    # all pages recycled, ledger consistent
    assert eng.allocator.free_pages == eng.num_pages
    assert len(eng.completed) == 6
    assert eng.tokens_emitted == 6 * 5


@pytest.mark.parametrize("depths", [(1, 2), (1, 4)])
def test_inflight_depth_is_inert(loaded, depths):
    """The InflightWindow depth is a dispatch-pipelining knob: token
    streams at depth 1/2/4 must be identical (the scheduler never
    branches on retirement timing)."""
    prompts = _prompts(5)
    streams = {}
    for depth in depths:
        eng = Engine(loaded, max_batch=2, page=8, max_context=16,
                     max_prompt=8, in_flight=depth)
        reqs = [eng.request(p, 4) for p in prompts]
        eng.run(reqs)
        assert all(r.state == "done" for r in reqs)
        streams[depth] = [tuple(r.tokens) for r in reqs]
    a, b = depths
    assert streams[a] == streams[b]


def test_queue_full_shedding(loaded):
    """Bounded queue: submissions past max_queue shed with queue_full
    BEFORE any decode work happens; the ledger counts every request
    exactly once."""
    adm = AdmissionController(max_queue=2)
    eng = Engine(loaded, max_batch=1, page=8, max_context=16,
                 max_prompt=8, in_flight=1, admission=adm)
    reqs = [eng.request(p, 3) for p in _prompts(6)]
    eng.run(reqs)
    done = [r for r in reqs if r.state == "done"]
    shed = [r for r in reqs if r.state == "rejected"]
    assert len(done) == 2 and len(shed) == 4
    assert all(r.reject_reason == QUEUE_FULL for r in shed)
    assert adm.submitted == 6
    assert {rej.rid for rej in adm.rejected} == {r.rid for r in shed}


def test_too_large_shedding(loaded):
    """Oversized requests (prompt past the static prefill width, or
    prompt+max_new past the context budget) shed at submit."""
    eng = Engine(loaded, max_batch=1, page=8, max_context=16,
                 max_prompt=8, in_flight=1)
    long_prompt = eng.request(list(range(9)), 2)      # prompt > 8
    long_gen = eng.request(list(range(4)), 13)        # 4+13 > 16
    ok = eng.request(list(range(4)), 3)
    eng.run([long_prompt, long_gen, ok])
    assert long_prompt.state == "rejected"
    assert long_prompt.reject_reason == TOO_LARGE
    assert long_gen.state == "rejected"
    assert long_gen.reject_reason == TOO_LARGE
    assert ok.state == "done" and len(ok.tokens) == 3


def test_deadline_shedding_and_goodput(loaded):
    """A fake clock where decode takes 1s/step: requests with a 0.5s
    deadline shed (screened at submit once TTFT is observed, expired at
    pop otherwise); in_deadline() partitions honestly."""
    t = itertools.count()
    clock = lambda: float(next(t))                      # noqa: E731
    adm = AdmissionController(max_queue=16, clock=clock)
    eng = Engine(loaded, max_batch=1, page=8, max_context=16,
                 max_prompt=8, in_flight=1, admission=adm, clock=clock)
    relaxed = eng.request(_prompts(1)[0], 2, deadline_s=1e6)
    tight = eng.request(_prompts(2)[1], 2, deadline_s=0.5)
    eng.run([relaxed, tight])
    assert relaxed.state == "done" and relaxed.in_deadline() is True
    assert tight.state == "rejected"
    assert tight.reject_reason == DEADLINE
    assert tight.in_deadline() is False
    # no-deadline requests report None (excluded from SLO accounting)
    free = eng.request(_prompts(3)[2], 1)
    assert free.in_deadline() is None


def test_eos_truncation(loaded):
    """Generation stops at eos_token_id even with budget left; the
    request still completes and its pages recycle."""
    pr = _prompts(1)[0]
    ref = _greedy_refs(loaded, [pr], 8)[0]
    eos = ref[2]                       # stop at the 3rd greedy token
    eng = Engine(loaded, max_batch=1, page=8, max_context=32,
                 max_prompt=8, in_flight=2)
    req = eng.request(pr, 8, eos_token_id=eos)
    eng.run([req])
    assert req.state == "done"
    assert req.tokens == ref[:3]       # eos included, then stop
    assert eng.allocator.free_pages == eng.num_pages


def test_engine_validates_geometry(loaded):
    with pytest.raises(ValueError, match="max_prompt"):
        Engine(loaded, max_prompt=32, max_context=16)
    with pytest.raises(ValueError, match="position table"):
        Engine(loaded, max_context=128, max_prompt=8)  # max_seq=64
