"""Committed APX201 deadlock fixture — the canonical SPMD
collective-schedule divergence, pinned by both tests/test_lint_spmd.py
and ci/gate.sh's spmd-verifier stage.

``bad_entry`` gates a ``psum`` on ``axis_index``: rank 0 enters the
collective, every other rank takes the identity branch, and on real
multi-host hardware the fleet deadlocks waiting for rank 0's partners.
``good_entry`` is the corrected twin: the collective runs unconditionally
on every rank and only the *use* of its result is rank-gated (data flow,
not control flow — ``jnp.where`` is schedule-safe).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

import apex_tpu._compat  # noqa: F401  (jax.shard_map on older jax)


def _mesh():
    return Mesh(np.asarray(jax.devices()[:1]), ("data",))


def _smap(fn):
    return jax.shard_map(fn, mesh=_mesh(), in_specs=(P("data"),),
                         out_specs=P(), check_vma=False)


def bad_entry():
    """(fn, args) whose psum is reachable only on rank 0 — APX201."""

    def rank_gated(x):
        i = jax.lax.axis_index("data")
        return jax.lax.cond(
            i == 0,
            lambda v: jax.lax.psum(v, "data"),
            lambda v: v,
            x)

    return _smap(rank_gated), (jnp.ones((4, 4)),)


def good_entry():
    """Corrected twin: every rank executes the same collective schedule."""

    def uniform_schedule(x):
        total = jax.lax.psum(x, "data")
        i = jax.lax.axis_index("data")
        return jnp.where(i == 0, total, x)

    return _smap(uniform_schedule), (jnp.ones((4, 4)),)
