"""Heterogeneity-aware rebalancing tests: the weighted ZeRO shard
assignment (bitwise gather-compare round-trips on REAL trained state —
60/40 two-member, 3-member uneven, weighted→equal and back,
chunk-boundary-straddling fractions), the layout-fingerprint restore
guard for weighted specs, the degradation supervisor's policy ladder
(hysteresis: a single slow step never triggers; a sustained straggler
triggers exactly once per cooldown; escalation to the cooperative
eviction), the planner's heterogeneous cost term + acting replanner,
the rendezvous profile channel, the inspect CLI weighted rendering,
and the off-switch pins (equal fingerprints byte-identical, supervisor
construction traces nothing)."""

import json
import os
import time
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import parallel, resilience, telemetry
from apex_tpu.contrib.optimizers import DistributedFusedAdam
from apex_tpu.parallel import multiproc
from apex_tpu.plan import cost as plan_cost
from apex_tpu.resilience import elastic, rebalance


def tree_params(key=None):
    ks = jax.random.split(key or jax.random.PRNGKey(3), 3)
    # sizes deliberately NOT divisible by any world size in play, so
    # every bucket carries world-dependent padding
    return {"w1": jax.random.normal(ks[0], (37, 11)),
            "w2": jax.random.normal(ks[1], (501,)),
            "b": jax.random.normal(ks[2], (3,))}


def train_zero(world, params, *, steps=3, chunk=256):
    """Real ZeRO training at ``world``; returns (opt, final ZeroState)
    with genuinely nonzero fp32 masters and both Adam moments."""
    mesh = parallel.reform_mesh(world)
    opt = DistributedFusedAdam(lr=0.05, shard_count=world,
                               chunk_elements=chunk)
    state = opt.init(params)
    specs = opt.state_pspec()
    step = jax.jit(shard_map(
        opt.step, mesh=mesh, in_specs=(P(), P(), specs),
        out_specs=(P(), specs), check_vma=False))
    state = jax.device_put(state, jax.tree_util.tree_map(
        lambda sp: NamedSharding(mesh, sp), specs))
    p = params
    for i in range(steps):
        ks = jax.random.split(jax.random.PRNGKey(100 + i), len(params))
        grads = {name: jax.random.normal(k, v.shape, jnp.float32)
                 for k, (name, v) in zip(ks, sorted(params.items()))}
        p, state = step(grads, p, state)
    return opt, state


# ---------------------------------------------------------------------------
# weight grammar + apportionment
# ---------------------------------------------------------------------------

def test_parse_and_normalize_weights():
    assert elastic.parse_weights("3:1") == [3, 1]
    assert elastic.parse_weights("60,40") == [60, 40]
    assert elastic.normalize_weights([60, 40]) == [3, 2]
    assert elastic.normalize_weights([6, 2]) == [3, 1]
    # equal vectors canonicalize to None — the ABSENT-key fingerprint
    assert elastic.normalize_weights([1, 1]) is None
    assert elastic.normalize_weights([4, 4, 4]) is None
    with pytest.raises(ValueError, match="positive integers"):
        elastic.parse_weights("3:x")
    with pytest.raises(ValueError, match="eviction"):
        elastic.normalize_weights([3, 0])
    with pytest.raises(ValueError, match="2 entries for world 3"):
        elastic.normalize_weights([3, 1], 3)


def test_apportion_exact_and_deterministic():
    for total in (0, 1, 7, 256, 911):
        for ws in ([1, 1], [3, 1], [5, 2, 1], [8, 1, 1, 1]):
            parts = elastic.apportion(total, ws)
            assert sum(parts) == total
            assert parts == elastic.apportion(total, ws)
            # within 1 of the real-valued share
            s = sum(ws)
            for p, w in zip(parts, ws):
                assert abs(p - total * w / s) < 1.0 + 1e-9


def test_weighted_fingerprint_equal_is_byte_identical():
    """The off-switch pin: no weights -> the fingerprint has NO weights
    key and equals the pre-rebalance form exactly."""
    params = tree_params()
    fp = DistributedFusedAdam(
        shard_count=2, chunk_elements=256).layout_fingerprint(params)
    assert set(fp) == {"chunk_elements", "shard_count", "total",
                       "padded", "n_buckets", "structure_crc32"}
    assert elastic.weighted_fingerprint(fp, None) == fp
    assert elastic.weighted_fingerprint(fp, [2, 2]) == fp
    wfp = elastic.weighted_fingerprint(fp, [3, 1])
    assert wfp["weights"] == [3, 1]
    assert {k: v for k, v in wfp.items() if k != "weights"} == fp
    # weighted weighting is idempotent and re-weightable
    assert elastic.weighted_fingerprint(wfp, None) == fp
    assert elastic.weighted_fingerprint(wfp, [1, 3])["weights"] == [1, 3]


# ---------------------------------------------------------------------------
# the acceptance pin: weighted bitwise gather round-trips on real state
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("world,weights", [
    (2, [3, 2]),          # the 60/40 two-member split
    (3, [5, 2, 1]),       # 3-member uneven
    (2, [8, 1]),          # extreme skew: small buckets apportion to 0
])
def test_weighted_reshard_gather_bitwise(world, weights):
    params = tree_params()
    opt, state = train_zero(world, params)
    fp = opt.layout_fingerprint(params)
    wfp = elastic.weighted_fingerprint(fp, weights)
    eq_spec = elastic.spec_for(params, fp)
    w_spec = elastic.spec_for(params, wfp)
    out = elastic.reshard_state(state, eq_spec, w_spec)
    assert out.master.shape == (fp["padded"],)   # padded UNCHANGED
    for field in ("master", "exp_avg", "exp_avg_sq"):
        a = elastic.unshard(np.asarray(getattr(state, field)), eq_spec)
        b = elastic.unshard(np.asarray(getattr(out, field)), w_spec)
        np.testing.assert_array_equal(a, b, err_msg=field)
        assert np.any(a != 0), f"{field} trivially zero"
    # ...and back: weighted -> equal recovers the canonical form
    back = elastic.reshard_state(out, w_spec, eq_spec)
    np.testing.assert_array_equal(
        elastic.unshard(np.asarray(state.master), eq_spec),
        elastic.unshard(np.asarray(back.master), eq_spec))


def test_weighted_to_weighted_and_world_change_bitwise():
    """weighted(W) -> weighted(W') crossing a world-size change stays a
    pure permutation."""
    params = tree_params()
    opt, state = train_zero(2, params)
    fp2 = opt.layout_fingerprint(params)
    w2 = elastic.spec_for(params, elastic.weighted_fingerprint(
        fp2, [3, 1]))
    fp3 = DistributedFusedAdam(
        shard_count=3, chunk_elements=256).layout_fingerprint(params)
    w3 = elastic.spec_for(params, elastic.weighted_fingerprint(
        fp3, [1, 2, 4]))
    eq2 = elastic.spec_for(params, fp2)
    mid = elastic.reshard_state(state, eq2, w2)
    out = elastic.reshard_state(mid, w2, w3)
    np.testing.assert_array_equal(
        elastic.unshard(np.asarray(state.master), eq2),
        elastic.unshard(np.asarray(out.master), w3))


def test_weighted_boundaries_straddle_chunks():
    """The weighted member boundary lands MID-bucket (never on the
    equal k boundary) for at least one bucket, and a skewed vector
    apportions a small bucket's extent entirely to the heavy member —
    the round trip stays exact through both."""
    params = tree_params()
    fp = DistributedFusedAdam(
        shard_count=2, chunk_elements=256).layout_fingerprint(params)
    spec = elastic.spec_for(
        params, elastic.weighted_fingerprint(fp, [8, 1]))
    ks = [elastic._spec_ks(spec, b) for b in spec["buckets"]]
    eq = [b["k"] for b in spec["buckets"]]
    assert any(k[0] != e for k, e in zip(ks, eq)), (ks, eq)
    assert any(0 in k for k in ks), \
        f"expected an all-to-one bucket under 8:1, got {ks}"
    flat = np.arange(spec["padded"], dtype=np.float64) + 1
    eq_spec = elastic.spec_for(params, fp)
    out = elastic.reshard_flat(flat, eq_spec, spec)   # verify=True
    np.testing.assert_array_equal(
        elastic.unshard(out, spec), elastic.unshard(flat, eq_spec))


def test_member_span_shrinks_for_light_member():
    params = tree_params()
    fp = DistributedFusedAdam(
        shard_count=2, chunk_elements=256).layout_fingerprint(params)
    eq = elastic.spec_for(params, fp)
    ws = elastic.spec_for(params, elastic.weighted_fingerprint(
        fp, [3, 1]))
    eq_lens = elastic.member_lengths(eq)
    w_lens = elastic.member_lengths(ws)
    assert sum(w_lens) == sum(eq_lens) == fp["padded"]
    assert w_lens[1] < eq_lens[1] < w_lens[0]
    s0, s1 = elastic.member_span(ws, 0), elastic.member_span(ws, 1)
    assert s0 == (0, w_lens[0]) and s1 == (w_lens[0], fp["padded"])
    with pytest.raises(ValueError, match="outside world"):
        elastic.member_span(ws, 2)


def test_weighted_classification_and_json_roundtrip():
    params = tree_params()
    fp = DistributedFusedAdam(
        shard_count=2, chunk_elements=256).layout_fingerprint(params)
    wfp = elastic.weighted_fingerprint(fp, [3, 1])
    kind, reason = elastic.classify_reshard(wfp, fp)
    assert kind == elastic.RESHARDABLE and "weights 3:1" in reason
    assert elastic.classify_reshard(fp, wfp)[0] == elastic.RESHARDABLE
    assert elastic.classify_reshard(wfp, dict(wfp))[0] \
        == elastic.IDENTICAL
    # the manifest stores JSON: the fingerprint must survive the trip
    back = json.loads(json.dumps(wfp))
    assert back == wfp
    assert elastic.spec_for(params, back)["weights"] == [3, 1]
    # non-canonical weights are refused loudly, never silently re-read
    with pytest.raises(ValueError, match="not canonical"):
        elastic.spec_for(params, dict(fp, weights=[6, 2]))


def test_check_world_weights_feasibility():
    params = tree_params()
    fp = DistributedFusedAdam(
        shard_count=2, chunk_elements=256).layout_fingerprint(params)
    ok, reason = elastic.check_world(fp, 2, weights=[3, 1])
    assert ok and "weights 3:1" in reason
    ok, reason = elastic.check_world(fp, 3, weights=[3, 1])
    assert not ok and "infeasible weight vector" in reason
    ok, reason = elastic.check_world(fp, 2, weights=[3, 0])
    assert not ok and "infeasible" in reason
    # equal-weight ask degrades to the plain form
    assert elastic.check_world(fp, 2, weights=[2, 2])[0]


def test_weighted_restore_guard_fails_fast_without_elastic(tmp_path):
    """The restore-guard satellite of the tentpole: a WEIGHTED snapshot
    restored by an equal-shard optimizer must fail fast naming the
    re-shard recipe — before this PR, a saved-only fingerprint key was
    invisible to layout_mismatch and the state loaded scrambled."""
    params = tree_params()
    opt, state = train_zero(2, params)
    fp = opt.layout_fingerprint(params)
    wfp = elastic.weighted_fingerprint(fp, [3, 1])
    assert opt.layout_mismatch(wfp, params) == {"weights": ([3, 1],
                                                            None)}
    wstate = elastic.reshard_state(
        state, elastic.spec_for(params, fp),
        elastic.spec_for(params, wfp))
    mgr = resilience.SnapshotManager(str(tmp_path))
    mgr.save((params, wstate), step=2, layout=wfp)
    with pytest.raises(ValueError) as ei:
        mgr.restore_latest((params, opt.init(params)), layout=fp)
    assert "RE-SHARDABLE" in str(ei.value)
    # ...and through the elastic seam it restores bitwise
    found = elastic.reshard_restore(
        mgr, (params, opt.init(params)), params=params, optimizer=opt)
    assert found is not None
    np.testing.assert_array_equal(
        elastic.unshard(np.asarray(state.master),
                        elastic.spec_for(params, fp)),
        elastic.unshard(np.asarray(found.state[1].master),
                        elastic.spec_for(params, fp)))


# ---------------------------------------------------------------------------
# rendezvous profile channel
# ---------------------------------------------------------------------------

def test_rendezvous_profiles_roundtrip(tmp_path):
    a = multiproc.Rendezvous(str(tmp_path / "r"), "0000")
    b = multiproc.Rendezvous(str(tmp_path / "r"), "0001")
    a.announce()
    b.announce(profile={"rank": 1, "step_s": 0.25, "steps": 4})
    assert a.profiles() == {
        "0000": {}, "0001": {"rank": 1, "step_s": 0.25, "steps": 4}}
    # heartbeat with a profile republishes; without one it sticks
    a.heartbeat(profile={"rank": 0, "step_s": 0.05, "steps": 9})
    a.heartbeat()
    assert a.profiles()["0000"]["step_s"] == 0.05
    # departed members drop out of the profile view
    b.leave()
    assert "0001" not in a.profiles()


# ---------------------------------------------------------------------------
# the degradation supervisor ladder
# ---------------------------------------------------------------------------

def _fleet(tmp_path, peer_step_s=0.01, peer_steps=50):
    """A 2-member registry where the PEER has a published profile and
    we supervise the 'self' member."""
    rdzv = multiproc.Rendezvous(str(tmp_path / "rdzv"), "0000")
    rdzv.announce()
    peer = multiproc.Rendezvous(str(tmp_path / "rdzv"), "0001")
    peer.announce(profile={"rank": 1, "step_s": peer_step_s,
                           "steps": peer_steps})
    return rdzv, peer


def test_single_slow_step_never_triggers(tmp_path):
    """THE hysteresis pin: one slow step among fast ones moves neither
    the rolling median nor the consecutive counter far enough — no
    decision, no detect event, ever."""
    rdzv, _ = _fleet(tmp_path)
    sup = rebalance.DegradationSupervisor(
        rdzv, rank=0, window=5, threshold=1.5, hysteresis=3,
        cooldown=4, evict_after=4, min_steps=2)
    with telemetry.capture() as col:
        kinds = []
        for i in range(30):
            dt = 0.5 if i == 10 else 0.01   # ONE slow step
            kinds.append(sup.observe(i, step_s=dt).kind)
        events = [e.name for e in col.drain()]
    assert set(kinds) == {"none"}, kinds
    assert not [n for n in events if n.startswith("rebalance/")]


def test_sustained_straggler_triggers_once_per_cooldown(tmp_path):
    """A sustained straggler triggers a rebalance exactly once per
    cooldown window, names itself in ONE detect event per episode, and
    (with a high evict floor) never escalates."""
    rdzv, _ = _fleet(tmp_path)
    sup = rebalance.DegradationSupervisor(
        rdzv, rank=0, window=3, threshold=1.5, hysteresis=2,
        cooldown=5, evict_after=1000, min_steps=2)
    with telemetry.capture() as col:
        decisions = []
        for i in range(26):
            d = sup.observe(i, step_s=0.5)   # sustained: every step slow
            decisions.append(d)
        events = [e for e in col.drain()
                  if e.name == "rebalance/detect"]
    reb = [i for i, d in enumerate(decisions) if d.kind == "rebalance"]
    assert reb, "sustained straggler never triggered"
    diffs = [b - a for a, b in zip(reb, reb[1:])]
    assert all(d == 5 for d in diffs), (reb, diffs)
    assert len(events) == 1                      # one episode, one name
    assert events[0].meta["straggler"] == "0000"
    assert events[0].meta["straggler_rank"] == 0
    d = decisions[reb[0]]
    assert d.weights is not None and len(set(d.weights)) > 1
    assert not any(x.kind == "evict" for x in decisions)


def test_recovery_resets_the_episode(tmp_path):
    rdzv, _ = _fleet(tmp_path)
    sup = rebalance.DegradationSupervisor(
        rdzv, rank=0, window=3, threshold=1.5, hysteresis=2,
        cooldown=3, evict_after=1000, min_steps=2)
    with telemetry.capture() as col:
        ks = [sup.observe(i, step_s=0.5).kind for i in range(6)]
        assert "rebalance" in ks
        # recovery: fast steps flush the window, the episode ends
        ks = [sup.observe(6 + i, step_s=0.01).kind for i in range(8)]
        assert set(ks) == {"none"}
        # a NEW sustained episode detects (and names) again
        ks = [sup.observe(20 + i, step_s=0.5).kind for i in range(6)]
        assert "rebalance" in ks
        detects = [e for e in col.drain()
                   if e.name == "rebalance/detect"]
    assert len(detects) == 2


def test_escalation_to_evict_me(tmp_path):
    rdzv, _ = _fleet(tmp_path)
    sup = rebalance.DegradationSupervisor(
        rdzv, rank=0, window=3, threshold=1.5, hysteresis=2,
        cooldown=10, evict_after=3, min_steps=2)
    with telemetry.capture() as col:
        kinds = [sup.observe(i, step_s=0.5).kind for i in range(12)]
        events = [e.name for e in col.drain()]
    assert "rebalance" in kinds and "evict" in kinds
    assert kinds.index("evict") > kinds.index("rebalance")
    evict = [d for d in [sup.last_decision] if d is not None]
    # after eviction the supervisor goes quiet
    assert kinds[kinds.index("evict") + 1:] == ["none"] * (
        len(kinds) - kinds.index("evict") - 1)
    assert "rebalance/evict" in events
    # the straggler is THIS member: the decision says evict ME
    assert any(k == "evict" for k in kinds)


def test_evict_decision_targets_only_the_straggler(tmp_path):
    """The fast member sees the same evict verdict but with
    evict_me=False — eviction is a cooperative SELF-leave."""
    rdzv, peer = _fleet(tmp_path, peer_step_s=0.6)   # PEER is slow
    sup = rebalance.DegradationSupervisor(
        rdzv, rank=0, window=3, threshold=1.5, hysteresis=2,
        cooldown=10, evict_after=2, min_steps=2)
    evicts = []
    for i in range(12):
        d = sup.observe(i, step_s=0.01)
        if d.kind == "evict":
            evicts.append(d)
    assert evicts and all(not d.evict_me for d in evicts)
    assert evicts[0].straggler == "0001"
    assert evicts[0].straggler_rank == 1


def test_supervisor_validation():
    with pytest.raises(ValueError, match="threshold"):
        rebalance.DegradationSupervisor(None, threshold=0.9)
    with pytest.raises(ValueError, match=">= 1"):
        rebalance.DegradationSupervisor(None, window=0)
    with pytest.raises(ValueError, match=">= 1"):
        rebalance.DegradationSupervisor(None, io_every=0)


def test_supervisor_io_every_throttles_registry_traffic(tmp_path):
    """io_every=N touches the rendezvous (publish + fleet read) only
    every Nth step: quiet steps decide nothing and leave the published
    profile untouched; detection still happens, just up to N steps
    later."""
    rdzv, _ = _fleet(tmp_path)
    sup = rebalance.DegradationSupervisor(
        rdzv, rank=0, window=3, threshold=1.5, hysteresis=2,
        cooldown=100, evict_after=1000, min_steps=2, io_every=3)
    published = []
    decisions = []
    for i in range(12):
        decisions.append(sup.observe(i, step_s=0.5).kind)
        prof = rdzv.profiles().get("0000") or {}
        published.append(prof.get("steps"))
    # quiet steps (observed count not a multiple of 3) decide nothing
    # and publish nothing
    for i, (kind, steps) in enumerate(zip(decisions, published), 1):
        if i % 3:
            assert kind == "none", (i, kind)
    assert sorted(set(p for p in published if p is not None)) \
        == [3, 6, 9, 12]
    assert "rebalance" in decisions   # detection still lands


def test_weights_from_rates_quantized_and_stable():
    w = rebalance.weights_from_rates({"a": 25.0, "b": 3.2})
    assert w == [8, 1]
    # near-equal rates quantize to EQUAL (None): jitter never produces
    # a gratuitous weighted layout
    assert rebalance.weights_from_rates({"a": 10.0, "b": 9.6}) is None
    assert rebalance.weights_from_rates({}) is None
    # member order is dense sorted id order (= rank order)
    w = rebalance.weights_from_rates({"0001": 24.0, "0000": 3.0})
    assert w == [1, 8]


# ---------------------------------------------------------------------------
# the rebalance action + loop integration
# ---------------------------------------------------------------------------

def test_apply_rebalance_persists_weighted_generation(tmp_path):
    params = tree_params()
    opt, state = train_zero(2, params)
    fp = opt.layout_fingerprint(params)
    mgr = resilience.SnapshotManager(str(tmp_path))
    seam = resilience.Elastic(opt, params)
    with telemetry.capture() as col:
        info = rebalance.apply_rebalance(
            mgr, seam, (params, state), step=4,
            rates={"0000": 25.0, "0001": 3.2},
            straggler="0001", straggler_rank=1,
            loader={"offset": 7})
        events = [e for e in col.drain() if e.name == "rebalance/apply"]
    assert info["saved"] and info["verified"]
    assert info["weights"] == [8, 1] and not info["planned"]
    assert events[0].meta["weights"] == [8, 1]
    man = mgr.latest_manifest()
    assert man["layout"]["weights"] == [8, 1]
    assert man["extra"]["rebalance"]["straggler_rank"] == 1
    # the weighted generation is the NEWEST restore source: it must
    # carry the data-loader offset exactly like the loop's cadence
    # saves, or a stateful loader would silently replay consumed data
    assert man["loader"] == {"offset": 7}
    # the slow member's span SHRANK
    spans = info["member_spans"]
    assert spans[1][1] - spans[1][0] < fp["padded"] // 2
    # the weighted generation restores bitwise at the equal layout
    found = elastic.reshard_restore(
        mgr, (params, opt.init(params)), params=params, optimizer=opt)
    np.testing.assert_array_equal(
        elastic.unshard(np.asarray(state.master),
                        elastic.spec_for(params, fp)),
        elastic.unshard(np.asarray(found.state[1].master),
                        elastic.spec_for(params, fp)))


def test_apply_rebalance_prefers_planner_weights(tmp_path):
    """The acting-replan carry: when the Elastic has a replan hook that
    produces a weight vector (the heterogeneous cost term), THAT vector
    goes into the re-shard — not the rate-proportional fallback."""
    params = tree_params()
    opt, state = train_zero(2, params)

    def hook(old_world, new_world, rates=None):
        return {"old": "x", "new": "x", "old_step_s": 1.0,
                "new_step_s": 1.0, "weights": [3, 1],
                "equal_shard": False}

    mgr = resilience.SnapshotManager(str(tmp_path))
    seam = resilience.Elastic(opt, params, replan=hook)
    info = rebalance.apply_rebalance(
        mgr, seam, (params, state), step=2,
        rates={"0000": 25.0, "0001": 3.2})
    assert info["planned"] and info["weights"] == [3, 1]
    assert mgr.latest_manifest()["layout"]["weights"] == [3, 1]


def test_apply_rebalance_degrades_dont_crash(tmp_path):
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert rebalance.apply_rebalance(None, None, {}, step=0) is None
        # equal weights: nothing to apply
        params = tree_params()
        opt, state = train_zero(2, params)
        mgr = resilience.SnapshotManager(str(tmp_path))
        seam = resilience.Elastic(opt, params)
        assert rebalance.apply_rebalance(
            mgr, seam, (params, state), step=0,
            weights=[1, 1]) is None
    assert any("nothing to apply" in str(x.message) for x in w)


def test_resilient_loop_supervisor_rebalances_and_continues(tmp_path):
    """Loop integration, straggler is the PEER: the supervisor applies
    the weighted re-shard mid-run (weighted generation in the store,
    rebalance/apply emitted), the evict verdict targets the peer, and
    THIS member runs to completion."""
    params = tree_params()
    world = 2
    mesh = parallel.reform_mesh(world)
    opt = DistributedFusedAdam(lr=0.05, shard_count=world,
                               chunk_elements=256)
    specs = opt.state_pspec()
    sharded = shard_map(opt.step, mesh=mesh, in_specs=(P(), P(), specs),
                        out_specs=(P(), specs), check_vma=False)

    @jax.jit
    def train(st, x):
        p, z = st
        loss, g = jax.value_and_grad(
            lambda p: sum(jnp.mean((l * x - 0.5) ** 2) for l in
                          jax.tree_util.tree_leaves(p)))(p)
        new_p, new_z = sharded(g, p, z)
        return (new_p, new_z), loss

    rdzv, peer = _fleet(tmp_path, peer_step_s=5.0)   # peer VERY slow
    sup = rebalance.DegradationSupervisor(
        rdzv, rank=0, window=3, threshold=1.5, hysteresis=2,
        cooldown=100, evict_after=3, min_steps=2)
    with telemetry.capture() as col:
        result = resilience.resilient_loop(
            lambda st, x, i: train(st, x),
            (params, opt.init(params)),
            lambda i: jnp.float32(1.0), steps=12,
            snapshot_dir=str(tmp_path / "snap"), snapshot_every=4,
            layout=opt.layout_fingerprint(params),
            elastic=resilience.Elastic(opt, params),
            supervisor=sup, handle_signals=False, keep_last=50)
        names = [e.name for e in col.drain()]
    assert result.step == 12 and not result.preempted
    assert "rebalance/detect" in names
    assert "rebalance/apply" in names
    assert "rebalance/evict" in names   # verdict recorded, peer's to act
    mgr = resilience.SnapshotManager(str(tmp_path / "snap"))
    weighted = [g for g in mgr.generations()
                if (mgr.manifest(g).get("layout") or {}).get("weights")]
    assert weighted, "no weighted generation persisted"


def test_resilient_loop_supervisor_self_evicts_exit_75(tmp_path):
    """Loop integration, straggler is SELF: the ladder escalates to the
    cooperative self-eviction — preempted, final snapshot, exit 75 (the
    W-1 relaunch contract the multiproc supervisor consumes)."""
    params = tree_params()
    opt = DistributedFusedAdam(lr=0.05, shard_count=1,
                               chunk_elements=256)

    def slow_step(st, x, i):
        time.sleep(0.03)
        return st, 0.0

    rdzv, peer = _fleet(tmp_path, peer_step_s=0.001)   # peer is fast
    sup = rebalance.DegradationSupervisor(
        rdzv, rank=0, window=3, threshold=1.5, hysteresis=2,
        cooldown=3, evict_after=2, min_steps=2)
    result = resilience.resilient_loop(
        slow_step, (params, opt.init(params)),
        lambda i: None, steps=100,
        snapshot_dir=str(tmp_path / "snap"), snapshot_every=10,
        layout=opt.layout_fingerprint(params),
        elastic=resilience.Elastic(opt, params),
        supervisor=sup, handle_signals=False)
    assert result.preempted and result.exit_code == 75
    assert result.reason and result.reason.startswith("evict:")
    assert result.step < 100
    assert result.final_snapshot_ok


# ---------------------------------------------------------------------------
# planner: heterogeneous cost term + acting replanner
# ---------------------------------------------------------------------------

def _toy_cost(exposed=0.004, roofline=0.01):
    return plan_cost.CostBreakdown(
        layout_id="dp2", compute_s=roofline, memory_s=0.0,
        roofline_s=roofline, wire=[], wire_bytes=0.0,
        comm_s=exposed, hidden_s=0.0, exposed_comm_s=exposed,
        bubble_s=0.0, latency_s=0.0, step_s=roofline + exposed,
        hbm={"total": 0.0})


def test_heterogeneous_step_homogeneous_reduces_exactly():
    c = _toy_cost()
    h = plan_cost.heterogeneous_step_s(c, [1.0, 1.0])
    assert h.step_s == pytest.approx(c.step_s, abs=1e-15)
    assert h.weights is None


def test_heterogeneous_step_max_over_members_and_weights_help():
    c = _toy_cost(exposed=0.004, roofline=0.01)
    speeds = [1.0, 0.5]                      # member 1 at half speed
    equal = plan_cost.heterogeneous_step_s(c, speeds)
    # the slow member dominates: fixed/0.5 + equal shard term
    assert equal.step_s == pytest.approx(0.01 / 0.5 + 0.004)
    weighted = plan_cost.heterogeneous_step_s(
        c, speeds, weights=plan_cost.optimal_weights(speeds))
    assert weighted.step_s < equal.step_s
    assert weighted.weights == [2, 1]
    # per-member bills: the light member's shard term shrank
    assert weighted.per_member_s[1] < equal.per_member_s[1]


def test_member_speeds_and_optimal_weights():
    s = plan_cost.member_speeds({"b": 10.0, "a": 20.0})
    assert s == [1.0, 0.5]                   # dense member order, a first
    assert plan_cost.optimal_weights([1.0, 1.0]) is None
    assert plan_cost.optimal_weights([1.0, 0.25]) == [4, 1]
    with pytest.raises(ValueError):
        plan_cost.member_speeds({"a": -1.0})


def test_replanner_emits_weights_and_elastic_carries_them():
    from apex_tpu import plan
    from apex_tpu.plan.adapters import GPTAdapter
    ad = GPTAdapter(vocab=128, layers=2, embed=64, heads=4, batch=16,
                    seq=64)
    hook = plan.replanner(ad)
    rates = {"0000": 25.0, "0001": 3.4}
    out = hook(2, 2, rates=rates)
    assert out["weights"] == [8, 1]
    assert out["hetero_step_s"] <= out["equal_step_s"]
    assert out["equal_shard"] is False
    # stale/partial rates stay equal-shard, loudly annotated
    out = hook(2, 1, rates=rates)
    assert "weights" not in out and out["weights_skipped"]
    # no rates: the PR 14 equal-shard re-rank, field-compatible
    out = hook(2, 1)
    assert out["equal_shard"] is True and "weights" not in out

    class FakeOpt:
        def layout_fingerprint(self, p):
            return {"shard_count": 2, "chunk_elements": 256,
                    "total": 911, "padded": 914, "n_buckets": 3,
                    "structure_crc32": 1}

    seam = resilience.Elastic(FakeOpt(), {}, replan=hook)
    assert seam.planned_weights(rates) == [8, 1]


def test_replan_failure_emits_telemetry_static():
    """The satellite: a failing replan hook warns AND lands a
    plan/replan_failed counter, so summarize can surface it."""
    class FakeOpt:
        def layout_fingerprint(self, p):
            return {"shard_count": 2, "chunk_elements": 256,
                    "total": 911, "padded": 914, "n_buckets": 3,
                    "structure_crc32": 1}

    def bad(a, b):
        raise RuntimeError("boom")

    seam = resilience.Elastic(FakeOpt(), {}, replan=bad)
    with telemetry.capture() as col:
        with pytest.warns(UserWarning, match="replan hook failed"):
            seam._replan(2, 1, step=4)
        ev = [e for e in col.drain() if e.name == "plan/replan_failed"]
    assert len(ev) == 1 and ev[0].kind == "counter"
    assert "boom" in ev[0].meta["error"]
    assert seam.last_replan is None


# ---------------------------------------------------------------------------
# telemetry summarize + inspect CLI + trainer resume
# ---------------------------------------------------------------------------

def test_summarize_rebalance_section_renders():
    ev = [{"name": "rebalance/detect", "value": 1.0, "ts": 1.0,
           "step": 8, "meta": {"straggler": "0001", "straggler_rank": 1,
                               "ratio": 5.9}},
          {"name": "rebalance/apply", "value": 2.0, "ts": 1.0,
           "step": 8, "meta": {"weights": [8, 1], "straggler": "0001",
                               "straggler_rank": 1, "verified": True,
                               "saved": True, "planned": True}},
          {"name": "rebalance/evict", "value": 1.0, "ts": 1.0,
           "step": 11, "kind": "counter",
           "meta": {"straggler": "0001", "straggler_rank": 1,
                    "ratio": 5.8, "after_rebalance_steps": 3}},
          {"name": "plan/replan_failed", "value": 1.0, "ts": 1.0,
           "kind": "counter", "meta": {"error": "RuntimeError: x"}},
          {"name": "resilience/reshard", "value": 1.0, "ts": 1.0,
           "step": 12, "meta": {"from_world": 2, "to_world": 2,
                                "generation": 3,
                                "from_weights": [8, 1],
                                "to_weights": None}}]
    agg = telemetry.summarize(ev)
    r = agg["resilience"]
    assert r["rebalance_detects"][0]["straggler_rank"] == 1
    assert r["rebalance_applies"][0]["weights"] == [8, 1]
    assert r["rebalance_evicts"][0]["after_rebalance_steps"] == 3
    assert r["replan_failures"] == 1
    assert r["reshards"][0]["from_weights"] == [8, 1]
    text = telemetry.format_summary(agg)
    assert "straggler detected: member 0001 (rank 1)" in text
    assert "rebalanced to weights 8:1" in text
    assert "planner-picked" in text
    assert "gather-verified bitwise" in text
    assert "EVICTED straggler member 0001" in text
    assert "replan FAILURE" in text
    assert "weights 8:1 -> equal" in text


def test_inspect_cli_weighted_rendering_and_check(tmp_path, capsys):
    from apex_tpu.resilience import cli
    params = tree_params()
    opt, state = train_zero(2, params)
    fp = opt.layout_fingerprint(params)
    wfp = elastic.weighted_fingerprint(fp, [3, 1])
    wstate = elastic.reshard_state(
        state, elastic.spec_for(params, fp),
        elastic.spec_for(params, wfp))
    mgr = resilience.SnapshotManager(str(tmp_path / "snap"))
    mgr.save((params, state), step=2, layout=fp)
    mgr.save((params, wstate), step=4, layout=wfp)

    assert cli.main(["inspect", str(tmp_path / "snap")]) == 0
    out = capsys.readouterr().out
    assert "weights 3:1 (75.0%/25.0%)" in out

    # --check W --weights: feasibility with the documented grammar,
    # exit-code contract unchanged
    assert cli.main(["inspect", str(tmp_path / "snap"),
                     "--check", "2", "--weights", "3:1"]) == 0
    out = capsys.readouterr().out
    assert "with weights 3:1 possible" in out
    assert cli.main(["inspect", str(tmp_path / "snap"),
                     "--check", "1", "--weights", "3:1"]) == 3
    capsys.readouterr()
    # malformed vector / --weights without --check: usage (2)
    assert cli.main(["inspect", str(tmp_path / "snap"),
                     "--check", "2", "--weights", "3:x"]) == 2
    assert cli.main(["inspect", str(tmp_path / "snap"),
                     "--weights", "3:1"]) == 2
    capsys.readouterr()
    # --json carries the weights row
    assert cli.main(["inspect", str(tmp_path / "snap"), "--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["rows"][1]["weights"] == [3, 1]
    assert data["rows"][0]["weights"] is None


def test_trainer_notify_resume_carries_weights():
    from apex_tpu.trainer.builder import Trainer, TrainerConfig
    tr = Trainer(fn=lambda s, b: (s, None),
                 traced_fn=lambda s, b: (s, None),
                 config=TrainerConfig(), donation=None)
    with telemetry.capture() as col:
        tr.notify_resume(7, world=2, from_world=2,
                         weights=None, from_weights=[8, 1])
        events = [e for e in col.drain() if e.name == "trainer/resume"]
    assert events[0].meta == {"world": 2, "from_world": 2,
                              "weights": None, "from_weights": [8, 1]}


def test_elastic_restore_records_weight_crossing(tmp_path):
    params = tree_params()
    opt, state = train_zero(2, params)
    fp = opt.layout_fingerprint(params)
    wfp = elastic.weighted_fingerprint(fp, [3, 1])
    wstate = elastic.reshard_state(
        state, elastic.spec_for(params, fp),
        elastic.spec_for(params, wfp))
    mgr = resilience.SnapshotManager(str(tmp_path))
    mgr.save((params, wstate), step=4, layout=wfp)
    seam = resilience.Elastic(opt, params)
    found = seam.restore(mgr, (params, opt.init(params)))
    assert found is not None
    assert seam.last_reshard["from_weights"] == [3, 1]
    assert seam.last_reshard["to_weights"] is None
    assert seam.last_reshard["from_world"] == 2
    assert seam.last_reshard["to_world"] == 2


# ---------------------------------------------------------------------------
# off-switch pins
# ---------------------------------------------------------------------------

def test_supervisor_off_traced_program_unchanged(tmp_path):
    """The whole rebalance stack is HOST-side: constructing supervisors
    and weighted fingerprints must not change a traced ZeRO step by a
    single equation (jaxpr-pinned), and the equal-shard fingerprint
    stays byte-identical."""
    params = tree_params()
    world = 2
    mesh = parallel.reform_mesh(world)

    def build():
        opt = DistributedFusedAdam(lr=0.05, shard_count=world,
                                   chunk_elements=256)
        specs = opt.state_pspec()
        sharded = shard_map(opt.step, mesh=mesh,
                            in_specs=(P(), P(), specs),
                            out_specs=(P(), specs), check_vma=False)
        state = opt.init(params)
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        return opt, jax.make_jaxpr(sharded)(grads, params, state)

    opt_a, jaxpr_a = build()
    fp_a = opt_a.layout_fingerprint(params)
    # arm the whole rebalance stack...
    rdzv = multiproc.Rendezvous(str(tmp_path / "r"), "0000")
    rdzv.announce()
    sup = rebalance.DegradationSupervisor(rdzv, rank=0)
    for i in range(3):
        sup.observe(i, step_s=0.01)
    elastic.weighted_fingerprint(fp_a, [3, 1])
    # ...and the traced program + equal fingerprint are unchanged
    opt_b, jaxpr_b = build()
    assert str(jaxpr_a) == str(jaxpr_b)
    assert opt_b.layout_fingerprint(params) == fp_a
    assert "weights" not in fp_a
