"""Checkpoint/resume tests — the analog of the reference's
tests/L0/run_amp/test_checkpointing.py (loss-scale round trip, O2/O5 fp32
transparency, bitwise resume)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp, checkpoint, optimizers


def _make_train_state(opt_level="O5"):
    opt = optimizers.FusedAdam(lr=0.05)
    aopt = amp.AmpOptimizer(opt, amp.resolve(opt_level))
    params = {"w": jnp.ones((8,), jnp.bfloat16),
              "b": jnp.zeros((2,), jnp.bfloat16)}
    state = aopt.init(params)
    return aopt, params, state


def _train(aopt, params, state, steps=3):
    x = jnp.linspace(-1, 1, 8, dtype=jnp.bfloat16)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            loss = ((p["w"] * x).sum() - 1.0) ** 2
            return aopt.scale_loss(loss, state)
        grads = jax.grad(loss_fn)(params)
        return aopt.step(grads, params, state)

    for _ in range(steps):
        params, state, _ = step(params, state)
    return params, state


def test_npz_roundtrip_bitwise(tmp_path):
    aopt, params, state = _make_train_state()
    params, state = _train(aopt, params, state)
    ck = {"params": params, "amp": state, "step": jnp.asarray(3)}
    path = str(tmp_path / "ck.npz")
    checkpoint.save_npz(path, ck)

    aopt2, params2, state2 = _make_train_state()
    restored = checkpoint.restore_npz(path, {"params": params2,
                                             "amp": state2,
                                             "step": jnp.asarray(0)})
    for a, b in zip(jax.tree_util.tree_leaves(ck),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # resumed training is bitwise identical to uninterrupted training
    cont_a, st_a = _train(aopt, params, state, steps=2)
    cont_b, st_b = _train(aopt2, restored["params"], restored["amp"], steps=2)
    for a, b in zip(jax.tree_util.tree_leaves(cont_a),
                    jax.tree_util.tree_leaves(cont_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_o5_checkpoint_carries_fp32_master(tmp_path):
    """O2/O5 transparency: the saved state holds fp32 master weights even
    though the live model is bf16 (reference _initialize.py:133-142)."""
    aopt, params, state = _make_train_state("O5")
    assert params["w"].dtype == jnp.bfloat16
    masters = jax.tree_util.tree_leaves(state.master)
    assert masters and all(m.dtype == jnp.float32 for m in masters)


def test_orbax_roundtrip(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    aopt, params, state = _make_train_state()
    params, state = _train(aopt, params, state)
    ck = {"params": params, "amp": state, "step": jnp.asarray(3)}
    path = str(tmp_path / "orbax_ck")
    checkpoint.save(path, ck)

    # template restore: structure (NamedTuples) and shardings preserved
    aopt2, params2, state2 = _make_train_state()
    template = {"params": params2, "amp": state2, "step": jnp.asarray(0)}
    restored = checkpoint.restore(path, template)
    for a, b in zip(jax.tree_util.tree_leaves(ck),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # template-free restore still yields the right values (dict-shaped)
    raw = checkpoint.restore(path)
    np.testing.assert_array_equal(np.asarray(raw["step"]), 3)


def test_amp_state_dict_roundtrip():
    """Scaler (loss_scale, unskipped) round trip — amp.state_dict parity
    (frontend.py:428-467)."""
    aopt, params, state = _make_train_state("O2")
    params, state = _train(aopt, params, state)
    d = amp.state_dict(aopt, state)
    aopt2, params2, state2 = _make_train_state("O2")
    state2 = amp.load_state_dict(aopt2, state2, d)
    np.testing.assert_array_equal(np.asarray(state.scaler.loss_scale),
                                  np.asarray(state2.scaler.loss_scale))


def test_orbax_sharded_roundtrip(tmp_path):
    """Save/restore arrays sharded over a mesh — the distributed analog of
    rank-0 torch.save (every host writes its addressable shards)."""
    pytest.importorskip("orbax.checkpoint")
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    x = jax.device_put(jnp.arange(32, dtype=jnp.float32), sharding)
    path = str(tmp_path / "sharded_ck")
    checkpoint.save(path, {"x": x})

    template = {"x": jax.device_put(jnp.zeros((32,), jnp.float32), sharding)}
    restored = checkpoint.restore(path, template)
    assert restored["x"].sharding == sharding
    np.testing.assert_array_equal(np.asarray(restored["x"]),
                                  np.arange(32, dtype=np.float32))


def test_npz_structure_mismatch_raises(tmp_path):
    """Loading into a template with a different tree structure must fail
    loudly, not silently scramble leaves."""
    path = str(tmp_path / "ck.npz")
    checkpoint.save_npz(path, {"a": jnp.ones((2,)), "b": jnp.zeros((3,))})
    with pytest.raises(ValueError, match="does not match the template"):
        checkpoint.restore_npz(path, {"a": jnp.ones((2,)),
                                      "c": jnp.zeros((3,))})


def test_save_npz_atomic_publish(tmp_path, monkeypatch):
    """A crash mid-write must leave the previous complete checkpoint in
    place (the write goes to a temp file published via os.replace), not
    a truncated archive."""
    path = str(tmp_path / "ck.npz")
    checkpoint.save_npz(path, {"a": jnp.ones((4,))})
    before = open(path, "rb").read()

    real_savez = np.savez

    def dying_savez(f, **kw):
        real_savez(f, **kw)
        raise RuntimeError("simulated crash mid-save")

    monkeypatch.setattr(np, "savez", dying_savez)
    with pytest.raises(RuntimeError, match="simulated crash"):
        checkpoint.save_npz(path, {"a": jnp.zeros((4,))})
    monkeypatch.undo()
    # target untouched, no tmp litter
    assert open(path, "rb").read() == before
    assert os.listdir(tmp_path) == ["ck.npz"]
    restored = checkpoint.restore_npz(path, {"a": jnp.zeros((4,))})
    np.testing.assert_array_equal(np.asarray(restored["a"]), np.ones(4))


def test_restore_npz_truncated_raises_clear_error(tmp_path):
    """A truncated .npz (mid-write crash from the pre-atomic era, disk
    damage) must raise a clear error NAMING the file — not a confusing
    pickle/zip traceback."""
    path = str(tmp_path / "ck.npz")
    checkpoint.save_npz(path, {"a": jnp.arange(1024.0)})
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[: len(blob) // 2])
    with pytest.raises(ValueError,
                       match="truncated or corrupt checkpoint.*ck.npz"):
        checkpoint.restore_npz(path, {"a": jnp.zeros((1024,))})


def test_restore_npz_garbage_raises_clear_error(tmp_path):
    path = str(tmp_path / "ck.npz")
    open(path, "wb").write(b"this was never an npz file")
    with pytest.raises(ValueError, match="truncated or corrupt"):
        checkpoint.restore_npz(path, {"a": jnp.zeros((2,))})


def test_npz_layout_fingerprint_roundtrip_and_mismatch(tmp_path):
    """The ZeRO-style layout fingerprint rides inside the archive and is
    validated BEFORE arrays materialize: a checkpoint from a different
    mesh/chunk resolution fails fast with both fingerprints in the
    message."""
    path = str(tmp_path / "ck.npz")
    fp = {"chunk_elements": 1 << 23, "shard_count": 8, "total": 72}
    checkpoint.save_npz(path, {"m": jnp.ones((72,))}, layout=fp)
    restored = checkpoint.restore_npz(path, {"m": jnp.zeros((72,))},
                                      expected_layout=fp)
    np.testing.assert_array_equal(np.asarray(restored["m"]), np.ones(72))
    other = dict(fp, shard_count=4)
    with pytest.raises(ValueError) as exc:
        checkpoint.restore_npz(path, {"m": jnp.zeros((72,))},
                               expected_layout=other)
    assert "layout fingerprint mismatch" in str(exc.value)
    assert "'shard_count': 8" in str(exc.value)    # found
    assert "'shard_count': 4" in str(exc.value)    # expected
    # a checkpoint that never recorded a layout also fails fast
    checkpoint.save_npz(path, {"m": jnp.ones((72,))})
    with pytest.raises(ValueError, match="predates layout recording"):
        checkpoint.restore_npz(path, {"m": jnp.zeros((72,))},
                               expected_layout=fp)


def test_orbax_layout_sidecar(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    path = str(tmp_path / "orbax_ck")
    fp = {"shard_count": 8, "structure_crc32": 12345}
    checkpoint.save(path, {"x": jnp.arange(8.0)}, layout=fp)
    template = {"x": jnp.zeros((8,))}
    restored = checkpoint.restore(path, template, expected_layout=fp)
    np.testing.assert_array_equal(np.asarray(restored["x"]),
                                  np.arange(8.0))
    with pytest.raises(ValueError, match="layout fingerprint mismatch"):
        checkpoint.restore(path, template,
                           expected_layout=dict(fp, shard_count=16))
