"""apex_tpu.telemetry: trace-safe record under jit/shard_map,
instrument_step timing fields, comm-byte accounting vs hand-computed
values on a 1xN mesh, JSONL round-trip + rotation, the summarize CLI on a
fixture run, and the producer wiring (amp scaler, ZeRO, PrefetchLoader,
device_peak_flops CPU fallback)."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import telemetry
from apex_tpu.telemetry import events as tel_events
from apex_tpu.telemetry import export as tel_export
from apex_tpu.telemetry.cli import main as cli_main


@pytest.fixture
def col():
    """Fresh enabled collector; global state restored afterwards."""
    with tel_events.capture() as c:
        yield c


def _by_name(col, name):
    return [e for e in col.snapshot() if e.name == name]


# ---------------------------------------------------------------------------
# events / collector
# ---------------------------------------------------------------------------

def test_disabled_record_is_noop():
    telemetry.get_collector().clear()
    assert not telemetry.enabled()
    telemetry.record("x", 1.0)
    telemetry.record_static("y", 2.0)
    assert len(telemetry.get_collector()) == 0


def test_collector_bounded_drops_oldest():
    c = tel_events.Collector(capacity=4)
    for i in range(7):
        c.record("n", float(i))
    evs = c.snapshot()
    assert len(evs) == 4
    assert [e.value for e in evs] == [3.0, 4.0, 5.0, 6.0]
    assert c.dropped == 3


def test_static_dedup_across_retraces(col):
    for _ in range(3):
        telemetry.record_static("comm/x", 5.0, dedup_key=("a", 1))
    telemetry.record_static("comm/x", 7.0, dedup_key=("a", 2))
    assert [e.value for e in _by_name(col, "comm/x")] == [5.0, 7.0]


def test_event_dict_roundtrip():
    e = tel_events.Event("a/b", 1.5, ts=12.0, step=3, kind="counter",
                        meta={"axis": "data"})
    assert tel_events.Event.from_dict(e.to_dict()) == e


# ---------------------------------------------------------------------------
# trace-safe record
# ---------------------------------------------------------------------------

def test_record_under_jit(col):
    @jax.jit
    def f(a):
        telemetry.record("jit/sum", jnp.sum(a), step=7)
        return a * 2

    jax.block_until_ready(f(jnp.ones((8,))))
    jax.effects_barrier()
    evs = _by_name(col, "jit/sum")
    assert len(evs) == 1
    assert evs[0].value == 8.0 and evs[0].step == 7


def test_record_traced_step_attribution(col):
    @jax.jit
    def f(a, s):
        telemetry.record("jit/v", jnp.max(a), step=s)
        return a

    jax.block_until_ready(f(jnp.full((3,), 4.0), jnp.int32(11)))
    jax.effects_barrier()
    (e,) = _by_name(col, "jit/v")
    assert (e.value, e.step) == (4.0, 11)


def test_record_under_shard_map(col):
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))

    def body(x):
        s = jax.lax.psum(jnp.sum(x), "data")
        telemetry.record("sm/total", s, step=0)
        return s

    f = jax.jit(shard_map(body, mesh=mesh, in_specs=P("data"),
                          out_specs=P(), check_vma=False))
    out = f(jnp.ones((8, 4)))
    jax.block_until_ready(out)
    jax.effects_barrier()
    evs = _by_name(col, "sm/total")
    # one callback per shard, all carrying the replicated global value
    assert 1 <= len(evs) <= 8
    assert all(e.value == 32.0 for e in evs)
    # the summarize dedup collapses the replicas to one step sample
    agg = tel_export.summarize([e.to_dict() for e in evs])
    assert agg["events"] == len(evs)


def test_record_inside_scan(col):
    @jax.jit
    def f(x):
        def body(c, i):
            telemetry.record("scan/c", c, step=i)
            return c + 1.0, c
        c, _ = jax.lax.scan(body, x, jnp.arange(4))
        return c

    jax.block_until_ready(f(jnp.float32(0.0)))
    jax.effects_barrier()
    evs = _by_name(col, "scan/c")
    assert sorted((e.step, e.value) for e in evs) == [
        (0, 0.0), (1, 1.0), (2, 2.0), (3, 3.0)]


# ---------------------------------------------------------------------------
# instrument_step
# ---------------------------------------------------------------------------

def test_instrument_step_fields(col):
    step = telemetry.instrument_step(
        jax.jit(lambda x: x * 2 + 1), tokens_per_step=1024)
    x = jnp.ones((16, 64))
    for _ in range(3):
        x = step(x)
    jax.effects_barrier()
    for suffix in ("time_s", "dispatch_s", "device_wait_s",
                   "tokens_per_s"):
        evs = _by_name(col, f"step/{suffix}")
        assert len(evs) == 3, suffix
        assert [e.step for e in evs] == [0, 1, 2]
        assert all(e.value >= 0 for e in evs)
    # dispatch + wait == total, per step
    for t, d, w in zip(_by_name(col, "step/time_s"),
                       _by_name(col, "step/dispatch_s"),
                       _by_name(col, "step/device_wait_s")):
        assert t.value == pytest.approx(d.value + w.value, rel=1e-6)
    # flops measured lazily (from call 2) -> static event + MFU samples
    assert len(_by_name(col, "step/model_flops")) == 1
    assert len(_by_name(col, "step/mfu")) == 2
    assert all(e.value > 0 for e in _by_name(col, "step/mfu"))


def test_instrument_step_passthrough_and_disabled():
    step = telemetry.instrument_step(lambda a, b: a + b)
    assert not telemetry.enabled()
    assert step(2, 3) == 5            # disabled: pure passthrough
    assert len(telemetry.get_collector()) == 0


def test_instrument_step_sync_every(col):
    step = telemetry.instrument_step(jax.jit(lambda x: x + 1),
                                     sync_every=2, measure_flops=False)
    x = jnp.zeros(())
    for _ in range(4):
        x = step(x)
    assert float(x) == 4.0
    assert [e.step for e in _by_name(col, "step/time_s")] == [0, 2]


def test_instrument_step_model_flops_override(col):
    step = telemetry.instrument_step(jax.jit(lambda x: x), name="b",
                                     model_flops=1e9, peak_flops=1e12)
    x = jnp.zeros((4,))
    for _ in range(2):
        x = step(x)
    (fl,) = _by_name(col, "b/model_flops")
    assert fl.value == 1e9 and fl.kind == "static"
    mfu = _by_name(col, "b/mfu")
    assert len(mfu) == 2
    # mfu = 1e9 / t / 1e12 = 1e-3 / t
    for e, t in zip(mfu, _by_name(col, "b/time_s")):
        assert e.value == pytest.approx(1e-3 / t.value, rel=1e-6)


# ---------------------------------------------------------------------------
# comm accounting (hand-computed on the 1x8 CPU mesh)
# ---------------------------------------------------------------------------

def test_comm_stats_hand_computed():
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))

    def body(x):
        return jax.lax.psum(x, "data"), jax.lax.all_gather(x, "data")

    f = shard_map(body, mesh=mesh, in_specs=P("data"),
                  out_specs=(P(), P()), check_vma=False)
    x = jnp.ones((8, 128), jnp.float32)   # per-shard (1, 128) f32 = 512 B
    recs = {r.primitive: r for r in telemetry.comm_stats(f, x)}
    assert set(recs) == {"psum", "all_gather"}
    ps, ag = recs["psum"], recs["all_gather"]
    assert (ps.axis, ps.count, ps.bytes_in) == ("data", 1, 512.0)
    assert ps.bytes_wire == pytest.approx(2 * 7 / 8 * 512)   # ring AR
    assert (ag.count, ag.bytes_in) == (1, 512.0)
    assert ag.bytes_wire == pytest.approx(7 * 512)           # ring AG


def test_comm_stats_scan_scaling_and_axis_sizes_arg():
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))

    def body(x):
        def it(c, _):
            return jax.lax.psum(c, "data"), None
        c, _ = jax.lax.scan(it, x, None, length=5)
        return c

    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P(),
                  check_vma=False)
    x = jnp.ones((8, 16), jnp.float32)    # per-shard 64 B
    (r,) = telemetry.comm_stats(f, x)
    assert (r.count, r.bytes_in) == (5, 5 * 64.0)
    assert r.bytes_wire == pytest.approx(5 * 64 * 2 * 7 / 8)


def test_comm_stats_axis_sizes_arg_and_unknown_axis():
    # a bare collective fragment (no enclosing shard_map): the axis size
    # must come from the caller; without it the wire bill is None
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("ax",))

    def bare(x):
        return jax.lax.psum(x, "ax")

    f = shard_map(bare, mesh=mesh, in_specs=P(), out_specs=P(),
                  check_vma=False)
    (r,) = telemetry.comm_stats(f, jnp.ones((4,), jnp.float32))
    assert r.bytes_in == 16.0
    assert r.bytes_wire == pytest.approx(2 * 3 / 4 * 16)
    # explicit axis_sizes pre-seed is honored where the mesh is unknown
    (r2,) = telemetry.comm_stats(f, jnp.ones((4,), jnp.float32),
                                 axis_sizes={"other": 2})
    assert r2.bytes_wire == pytest.approx(2 * 3 / 4 * 16)


def test_record_comm_stats_emits_static_events(col):
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))
    f = shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                  in_specs=P("data"), out_specs=P(), check_vma=False)
    x = jnp.ones((8, 32), jnp.float32)
    telemetry.record_comm_stats(f, x)
    telemetry.record_comm_stats(f, x)   # retrace: dedup'd
    evs = _by_name(col, "comm/data/psum_bytes")
    assert len(evs) == 1
    assert evs[0].value == 128.0 and evs[0].kind == "static"
    assert evs[0].meta["axis"] == "data"


# ---------------------------------------------------------------------------
# export: JSONL round-trip, rotation, CSV, summarize
# ---------------------------------------------------------------------------

def test_jsonl_roundtrip(tmp_path, col):
    telemetry.record("a", 1.0, step=0)
    telemetry.record("a", 2.0, step=1)
    telemetry.record_static("s", 3.0, meta={"k": "v"})
    path = str(tmp_path / "run.jsonl")
    telemetry.write_jsonl(path)           # drains the collector
    assert len(col) == 0
    back = telemetry.read_jsonl(path)
    assert [(d["name"], d["value"]) for d in back] == [
        ("a", 1.0), ("a", 2.0), ("s", 3.0)]
    assert back[2]["kind"] == "static" and back[2]["meta"] == {"k": "v"}


def test_jsonl_rotation(tmp_path):
    path = str(tmp_path / "r.jsonl")
    with tel_export.JsonlWriter(path, max_bytes=200, max_files=2) as w:
        for i in range(20):
            w.write(tel_events.Event("n", float(i), ts=0.0))
    assert os.path.exists(path) and os.path.exists(path + ".1")
    assert not os.path.exists(path + ".3")
    # every surviving line still parses
    for p in (path, path + ".1", path + ".2"):
        if os.path.exists(p):
            telemetry.read_jsonl(p)


def test_read_jsonl_rejects_malformed(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_text('{"name": "a", "value": 1}\nnot json\n')
    with pytest.raises(ValueError, match="bad.jsonl:2"):
        telemetry.read_jsonl(str(p))


def test_csv_export(tmp_path):
    path = str(tmp_path / "out.csv")
    tel_export.write_csv(path, [tel_events.Event("n", 1.0, ts=2.0, step=3)])
    lines = open(path).read().strip().splitlines()
    assert lines[0] == "name,value,ts,step,kind"
    assert lines[1] == "n,1.0,2.0,3,point"


def _fixture_events():
    evs = []
    for step in range(10):
        evs.append({"name": "step/time_s", "value": 0.1 + 0.01 * step,
                    "ts": float(step), "step": step})
        evs.append({"name": "step/dispatch_s", "value": 0.02,
                    "ts": float(step), "step": step})
        evs.append({"name": "step/device_wait_s",
                    "value": 0.08 + 0.01 * step, "ts": float(step),
                    "step": step})
        # two shards' worth of replicated amp events
        for _ in range(2):
            evs.append({"name": "amp/overflow",
                        "value": 1.0 if step == 3 else 0.0,
                        "ts": float(step), "step": step})
            evs.append({"name": "amp/loss_scale",
                        "value": 2.0 ** 16 / (2 if step >= 3 else 1),
                        "ts": float(step), "step": step})
    evs.append({"name": "ddp/data/allreduce_bytes", "value": 4096.0,
                "ts": 0.0, "kind": "static",
                "meta": {"axis": "data", "primitive": "psum", "count": 2,
                         "bytes_wire": 7168}})
    evs.append({"name": "step/model_flops", "value": 1e9, "ts": 0.0,
                "kind": "static"})
    evs.append({"name": "data/starvation", "value": 1.0, "ts": 0.0,
                "kind": "counter"})
    return evs


def test_summarize_aggregates():
    s = tel_export.summarize(_fixture_events())
    assert s["step_time_s"]["count"] == 10
    assert s["step_time_s"]["p50"] == pytest.approx(0.145)
    assert s["step_time_s"]["max"] == pytest.approx(0.19)
    # replicated shard samples collapse to one per step
    assert s["overflow"] == {"steps": 10, "overflows": 1, "rate": 0.1}
    tl = dict(map(tuple, s["loss_scale"]["timeline"]))
    assert tl[0] == 2.0 ** 16 and tl[9] == 2.0 ** 15
    assert s["comm"]["data"]["bytes_in_per_step"] == 4096.0
    assert s["comm"]["data"]["collectives"]["psum"]["count"] == 2
    assert s["static"]["step/model_flops"] == 1e9
    assert s["counters"]["data/starvation"] == 1.0


def test_summarize_no_double_count_walker_vs_producer():
    """A run carrying BOTH the jaxpr walker's comm bill and the ddp/zero
    producer events for the same axis must not sum the same bytes twice:
    walker events are the complete account, producers become a named
    breakdown."""
    evs = [
        {"name": "comm/data/psum_bytes", "value": 1000.0, "ts": 0.0,
         "kind": "static",
         "meta": {"axis": "data", "primitive": "psum", "count": 3}},
        {"name": "ddp/data/allreduce_bytes", "value": 900.0, "ts": 0.0,
         "kind": "static",
         "meta": {"axis": "data", "primitive": "psum", "count": 2}},
        # a producer-only axis still gets its totals from the producer
        {"name": "zero/model/reduce_scatter_bytes", "value": 512.0,
         "ts": 0.0, "kind": "static",
         "meta": {"axis": "model", "primitive": "psum_scatter",
                  "count": 1}},
    ]
    s = tel_export.summarize(evs)
    assert s["comm"]["data"]["bytes_in_per_step"] == 1000.0
    assert s["comm"]["data"]["producers"] == {
        "ddp/data/allreduce_bytes": 900.0}
    assert s["comm"]["model"]["bytes_in_per_step"] == 512.0


def test_summarize_cli_on_fixture_run(tmp_path, capsys):
    path = str(tmp_path / "fix.jsonl")
    tel_export.write_jsonl(path, _fixture_events())
    assert cli_main(["summarize", path]) == 0
    out = capsys.readouterr().out
    for frag in ("step time", "overflow", "loss scale", "axis 'data'",
                 "psum"):
        assert frag in out, frag
    assert cli_main(["summarize", path, "--json"]) == 0
    agg = json.loads(capsys.readouterr().out)
    assert agg["overflow"]["overflows"] == 1
    assert cli_main(["tail", path, "-n", "3"]) == 0
    assert cli_main(["summarize", str(tmp_path / "missing.jsonl")]) == 1


# ---------------------------------------------------------------------------
# producer wiring
# ---------------------------------------------------------------------------

def test_amp_scaler_emits_overflow_and_scale(col):
    from apex_tpu import amp, optimizers

    inner = optimizers.FusedSGD(lr=0.1)
    _, aopt = amp.initialize(None, inner, opt_level="O2", verbosity=0)
    params = {"w": jnp.ones((4, 4), jnp.float16)}
    state = aopt.init(params)

    @jax.jit
    def step(g, p, s):
        return aopt.step(g, p, s)

    # clean grads, then an overflow (inf) step
    good = {"w": jnp.ones((4, 4), jnp.float16)}
    bad = {"w": jnp.full((4, 4), jnp.inf, jnp.float16)}
    params, state, _ = step(good, params, state)
    params, state, _ = step(bad, params, state)
    jax.block_until_ready(state.scaler.loss_scale)
    jax.effects_barrier()
    ov = _by_name(col, "amp/overflow")
    ls = _by_name(col, "amp/loss_scale")
    assert [e.value for e in ov] == [0.0, 1.0]
    # execution-index attribution: advances even though the overflow
    # execution skipped the inner optimizer step
    assert [e.step for e in ov] == [0, 1]
    assert ls[0].value == 2.0 ** 16
    assert ls[1].value == 2.0 ** 15        # halved on overflow


def test_zero_emits_comm_bytes(col):
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    n = 8
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("data",))
    opt = DistributedFusedAdam(lr=1e-3, axis_name="data", shard_count=n)
    p = {"w": jnp.ones((8, 16)), "b": jnp.ones((8,))}   # 136 el -> pad 136
    st = opt.init(p)

    f = jax.jit(shard_map(
        lambda g, p, s: opt.step(g, p, s), mesh=mesh,
        in_specs=(P(), P(), opt.state_pspec()),
        out_specs=(P(), opt.state_pspec()), check_vma=False))
    new_p, new_st = f(p, p, st)
    jax.block_until_ready(new_st.master)
    rs = _by_name(col, "zero/data/reduce_scatter_bytes")
    ag = _by_name(col, "zero/data/all_gather_bytes")
    assert len(rs) == 1 and len(ag) == 1
    # 136 elements pad to 136 (17 * 8) -> 544 B f32 in; shard k=17 -> 68 B
    assert rs[0].value == 544.0
    assert rs[0].meta["bytes_wire"] == round(544 * 7 / 8)
    assert ag[0].value == 68.0
    assert ag[0].meta["bytes_wire"] == 68 * 7


def test_ddp_emits_comm_bytes(col):
    from apex_tpu import parallel

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))
    grads = {"a": jnp.ones((16, 8), jnp.float32),
             "b": jnp.ones((32,), jnp.bfloat16)}

    f = jax.jit(shard_map(
        lambda g: parallel.allreduce_gradients(g, "data"), mesh=mesh,
        in_specs=(P(),), out_specs=P(), check_vma=False))
    jax.block_until_ready(f(grads))
    (e,) = _by_name(col, "ddp/data/allreduce_bytes")
    assert e.value == 16 * 8 * 4 + 32 * 2
    assert e.meta["count"] == 2       # one bucket per dtype
    assert e.meta["world"] == 8


def test_prefetch_loader_stats_and_telemetry(col):
    from apex_tpu import runtime

    loader = runtime.PrefetchLoader(iter(range(10)), depth=4, workers=1)
    out = list(loader)
    assert sorted(out) == list(range(10))
    st = loader.stats()
    assert st["produced"] == 10 and st["consumed"] == 10
    assert 0 <= st["starvations"] <= 10
    assert st["queue_depth"] == 0 and st["depth"] == 4
    depth_evs = _by_name(col, "data/queue_depth")
    assert len(depth_evs) == 10
    starve_evs = _by_name(col, "data/starvation")
    assert len(starve_evs) == st["starvations"]
    assert all(e.kind == "counter" for e in starve_evs)


def test_prefetch_loader_starvation_counts_slow_source():
    import time as _time

    from apex_tpu import runtime

    def slow():
        for i in range(5):
            _time.sleep(0.05)
            yield i

    loader = runtime.PrefetchLoader(slow(), depth=4, workers=1)
    assert list(loader) == list(range(5))
    # a source slower than the consumer starves every fetch
    assert loader.stats()["starvations"] >= 4


def test_device_peak_flops_cpu_fallback(monkeypatch):
    from apex_tpu.pyprof import prof

    monkeypatch.delenv("APEX_TPU_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("BENCH_PEAK_FLOPS", raising=False)
    peak = prof.device_peak_flops()           # CPU backend under tests
    assert peak == prof.PEAK_CPU_NOMINAL
    assert np.isfinite(peak) and peak > 0
    monkeypatch.setenv("APEX_TPU_PEAK_FLOPS", "5e12")
    assert prof.device_peak_flops() == 5e12   # calibrated override wins


# Integration tier: ~40 s (compiles an amp GPT shard_map step). The same
# product path runs in ci/gate.sh stage 6/7 (instrumented train_lm ->
# JSONL -> summarize); the unit tests above cover every piece separately.
@pytest.mark.slow
def test_instrumented_train_step_end_to_end(tmp_path, col):
    """The acceptance path in miniature: an amp GPT train step under
    shard_map emits step-time, loss-scale/overflow, comm and MFU events;
    the JSONL parses; summarize renders it."""
    from apex_tpu import amp, optimizers
    from apex_tpu.models import GPTTiny
    from apex_tpu.models.gpt import next_token_loss

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))
    m = GPTTiny(vocab_size=64, max_seq=16, dtype=jnp.float16)
    toks = jnp.zeros((8, 16), jnp.int32)
    params32 = m.init(jax.random.PRNGKey(0), toks[:1])["params"]
    inner = optimizers.FusedAdam(lr=1e-3)
    _, aopt = amp.initialize(None, inner, opt_level="O2", verbosity=0)
    params = amp.cast_model(params32, amp.resolve(
        "O2", keep_batchnorm_fp32=False))
    state = aopt.init(params)

    def per_device(p, s, t):
        def scaled(p):
            return aopt.scale_loss(
                next_token_loss(m.apply({"params": p}, t), t), s)
        g = jax.grad(scaled)(p)
        g = jax.lax.pmean(g, "data")
        new_p, new_s, info = aopt.step(g, p, s)
        return new_p, new_s, info["loss_scale"]

    step_fn = jax.jit(shard_map(
        per_device, mesh=mesh, in_specs=(P(), P(), P("data")),
        out_specs=(P(), P(), P()), check_vma=False))
    step = telemetry.instrument_step(step_fn,
                                     tokens_per_step=toks.size)
    for _ in range(3):
        params, state, scale = step(params, state, toks)
    telemetry.record_comm_stats(step_fn, params, state, toks)
    jax.block_until_ready(scale)
    jax.effects_barrier()

    path = str(tmp_path / "run.jsonl")
    telemetry.write_jsonl(path)
    agg = tel_export.summarize(telemetry.read_jsonl(path))
    assert agg["step_time_s"]["count"] == 3
    assert "dispatch_s" in agg and "device_wait_s" in agg
    assert agg["overflow"]["steps"] == 3
    assert agg["loss_scale"]["timeline"]
    assert agg["comm"]["data"]["bytes_in_per_step"] > 0
    assert "mfu" in agg            # CPU cost analysis + nominal peak
    assert cli_main(["summarize", path]) == 0
