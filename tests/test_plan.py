"""apex_tpu.plan — the cost-model-driven parallelism planner.

The load-bearing pins:

  * cost-model wire bytes EQUAL hand-computed telemetry.comm numbers on
    three known layouts (1x8 dp, dp4 x tp2, ZeRO-2) — the numbers are
    derived from the layout spec (param counts, ring multipliers), not
    from the walker, so a walker/planner drift cannot self-certify.
  * infeasible candidates (HBM overflow, non-divisible axis) raise /
    filter LOUDLY with named reasons.
  * every emitted layout passes lint.spmd (APX201-209); a deliberately
    rank-gated candidate raises PlanRejected BEFORE emission.
  * the planner-emitted TrainerConfig trains 3 steps bitwise-stable on
    the 8-device CPU mesh.
  * planner-resolved buckets land in the tune cache schema-v1 with
    "planner" provenance and resolve under APEX_TPU_TUNE=cache with
    zero re-measurement.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import plan
from apex_tpu.plan.adapters import Built, _wrap
from apex_tpu.plan.describe import ModelDesc, tree_bytes, tree_count
from apex_tpu.plan.emit import emit as emit_fn
from apex_tpu.plan.layout import Layout

N_DEV = 8

# one small GPT workload for the whole module (builds are traced, not
# executed, so sharing them across tests is safe)
ADAPTER = plan.GPTAdapter(vocab=64, layers=2, embed=64, heads=4,
                          batch=16, seq=64)


@pytest.fixture(scope="module")
def desc():
    return ADAPTER.describe(compile_reference=False)


_BUILT = {}


def built_for(lid: str) -> Built:
    if lid not in _BUILT:
        _BUILT[lid] = ADAPTER.build(plan.parse_layout_id(lid))
    return _BUILT[lid]


def traced_est(desc, lid: str):
    built = built_for(lid)
    return plan.estimate(desc, built.layout,
                         wire=plan.traced_wire(built))


# ---------------------------------------------------------------------------
# layout ids
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("lid", [
    "dp8", "dp4-tp2", "dp8-zero2-mb2-bf16", "dp2-sq4", "dp2-uly4",
    "dp1", "dp4-pp2", "dp8-noov", "dp8-zero2-fp16",
])
def test_layout_id_roundtrip(lid):
    assert plan.parse_layout_id(lid).layout_id() == lid


def test_layout_id_parse_rejects_garbage():
    with pytest.raises(ValueError, match="grammar"):
        plan.parse_layout_id("tp4-dp2")


@pytest.mark.parametrize("kw,match", [
    (dict(zero=2, dp=1), "requires dp >= 2"),
    (dict(zero=2, dp=2, tp=2), "not a supported composition"),
    (dict(dp=2, tp=2, seq=2), "two axes at once"),
    (dict(reduce_dtype="int4"), "reduce_dtype"),
    (dict(zero=3, dp=2), "stages the toolkit implements"),
    (dict(ddp_bucket=0, dp=2), "positive element count"),
])
def test_layout_validate_loud(kw, match):
    with pytest.raises(ValueError, match=match):
        Layout(**kw)


# ---------------------------------------------------------------------------
# wire bytes pinned to hand-computed telemetry.comm numbers
# ---------------------------------------------------------------------------

def test_wire_bytes_dp8_hand_computed(desc):
    """1x8 dp: one bucketed fp32 grad psum (4P bytes) + the scalar loss
    pmean; wire = 2(n-1)/n x bytes_in (ring all-reduce)."""
    est = traced_est(desc, "dp8")
    p_count = tree_count(ADAPTER._dense_params_sds())
    bytes_in = 4 * (p_count + 1)           # grads + loss scalar
    expect = bytes_in * 2 * (N_DEV - 1) / N_DEV
    assert est.wire_bytes == pytest.approx(expect, rel=1e-9)
    assert est.wire_source == "traced"


def test_wire_bytes_zero2_hand_computed(desc):
    """ZeRO-2 over 8: reduce_scatter of the flat fp32 grads
    ((n-1)/n x 4P) + all_gather of each updated shard ((n-1) x 4P/n)
    + the scalar loss pmean. P divides 8 here, so no chunk padding."""
    p_count = tree_count(ADAPTER._dense_params_sds())
    assert p_count % N_DEV == 0
    est = traced_est(desc, "dp8-zero2")
    rs = 4 * p_count * (N_DEV - 1) / N_DEV
    ag = (4 * p_count / N_DEV) * (N_DEV - 1)
    loss = 4 * 2 * (N_DEV - 1) / N_DEV
    assert est.wire_bytes == pytest.approx(rs + ag + loss, rel=1e-9)


def test_wire_bytes_dp4_tp2_hand_computed(desc):
    """dp4 x tp2: 4 activation psums per block over the model axis at
    2(n-1)/n = 1.0, plus the dp psum of the LOCAL (tp-sharded) tree.
    The local element count is derived from the tp pspecs — the layout
    spec, not the walker."""
    from apex_tpu.parallel import lm_tp_pspecs, tp_shard_lm_params
    est = traced_est(desc, "dp4-tp2")
    params = ADAPTER._dense_params()
    sharded = tp_shard_lm_params(params, 2)
    specs = lm_tp_pspecs(sharded)
    local = 0
    for leaf, spec in zip(jax.tree_util.tree_leaves(sharded),
                          jax.tree_util.tree_leaves(
                              specs, is_leaf=lambda x: isinstance(x, P))):
        shard = 2 if any(ax == "model" for ax in spec) else 1
        local += int(np.prod(leaf.shape)) // shard
    dp_in = 4 * (local + 1)                # local grads + loss scalar
    dp_wire = dp_in * 2 * (4 - 1) / 4
    b_loc, s, e = ADAPTER.batch // 4, ADAPTER.seq, ADAPTER.embed
    tp_wire = (4 * ADAPTER.layers) * (b_loc * s * e * 4) \
        * 2 * (2 - 1) / 2
    assert est.wire_bytes == pytest.approx(dp_wire + tp_wire, rel=1e-9)


@pytest.mark.parametrize("lid", [
    "dp8", "dp8-bf16", "dp8-zero2", "dp4-tp2", "dp4-sq2", "dp2-uly4",
    "dp2-sq4", "dp4-pp2-mb2", "dp1-pp2-mb4",
])
def test_analytic_bill_matches_walker(desc, lid):
    """The closed-form bill the full candidate space is ranked with
    stays within 0.5% of the walker's traced bill for every family —
    no silent cost-model drift (the drift itself is reported)."""
    est = traced_est(desc, lid)
    assert est.wire_drift_pct is not None
    assert abs(est.wire_drift_pct) < 0.5, (lid, est.wire_drift_pct)


# ---------------------------------------------------------------------------
# pruning: loud infeasibility
# ---------------------------------------------------------------------------

def test_prune_non_divisible_axis_filters_with_reason(desc):
    verdicts = plan.prune([Layout(dp=1, tp=8)], desc, adapter=ADAPTER)
    assert not verdicts[0].feasible
    assert "heads 4 not divisible by tp=8" in verdicts[0].reason


def test_estimate_layout_raises_on_infeasible(desc):
    with pytest.raises(plan.PlanError, match="not divisible"):
        plan.estimate_layout(desc, Layout(dp=1, seq=8,
                                          seq_impl="ulysses"))


def test_prune_hbm_overflow_filters_with_reason(desc):
    cons = plan.Constraints(hbm_bytes=1024.0)     # 1 KiB: nothing fits
    verdicts = plan.prune([Layout(dp=N_DEV)], desc, adapter=ADAPTER,
                          constraints=cons)
    assert not verdicts[0].feasible
    assert "HBM overflow" in verdicts[0].reason
    with pytest.raises(plan.PlanError, match="HBM overflow"):
        plan.estimate_layout(desc, Layout(dp=N_DEV), constraints=cons)


def test_auto_raises_when_nothing_survives():
    with pytest.raises(plan.PlanError, match="no feasible layout"):
        plan.auto(ADAPTER,
                  constraints=plan.Constraints(hbm_bytes=1024.0),
                  write_cache=False, compile_reference=False)


def test_adapter_veto_named_reasons():
    # PR 19 un-veto: plain dp x pp BUILDS; only the unbuilt pp
    # compositions keep named vetoes
    assert ADAPTER.veto(Layout(dp=4, pp=2)) is None
    assert "composes with dp only" in ADAPTER.veto(
        Layout(dp=2, pp=2, tp=2))
    assert "pipeline layouts sync grads" in ADAPTER.veto(
        Layout(dp=2, pp=2, reduce_dtype="bf16"))
    assert "pipe-aware flat layout" in ADAPTER.veto(
        Layout(dp=2, pp=2, zero=2))
    assert "DDP bucketed-allreduce" in ADAPTER.veto(
        Layout(dp=4, tp=2, reduce_dtype="bf16"))
    res = plan.ResNetAdapter(batch=16)
    assert "dp/zero layouts only" in res.veto(Layout(dp=4, tp=2))


def test_search_enumerates_feasible_pp_candidates(desc):
    """The un-veto is reachable end to end: the candidate space now
    contains pp>1 layouts the adapter will build, and at least one
    survives pruning (so plan.auto CAN return a pipeline layout)."""
    from apex_tpu.plan.search import enumerate_candidates
    cons = plan.Constraints(validate="none")
    cands = enumerate_candidates(N_DEV, desc, cons)
    pps = [c for c in cands if c.pp > 1]
    assert pps, "search emitted no pipeline candidates"
    assert all(ADAPTER.veto(c) is None for c in pps)
    verdicts = plan.prune(pps, desc, adapter=ADAPTER, constraints=cons)
    assert any(v.feasible for v in verdicts)


def test_hbm_footprint_zero_shards_optimizer(desc):
    full = plan.hbm_footprint(desc, Layout(dp=N_DEV))
    z = plan.hbm_footprint(desc, Layout(dp=N_DEV, zero=2))
    # 8 bytes/param replicated Adam vs 12/dp sharded master+moments
    assert full["opt"] == 8.0 * desc.param_count
    assert z["opt"] == 12.0 * desc.param_count / N_DEV
    assert z["total"] < full["total"]


def test_no_overlap_credit_off_pure_dp(desc):
    """tp/seq builders sync grads with a PLAIN post-backward pmean (no
    staged seam — the adapters' APX206 note), so the cost model must
    not grant their dp collective the staged-backward overlap credit;
    pure dp keeps it. Pinned on both the analytic and traced tiers."""
    for lid in ("dp4-tp2", "dp2-uly4"):
        for est in (plan.estimate(desc, plan.parse_layout_id(lid)),
                    traced_est(desc, lid)):
            assert not any(w.hideable for w in est.wire), (lid, est.wire)
            assert est.hidden_s == 0.0
    assert any(w.hideable for w in
               plan.estimate(desc, plan.parse_layout_id("dp8")).wire)


# ---------------------------------------------------------------------------
# emission: lint gate
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("lid", ["dp8", "dp8-zero2", "dp4-tp2",
                                 "dp4-sq2", "dp2-uly4", "dp4-pp2-mb2"])
def test_emitted_layouts_lint_spmd_clean(lid):
    assert plan.verify_built(built_for(lid)) == []


def test_verify_built_zero_apx204_threshold_is_state_bound(monkeypatch):
    """ZeRO candidates verify with APX204's replication threshold
    raised to the state's own size: the bucketed param all_gathers are
    the zero-2 DESIGN (at real model sizes they cross the default
    1 MiB and disqualified every zero candidate — caught live on the
    resnet-bench comparison), while an activation-sized accidental
    gather still dwarfs the state and fires. Non-zero layouts keep the
    rule's own default."""
    from apex_tpu import lint
    from apex_tpu.lint.spmd_checks import replication_threshold_bytes
    from apex_tpu.plan.describe import tree_bytes
    seen = {}

    def fake(fn, args, **kw):
        seen.update(kw)
        return []

    monkeypatch.setattr(lint, "check_entry_spmd", fake)
    built = built_for("dp8-zero2")
    plan.verify_built(built)
    assert seen["threshold_bytes"] == max(
        replication_threshold_bytes(),
        int(tree_bytes(built.state_avals)) + 1)
    seen.clear()
    plan.verify_built(built_for("dp8"))
    assert seen["threshold_bytes"] is None


def _rank_gated_built():
    lay = Layout(dp=N_DEV)
    from apex_tpu.parallel.mesh import named_mesh
    mesh = named_mesh(lay.mesh_axes())

    def bad_step(state, batch):
        g = state * batch.mean()
        g = jax.lax.cond(jax.lax.axis_index("data") == 0,
                         lambda v: jax.lax.psum(v, "data"),
                         lambda v: v, g)
        return state - 0.01 * g, g.mean()

    return Built(
        layout=lay, mesh=mesh, step=bad_step,
        wrapped=_wrap(bad_step, mesh, P(), P("data")),
        state_spec=P(), batch_spec=P("data"),
        state_avals=jax.ShapeDtypeStruct((4096,), jnp.float32),
        batch_avals=jax.ShapeDtypeStruct((N_DEV, 4096), jnp.float32),
        init_state=lambda: jnp.zeros((4096,)),
        batch_fn=lambda i: jnp.ones((N_DEV, 4096)),
        axis_sizes={"data": N_DEV})


def test_rank_gated_candidate_rejected_before_emission(desc):
    """The acceptance pin: a deliberately rank-gated collective (the
    APX201 multi-host deadlock) must raise PlanRejected from emit —
    the planner never emits a layout the verifier rejects."""
    built = _rank_gated_built()
    findings = plan.verify_built(built)
    assert {f.rule_id for f in findings} == {"APX201"}
    toy = ModelDesc("toy", 4096, 16384, 1e9, 1e8, 1e4, 8 * 4096,
                    {"batch": N_DEV})
    with pytest.raises(plan.PlanRejected, match="APX201"):
        emit_fn(built, plan.estimate(toy, built.layout), desc=toy)


# ---------------------------------------------------------------------------
# auto end to end
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def auto_plan(tmp_path_factory):
    cache_dir = tmp_path_factory.mktemp("tunecache")
    old = os.environ.get("APEX_TPU_TUNE_CACHE_DIR")
    os.environ["APEX_TPU_TUNE_CACHE_DIR"] = str(cache_dir)
    try:
        p = plan.auto(ADAPTER,
                      constraints=plan.Constraints(validate="trace",
                                                   top_k=2),
                      write_cache=True, compile_reference=False)
    finally:
        if old is None:
            os.environ.pop("APEX_TPU_TUNE_CACHE_DIR", None)
        else:
            os.environ["APEX_TPU_TUNE_CACHE_DIR"] = old
    return p, cache_dir


def test_auto_pick_is_traced_and_clean(auto_plan):
    p, _ = auto_plan
    assert p.cost.wire_source == "traced"
    assert plan.verify_built(p.built) == []
    feasible = [r for r in p.table if r["feasible"]]
    infeasible = [r for r in p.table if not r["feasible"]]
    assert feasible and infeasible            # both fates in the table
    assert p.layout_id == feasible[0]["layout"]
    # parseable table render
    text = plan.format_table(p.table)
    assert text.splitlines()[0].startswith("rank")
    assert "infeasible:" in text
    # explain names the terms
    exp = p.explain()
    assert "compute floor" in exp and "exposed comm" in exp


def test_auto_trains_3_steps_bitwise_stable(auto_plan):
    """Two independent 3-step runs through the planner-emitted
    TrainerConfig produce bit-identical final states (the emitted
    package is deterministic end to end on the 8-device CPU mesh)."""
    p, _ = auto_plan

    def run():
        tr = p.build_trainer()
        state = tr.run(p.init_state(), p.batch_fn, 3)
        jax.block_until_ready(state)
        return state

    a, b = run(), run()
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_auto_plan_telemetry_statics(auto_plan):
    from apex_tpu import telemetry
    p, _ = auto_plan
    with telemetry.capture() as col:
        tr = p.build_trainer()
        state = tr.run(p.init_state(), p.batch_fn, 1)
        jax.block_until_ready(state)
        events = col.drain()
    picks = [e for e in events if e.name == "plan/pick"]
    assert picks, [e.name for e in events]
    meta = picks[-1].meta
    assert meta["layout"] == p.layout_id
    assert meta["step_s"] == pytest.approx(p.cost.step_s)


def test_cache_entries_planner_provenance(auto_plan):
    """Schema-v1 cache file, 'planner' provenance, and zero-re-measure
    resolution under APEX_TPU_TUNE=cache with the exact runtime key."""
    from apex_tpu.tune import cache as _cache, tuner
    p, cache_dir = auto_plan
    assert p.cache_entries and p.cache_written == len(p.cache_entries)
    files = list(cache_dir.glob("*.json"))
    assert len(files) == 1
    data = json.loads(files[0].read_text())
    assert data["version"] == _cache.SCHEMA_VERSION
    for e in p.cache_entries:
        stored = data["entries"][e["cache_key"]]
        assert stored["provenance"] == "planner"
        assert stored["config"] == e["entry"]["config"]
        assert stored["planned_s"] == pytest.approx(p.cost.step_s)
    # runtime resolution: cache policy returns the planner config with
    # its provenance, without measuring anything
    old_dir = os.environ.get("APEX_TPU_TUNE_CACHE_DIR")
    os.environ["APEX_TPU_TUNE_CACHE_DIR"] = str(cache_dir)
    tuner.reset()
    tuner.set_policy("cache")
    try:
        e = p.cache_entries[0]
        cfg, prov = tuner.resolve(e["op"], e["key"])
        assert prov == "planner"
        assert cfg == e["entry"]["config"]
    finally:
        tuner.set_policy(None)
        tuner.reset()
        if old_dir is None:
            os.environ.pop("APEX_TPU_TUNE_CACHE_DIR", None)
        else:
            os.environ["APEX_TPU_TUNE_CACHE_DIR"] = old_dir


def test_measured_tier_settles_the_pick(desc, monkeypatch):
    """validate="measure": measured candidates rank by MEASURED step
    time ahead of every unmeasured rival — the AMP arc: the analytic
    model shortlists the top_k, the device clock settles the pick.
    Deterministic here: the 'clock' is a canned table that inverts the
    modeled order (CI never times a wall clock)."""
    from apex_tpu.plan import search as _search
    cons = plan.Constraints(validate="measure", measure_force=True,
                            top_k=2, reduce_dtypes=(None,),
                            microbatches=(1,))
    ranked = plan.rank(plan.prune(
        plan.enumerate_candidates(N_DEV, desc, cons), desc,
        adapter=ADAPTER, constraints=cons))
    top2 = [v.layout.layout_id() for v in ranked if v.feasible][:2]
    times = {top2[0]: 2.0, top2[1]: 1.0}   # modeled runner-up measures 2x faster
    monkeypatch.setattr(
        _search, "_measure_built",
        lambda built, force=False: times[built.layout.layout_id()])
    p = plan.auto(ADAPTER, constraints=cons, write_cache=False,
                  compile_reference=False)
    assert p.layout_id == top2[1]
    assert p.measured_s == 1.0
    row = next(r for r in p.table if r["layout"] == top2[1])
    assert row["measured_ms"] == pytest.approx(1000.0)
    # without the measured tier the modeled leader would have won
    assert top2[0] != p.layout_id


# ---------------------------------------------------------------------------
# elastic replanning seam
# ---------------------------------------------------------------------------

def test_replanner_equal_shard_rerank():
    rp = plan.replanner(ADAPTER)
    out = rp(8, 4)
    assert out["equal_shard"] is True
    assert out["old"].startswith("dp8") or "8" in out["old"]
    assert plan.parse_layout_id(out["new"]).world == 4
    assert out["new_step_s"] > 0


def test_elastic_replan_emits_telemetry():
    """Elastic(replan=) logs the plan/replan static with the old/new
    pick on a membership change (exercised via the seam directly — the
    full snapshot round trip is tests/test_elastic.py's job)."""
    from apex_tpu import telemetry
    from apex_tpu.resilience.elastic import Elastic

    calls = []

    def fake_replan(old_world, new_world):
        calls.append((old_world, new_world))
        return {"old": f"dp{old_world}-zero2", "new":
                f"dp{new_world}-zero2", "old_step_s": 2e-3,
                "new_step_s": 3e-3, "equal_shard": True}

    ela = Elastic(optimizer=None, params=None, replan=fake_replan)
    with telemetry.capture() as col:
        ela._replan(2, 1, step=5)
        events = col.drain()
    assert calls == [(2, 1)]
    assert ela.last_replan["new"] == "dp1-zero2"
    reps = [e for e in events if e.name == "plan/replan"]
    assert len(reps) == 1
    assert reps[0].meta["from_world"] == 2
    assert reps[0].meta["to_world"] == 1
    assert reps[0].meta["old"] == "dp2-zero2"


def test_elastic_replan_failure_degrades_to_warning():
    from apex_tpu.resilience.elastic import Elastic

    def broken(old, new):
        raise RuntimeError("boom")

    ela = Elastic(optimizer=None, params=None, replan=broken)
    with pytest.warns(UserWarning, match="replan hook failed"):
        ela._replan(2, 1, step=0)
    assert ela.last_replan is None


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _cli(argv):
    from apex_tpu.plan.cli import main
    return main(argv)


GPT_ARGS = ["--vocab", "64", "--layers", "2", "--embed-dim", "64",
            "--heads", "4", "--batch", "16", "--seq-len", "64",
            "--no-compile"]


def test_cli_auto_table(capsys):
    rc = _cli(["auto", *GPT_ARGS, "--top-k", "1", "--no-cache"])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.splitlines()[0].startswith("rank")
    assert "pick: " in out and "lint.spmd clean" in out


def test_cli_auto_json(capsys):
    rc = _cli(["auto", *GPT_ARGS, "--top-k", "1", "--no-cache",
               "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["pick"]["id"] == doc["table"][0]["layout"]
    assert doc["wire_source"] == "traced"


def test_cli_explain(capsys):
    rc = _cli(["explain", "dp8-zero2", *GPT_ARGS])
    out = capsys.readouterr().out
    assert rc == 0
    assert "compute floor" in out and "reduce_scatter" in out


def test_cli_explain_infeasible_loud(capsys):
    rc = _cli(["explain", "dp1-tp8", *GPT_ARGS])
    err = capsys.readouterr().err
    assert rc == 1
    assert "not divisible" in err


def test_cli_explain_bad_id_usage(capsys):
    rc = _cli(["explain", "nonsense!!", *GPT_ARGS])
    assert rc == 2


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def test_named_mesh_drops_unit_axes_and_validates():
    from apex_tpu.parallel.mesh import named_mesh
    m = named_mesh([("data", 4), ("pipe", 1), ("model", 2)])
    assert m.axis_names == ("data", "model")
    assert m.devices.shape == (4, 2)
    with pytest.raises(ValueError, match="needs"):
        named_mesh([("data", 16)])
    with pytest.raises(ValueError, match="duplicate"):
        named_mesh([("data", 2), ("data", 2)])


def test_device_peaks_table():
    from apex_tpu.pyprof.roofline import device_hbm_bytes, device_peaks
    peaks = device_peaks()
    assert set(peaks) == {"flops", "bytes_per_s", "hbm_bytes", "ridge"}
    assert peaks["hbm_bytes"] > 0
    old = os.environ.get("APEX_TPU_HBM_BYTES")
    os.environ["APEX_TPU_HBM_BYTES"] = "12345"
    try:
        assert device_hbm_bytes() == 12345.0
    finally:
        if old is None:
            os.environ.pop("APEX_TPU_HBM_BYTES", None)
        else:
            os.environ["APEX_TPU_HBM_BYTES"] = old


def test_resolve_buckets_sane_range(desc):
    from apex_tpu.plan.search import resolve_buckets
    lay = resolve_buckets(desc, Layout(dp=8))
    assert lay.ddp_bucket is not None
    assert 1 << 20 <= lay.ddp_bucket <= 1 << 25
    # tp layouts sync with plain collectives: no bucket resolved
    assert resolve_buckets(desc, Layout(dp=4, tp=2)).ddp_bucket is None
    z = resolve_buckets(desc, Layout(dp=8, zero=2))
    assert z.zero_chunk is not None and z.ddp_bucket is None


def test_build_defers_param_materialization(monkeypatch):
    """The ROADMAP item-2 satellite: adapter.build touches ONLY avals —
    the concrete (seeded) param init is deferred to the winner's
    init_state, so the top_k trace tier never pays per-candidate full
    param inits."""
    ad = plan.GPTAdapter(vocab=64, layers=1, embed=32, heads=2,
                         batch=8, seq=32)
    calls = []
    orig = plan.GPTAdapter._dense_params

    def spy(self):
        calls.append(1)
        return orig(self)

    monkeypatch.setattr(plan.GPTAdapter, "_dense_params", spy)
    for lay in (Layout(dp=2), Layout(dp=2, zero=2, zero_chunk=256)):
        calls.clear()
        built = ad.build(lay, devices=jax.devices()[:2])
        assert not calls, \
            f"build({lay.layout_id()}) materialized concrete params"
        # every build-time aval is abstract, no device arrays
        for leaf in jax.tree_util.tree_leaves(built.state_avals):
            assert isinstance(leaf, jax.ShapeDtypeStruct), type(leaf)
        state = built.init_state()
        assert calls, "init_state() did not materialize"
        assert all(hasattr(l, "addressable_shards") or
                   isinstance(l, jax.Array)
                   for l in jax.tree_util.tree_leaves(state))
    # resnet rides the same contract (eval_shape'd init)
    rad = plan.ResNetAdapter(image=8, classes=4, batch=8)
    rbuilt = rad.build(Layout(dp=2), devices=jax.devices()[:2])
    for leaf in jax.tree_util.tree_leaves(rbuilt.state_avals):
        assert isinstance(leaf, jax.ShapeDtypeStruct), type(leaf)


# ---------------------------------------------------------------------------
# HBM honesty: microbatch-aware footprint + the lint.mem cross-check
# ---------------------------------------------------------------------------

def test_hbm_footprint_microbatch_moves_both_terms(desc):
    """Gradient accumulation carries a full grad-sized accumulator
    through the scan (grads x2) while only one chunk's activations are
    live at a time (act / microbatch) — both movements pinned, and the
    static analyzer confirms the direction on real builds (the
    validate-tier cross-check below)."""
    mb1 = plan.hbm_footprint(desc, Layout(dp=4))
    mb2 = plan.hbm_footprint(desc, Layout(dp=4, microbatch=2))
    assert mb2["grads"] == 2.0 * mb1["grads"]
    assert mb2["act"] == mb1["act"] / 2.0
    assert mb2["params"] == mb1["params"] and mb2["opt"] == mb1["opt"]


def test_validated_rows_carry_hbm_cross_check(auto_plan):
    """Every traced candidate's row reports the analyzer's verified
    peak next to the analytic estimate's drift from it — the HBM twin
    of the wire-drift column."""
    p, _ = auto_plan
    checked = [r for r in p.table if "hbm_verified_mib" in r]
    assert checked, "no validated row carries the mem cross-check"
    for r in checked:
        assert r["feasible"], r               # survivors, not demotions
        assert r["hbm_verified_mib"] > 0
        assert isinstance(r["hbm_error_pct"], float)
        # the formula's structural gap stays inside the demotion band
        assert r["hbm_error_pct"] > -plan.plan_hbm_tolerance_pct(), r
    # the pick itself was cross-checked
    assert "hbm_verified_mib" in p.table[0] or not p.table[0]["feasible"]
