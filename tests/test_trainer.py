"""apex_tpu.trainer — the compiled-step builder.

The load-bearing blocks are the parity tests: (1) jaxpr equality pinning
trainer-built steps to the pre-refactor hand-built train_lm/bench forms
(the builder must inject NOTHING into the traced program), and (2)
bitwise equality across dispatch modes (per_step / scan / unroll) and
in-flight depths — pipelining moves WHERE the host blocks, never what
the device computes. Plus the donation audit, the plugin seam, the
PrefetchLoader device_put staging, and the resilient_loop integration.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu  # noqa: F401  (jax shims)
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import telemetry, trace, trainer
from apex_tpu.trainer import (DonationReport, InflightWindow, Trainer,
                              TrainerConfig, build, stack_batches)


def _mesh():
    return Mesh(np.asarray(jax.devices()).reshape(-1), ("data",))


REP = P()


# a train_lm-shaped per-device step: params + opt state carried, tokens
# sharded over the mesh axis, grads pmean'd — small but structurally
# faithful (collective inside, multi-tree carry)
def per_device(params, opt, tokens, rng, mult):
    def loss_fn(p):
        return jnp.mean(p["w"][tokens].sum(-1)) * mult
    loss = loss_fn(params)
    g = jax.lax.pmean(jax.grad(loss_fn)(params), "data")
    new_p = jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, params, g)
    return new_p, opt + 1.0, jax.lax.pmean(loss, "data")


def tstep(state, batch):
    params, opt = state
    tokens, rng, mult = batch
    p, o, loss = per_device(params, opt, tokens, rng, mult)
    return (p, o), loss


def _state():
    return ({"w": jnp.arange(64.0).reshape(16, 4) / 64.0},
            jnp.zeros((3,)))


def _batch(i=0):
    tokens = jnp.asarray(
        np.random.default_rng([11, i]).integers(0, 16, (8, 2)), jnp.int32)
    return (tokens, jnp.zeros((2,), jnp.uint32), jnp.float32(1.0))


BATCH_SPEC = (P("data"), REP, REP)


def _build(config=None, plugins=(), state=None, batch=None):
    return build(tstep, state or _state(), batch or _batch(),
                 mesh=_mesh(), state_spec=REP, batch_spec=BATCH_SPEC,
                 config=config, plugins=plugins)


def _leaves(tree):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(tree)]


def _assert_tree_equal(a, b):
    for x, y in zip(_leaves(a), _leaves(b)):
        np.testing.assert_array_equal(x, y)


# ---------------------------------------------------------------------------
# jaxpr parity: trainer-built == pre-refactor hand-built
# ---------------------------------------------------------------------------

def test_per_step_jaxpr_identical_to_hand_built_train_lm_form():
    """The train_lm pattern before this PR: jit(shard_map(per_device,
    ...), donate_argnums=(0, 1)) over FIVE positional args. The trainer
    builds from the (state, batch) wrapper — the flattened jaxprs must
    be IDENTICAL (tuple repacking is structure, not computation)."""
    mesh = _mesh()
    hand = shard_map(per_device, mesh=mesh,
                     in_specs=(REP, REP, P("data"), REP, REP),
                     out_specs=(REP, REP, REP), check_vma=False)
    tr = _build()
    (params, opt), (tokens, rng, mult) = _state(), _batch()
    j_hand = str(jax.make_jaxpr(hand)(params, opt, tokens, rng, mult))
    j_tr = str(jax.make_jaxpr(tr.traced_fn)((params, opt),
                                            (tokens, rng, mult)))
    assert j_hand == j_tr


def test_scan_shared_jaxpr_identical_to_hand_built_bench_form():
    """The bench pattern before this PR: a hand-rolled lax.scan of k
    steps over one shared batch inside shard_map, returning losses[-1].
    trainer mode="scan", batch_mode="shared" must trace the same
    program."""
    mesh = _mesh()
    k = 3

    def multi_step(params, opt, batch):
        def body(carry, _):
            p, o = carry
            tokens, rng, mult = batch
            p, o, loss = per_device(p, o, tokens, rng, mult)
            return (p, o), loss
        (params, opt), losses = jax.lax.scan(
            body, (params, opt), None, length=k)
        return params, opt, losses[-1]

    hand = shard_map(multi_step, mesh=mesh,
                     in_specs=(REP, REP, BATCH_SPEC),
                     out_specs=(REP, REP, REP), check_vma=False)
    tr = _build(TrainerConfig(mode="scan", steps_per_call=k,
                              batch_mode="shared"))
    (params, opt), batch = _state(), _batch()
    j_hand = str(jax.make_jaxpr(hand)(params, opt, batch))
    j_tr = str(jax.make_jaxpr(tr.traced_fn)((params, opt), batch))
    assert j_hand == j_tr


def test_per_step_bitwise_identical_to_hand_built():
    tr = _build()
    state_h = _state()
    hand = jax.jit(shard_map(
        per_device, mesh=_mesh(),
        in_specs=(REP, REP, P("data"), REP, REP),
        out_specs=(REP, REP, REP), check_vma=False))
    state_t = _state()
    for i in range(4):
        tokens, rng, mult = _batch(i)
        p, o, loss_h = hand(state_h[0], state_h[1], tokens, rng, mult)
        state_h = (p, o)
        state_t, loss_t = tr.step(state_t, (tokens, rng, mult))
    tr.drain()
    _assert_tree_equal(state_h, state_t)
    np.testing.assert_array_equal(np.asarray(loss_h), np.asarray(loss_t))


# ---------------------------------------------------------------------------
# mode parity: per_step == scan == unroll, bitwise
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["scan", "unroll"])
def test_mode_bitwise_parity_stacked(mode):
    k = 4
    batches = [_batch(i) for i in range(k)]

    ref = _build(TrainerConfig(in_flight=1))
    state = _state()
    for b in batches:
        state, loss_ref = ref.step(state, b)
    ref.drain()

    stacked = stack_batches(batches)
    tr = build(tstep, _state(), stacked, mesh=_mesh(), state_spec=REP,
               batch_spec=(P(None, "data"), REP, REP),
               config=TrainerConfig(mode=mode, steps_per_call=k,
                                    in_flight=1))
    assert tr.steps_per_call == k
    state_k, loss_k = tr.step(_state(), stacked)
    tr.drain()
    _assert_tree_equal(state, state_k)
    # scan/unroll return the LAST step's aux (the bench convention)
    np.testing.assert_array_equal(np.asarray(loss_ref),
                                  np.asarray(loss_k))


def test_stacked_batch_length_mismatch_refused():
    """A stacked batch whose leading dim disagrees with steps_per_call
    would run a different number of steps than the trainer accounts
    for — refused loudly at build (the audit's trace) instead of
    silently desyncing snapshot step numbers."""
    stacked8 = stack_batches([_batch(i) for i in range(8)])
    with pytest.raises(ValueError, match="steps_per_call=4"):
        build(tstep, _state(), stacked8, mesh=_mesh(), state_spec=REP,
              batch_spec=(P(None, "data"), REP, REP),
              config=TrainerConfig(mode="scan", steps_per_call=4,
                                   in_flight=1))
    with pytest.raises(ValueError, match="leading dim"):
        build(tstep, _state(), stacked8,
              config=TrainerConfig(mode="unroll", steps_per_call=4,
                                   in_flight=1))


def test_donation_report_records_compile_seconds():
    rep = _build().donation
    assert rep.compile_s >= 0.0
    assert "compile_s" in rep.to_json()


def test_call_fn_exposes_wrapped_dispatch():
    telemetry.enable()
    try:
        plug = trainer.TelemetryPlugin(sync_every=1)
        tr = _build(TrainerConfig(in_flight=1), plugins=[plug])
        # the A/B baseline handle: the instrumented callable, outside
        # the window
        assert tr.call_fn is plug.instrument
        state, aux = tr.call_fn(_state(), _batch())
        jax.block_until_ready(aux)
    finally:
        telemetry.disable()
    k, b = 3, _batch(7)
    ref = _build(TrainerConfig(in_flight=1))
    state = _state()
    for _ in range(k):
        state, _ = ref.step(state, b)
    ref.drain()
    tr = _build(TrainerConfig(mode="scan", steps_per_call=k,
                              batch_mode="shared", in_flight=1))
    state_k, _ = tr.step(_state(), b)
    tr.drain()
    _assert_tree_equal(state, state_k)


# ---------------------------------------------------------------------------
# dispatch pipelining: bitwise at every depth, deferred delivery
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("depth", [1, 2, 4])
def test_in_flight_depth_is_bitwise_inert(depth):
    ref_state = _state()
    ref = _build(TrainerConfig(in_flight=1))
    for i in range(6):
        ref_state, _ = ref.step(ref_state, _batch(i))
    ref.drain()

    tr = _build(TrainerConfig(in_flight=depth))
    state = _state()
    for i in range(6):
        state, _ = tr.step(state, _batch(i))
    tr.drain()
    _assert_tree_equal(ref_state, state)


def test_window_defers_delivery_and_preserves_order():
    tr = _build(TrainerConfig(in_flight=3))
    seen = []
    tr.add_on_step(lambda i, aux: seen.append(i))
    state = _state()
    for i in range(5):
        state, _ = tr.step(state, _batch(i))
    # depth 3: after 5 dispatches only the first 3 retirements happened
    # (each push retires down to depth-1=2 pending)
    assert seen == [0, 1, 2]
    assert tr.pipeline_stats()["pending"] == 2
    tr.drain()
    assert seen == [0, 1, 2, 3, 4]
    assert tr.pipeline_stats()["pending"] == 0
    assert tr.pipeline_stats()["retired"] == 5


def test_inflight_window_unit():
    w = InflightWindow(2)
    assert w.push(0, jnp.float32(0)) == []
    assert [i for i, _ in w.push(1, jnp.float32(1))] == [0]
    assert [i for i, _ in w.push(2, jnp.float32(2))] == [1]
    assert [i for i, _ in w.drain()] == [2]
    assert len(w) == 0 and w.retired == 3


# ---------------------------------------------------------------------------
# donation audit
# ---------------------------------------------------------------------------

def test_donation_audit_all_aliased():
    tr = _build()
    rep = tr.donation
    assert isinstance(rep, DonationReport)
    assert rep.declared == len(jax.tree_util.tree_leaves(_state()))
    assert rep.aliased == rep.declared
    assert rep.refused == () and rep.ok
    assert "0 refused" in rep.summary()
    assert rep.to_json()["ok"] is True


def test_donation_audit_reports_refusal_loudly():
    # a carried leaf that changes dtype across the step cannot alias —
    # XLA refuses it and the audit must both record and warn
    def bad(state, batch):
        return {"w": (state["w"] + jnp.mean(batch)).astype(jnp.bfloat16),
                "v": state["v"] * 2.0}, jnp.mean(batch)

    s = {"w": jnp.ones((4,)), "v": jnp.zeros((2,))}
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        tr = build(bad, s, jnp.ones((3,)))
    rep = tr.donation
    assert not rep.ok and len(rep.refused) == 1
    assert "float32[4]" in rep.refused[0]
    assert any("donation audit" in str(w.message) for w in caught)


def test_donation_audit_counts_dead_code_drops():
    def dropper(state, batch):
        # 'unused' is read by nothing and its output slot is a fresh
        # constant: XLA dead-code-eliminates the parameter — a DROP
        # (nothing double-buffers), not a refusal
        return {"w": state["w"] + jnp.mean(batch),
                "unused": jnp.zeros((7,))}, jnp.mean(batch)

    s = {"w": jnp.ones((4,)), "unused": jnp.zeros((7,))}
    rep = build(dropper, s, jnp.ones((3,))).donation
    assert rep.ok and rep.refused == ()
    assert rep.declared == 2
    assert rep.aliased == 1 and rep.dropped == 1
    assert "dead-code-dropped" in rep.summary()


def test_donation_off_skips_audit():
    tr = _build(TrainerConfig(donate=False))
    assert tr.donation is None


def test_donation_audit_emits_telemetry_static():
    telemetry.enable()
    try:
        telemetry.get_collector().clear()
        _build()
        evs = [e for e in telemetry.get_collector().snapshot()
               if e.name == "trainer/donation_refused"]
        assert len(evs) == 1 and evs[0].value == 0.0
        assert evs[0].meta["ok"] is True
    finally:
        telemetry.disable()


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------

def test_config_validation():
    with pytest.raises(ValueError, match="mode"):
        TrainerConfig(mode="bogus")
    with pytest.raises(ValueError, match="batch_mode"):
        TrainerConfig(batch_mode="bogus")
    with pytest.raises(ValueError, match="in_flight"):
        TrainerConfig(in_flight=0)
    with pytest.raises(ValueError, match="steps_per_call"):
        TrainerConfig(mode="scan", steps_per_call=0)


# ---------------------------------------------------------------------------
# plugin seam
# ---------------------------------------------------------------------------

class _Recorder:
    def __init__(self):
        self.built = 0
        self.steps = []
        self.resumes = []

    def on_build(self, tr):
        self.built += 1

    def on_step(self, i, aux):
        self.steps.append(i)

    def on_resume(self, tr, step):
        self.resumes.append(step)


def test_plugin_hooks_fire_exactly_once_per_event():
    rec = _Recorder()
    tr = _build(TrainerConfig(in_flight=1), plugins=[rec])
    assert rec.built == 1
    state = _state()
    for i in range(3):
        state, _ = tr.step(state, _batch(i))
    tr.drain()
    assert rec.steps == [0, 1, 2]
    tr.notify_resume(10)
    assert rec.resumes == [10]
    assert tr.step_index == 10


def test_telemetry_plugin_instruments_dispatch():
    telemetry.enable()
    try:
        telemetry.get_collector().clear()
        plug = trainer.TelemetryPlugin(examples_per_step=8.0,
                                       sync_every=1)
        tr = _build(TrainerConfig(in_flight=1), plugins=[plug])
        state = _state()
        for i in range(3):
            state, _ = tr.step(state, _batch(i))
        tr.drain()
        jax.effects_barrier()
        names = {e.name for e in telemetry.get_collector().snapshot()}
        assert {"step/time_s", "step/dispatch_s", "step/device_wait_s",
                "step/examples_per_s", "trainer/in_flight"} <= names
    finally:
        telemetry.disable()


def test_telemetry_plugin_sync_every_defaults_to_window_depth():
    telemetry.enable()
    try:
        telemetry.get_collector().clear()
        plug = trainer.TelemetryPlugin()
        _build(TrainerConfig(in_flight=3), plugins=[plug])
        assert plug.instrument.sync_every == 3
        ev = telemetry.get_collector().last("trainer/in_flight")
        assert ev is not None and ev.value == 3.0
        assert ev.meta["sync_every"] == 3
    finally:
        telemetry.disable()


def test_amp_and_tune_plugins_record_statics():
    telemetry.enable()
    try:
        telemetry.get_collector().clear()
        _build(plugins=[trainer.AmpPlugin("O5"), trainer.TunePlugin()])
        col = telemetry.get_collector()
        amp_ev = col.last("trainer/amp_opt_level")
        assert amp_ev is not None and amp_ev.value == 5.0
        assert amp_ev.meta["opt_level"] == "O5"
        tune_ev = col.last("trainer/tune_policy")
        assert tune_ev is not None and tune_ev.meta["policy"] in (
            "off", "cache", "auto")
    finally:
        telemetry.disable()


def test_health_plugin_feeds_detector_from_retired_steps():
    telemetry.enable()
    try:
        telemetry.get_collector().clear()
        plug = trainer.HealthPlugin(loss_from_aux=float)
        tr = _build(TrainerConfig(in_flight=2), plugins=[plug])
        state = _state()
        for i in range(4):
            state, _ = tr.step(state, _batch(i))
        tr.drain()
        losses = [e for e in telemetry.get_collector().snapshot()
                  if e.name == "train/loss"]
        assert [e.step for e in losses] == [0, 1, 2, 3]
    finally:
        telemetry.disable()


def test_health_plugin_gates_per_step_signals_on_window_depth():
    """Under a pipelined window the collector's freshest health/*
    emissions describe a LATER dispatch than the retired loss — the
    plugin must consume them only at depth 1 (and warn once about the
    dropped signals otherwise); loss-only rules keep running either
    way."""
    import io
    telemetry.enable()
    try:
        out = io.StringIO()
        plug = trainer.HealthPlugin(loss_from_aux=float, out=out)
        _build(TrainerConfig(in_flight=1), plugins=[plug])
        assert plug._synced

        out2 = io.StringIO()
        plug2 = trainer.HealthPlugin(loss_from_aux=float, out=out2,
                                     overflow_total=lambda: 0.0)
        tr = _build(TrainerConfig(in_flight=3), plugins=[plug2])
        assert not plug2._synced
        assert "loss-based rules" in out2.getvalue()   # warned at build
        state = _state()
        for i in range(3):
            state, _ = tr.step(state, _batch(i))
        tr.drain()
        assert out2.getvalue().count("loss-based rules") == 1  # once
    finally:
        telemetry.disable()


# ---------------------------------------------------------------------------
# trainer/retire spans + reconciliation family contract
# ---------------------------------------------------------------------------

def test_retire_spans_emitted_and_balanced():
    telemetry.enable()
    trace.enable()
    try:
        telemetry.get_collector().clear()
        tr = _build(TrainerConfig(in_flight=2))
        state = _state()
        for i in range(3):
            state, _ = tr.step(state, _batch(i))
        tr.drain()
        rows = trace.span_rows(telemetry.get_collector().snapshot())
        retire = [r for r in rows if r["name"] == "span/trainer/retire"]
        assert len(retire) == 3
        assert [r["step"] for r in retire] == [0, 1, 2]
    finally:
        trace.disable()
        telemetry.disable()


def test_retire_family_never_billed_as_host_overhead():
    assert "trainer/retire" in trace.DEVICE_WAIT_FAMILIES
    assert "data/put" in trace.CONCURRENT_FAMILIES


# ---------------------------------------------------------------------------
# Trainer.run + PrefetchLoader double-buffered IO
# ---------------------------------------------------------------------------

def test_run_over_prefetch_loader_with_device_put_staging():
    from apex_tpu import runtime
    telemetry.enable()
    trace.enable()
    try:
        telemetry.get_collector().clear()
        batches = [_batch(i) for i in range(5)]
        loader = runtime.PrefetchLoader(
            iter(batches), depth=2,
            device_put=lambda b: (jax.device_put(b[0]), b[1], b[2]))
        tr = _build(TrainerConfig(in_flight=2))
        seen = []
        state = tr.run(_state(), loader, steps=5,
                       on_step=lambda i, aux: seen.append(i))
        assert seen == [0, 1, 2, 3, 4]

        ref = _build(TrainerConfig(in_flight=1))
        ref_state = _state()
        for b in batches:
            ref_state, _ = ref.step(ref_state, b)
        ref.drain()
        _assert_tree_equal(ref_state, state)

        stats = loader.stats()
        assert stats["consumed"] == 5
        assert stats["put_s"] > 0.0
        rows = trace.span_rows(telemetry.get_collector().snapshot())
        puts = [r for r in rows if r["name"] == "span/data/put"]
        assert len(puts) == 5
        loader.close()
    finally:
        trace.disable()
        telemetry.disable()


def test_prefetch_loader_put_s_zero_without_staging():
    from apex_tpu import runtime
    loader = runtime.PrefetchLoader(iter(range(3)))
    assert list(loader) == [0, 1, 2]
    assert loader.stats()["put_s"] == 0.0


# ---------------------------------------------------------------------------
# resilient_loop integration
# ---------------------------------------------------------------------------

def test_resilient_loop_through_trainer_snapshots_and_resumes(tmp_path):
    from apex_tpu import resilience

    def run(snap_dir, steps):
        tr = _build(TrainerConfig(in_flight=2))
        deliveries = []
        result = resilience.resilient_loop(
            None, _state(), _batch, steps=steps, trainer=tr,
            snapshot_dir=str(snap_dir), snapshot_every=2, resume="auto",
            on_step=lambda i, st, aux: deliveries.append(i))
        return result, deliveries

    res_a, deliv_a = run(tmp_path / "a", 6)
    assert res_a.step == 6 and not res_a.preempted
    assert deliv_a == [0, 1, 2, 3, 4, 5]

    # uninterrupted vs stop-at-4-then-continue: bitwise equal
    tr_b = _build(TrainerConfig(in_flight=2))
    from apex_tpu import resilience as res
    r1 = res.resilient_loop(None, _state(), _batch, steps=4,
                            trainer=tr_b, snapshot_dir=str(tmp_path / "b"),
                            snapshot_every=2, resume="auto")
    tr_c = _build(TrainerConfig(in_flight=2))
    r2 = res.resilient_loop(None, _state(), _batch, steps=6,
                            trainer=tr_c, snapshot_dir=str(tmp_path / "b"),
                            snapshot_every=2, resume="auto")
    assert r2.resumed_from is not None
    assert tr_c.step_index == 6
    _assert_tree_equal(res_a.state, r2.state)


def test_resilient_loop_requires_step_fn_or_trainer():
    from apex_tpu import resilience
    with pytest.raises(ValueError, match="step_fn is required"):
        resilience.resilient_loop(None, _state(), _batch, steps=1)


def test_resilient_loop_rejects_misaligned_scan_cadence(tmp_path):
    """A scan trainer only surfaces dispatch-boundary step values: a
    non-k-aligned snapshot cadence (or a step-targeted fault between
    boundaries) would silently misfire — the loop must refuse loudly."""
    from apex_tpu import resilience
    from apex_tpu.resilience.faults import FaultInjector
    k = 4
    batches = [_batch(i) for i in range(k)]
    stacked = stack_batches(batches)
    tr = build(tstep, _state(), stacked, mesh=_mesh(), state_spec=REP,
               batch_spec=(P(None, "data"), REP, REP),
               config=TrainerConfig(mode="scan", steps_per_call=k,
                                    in_flight=1))
    with pytest.raises(ValueError, match="not a multiple"):
        resilience.resilient_loop(
            None, _state(), lambda i: stacked, steps=8, trainer=tr,
            snapshot_dir=str(tmp_path / "s"), snapshot_every=3)
    with pytest.raises(ValueError, match="never\\s+observes"):
        resilience.resilient_loop(
            None, _state(), lambda i: stacked, steps=8, trainer=tr,
            injector=FaultInjector("nan_grad", step=3))
    # aligned cadence + boundary-targeted fault are accepted
    result = resilience.resilient_loop(
        None, _state(), lambda i: stacked, steps=8, trainer=tr,
        snapshot_dir=str(tmp_path / "ok"), snapshot_every=4,
        injector=FaultInjector("nan_grad", step=4))
    assert result.step == 8


def test_resilient_loop_drains_before_preemption_save(tmp_path):
    from apex_tpu import resilience
    tr = _build(TrainerConfig(in_flight=4))
    # deadline already expired: the loop must drain + final-snapshot and
    # return the exit-75 contract without executing further steps
    result = resilience.resilient_loop(
        None, _state(), _batch, steps=50, trainer=tr,
        snapshot_dir=str(tmp_path / "snap"), snapshot_every=0,
        resume="none", deadline_s=0.0)
    assert result.preempted and result.exit_code == 75
    assert result.final_snapshot_ok
    assert tr.pipeline_stats()["pending"] == 0


# ---------------------------------------------------------------------------
# builder misc
# ---------------------------------------------------------------------------

def test_build_without_mesh_plain_jit():
    def pstep(s, b):
        return jax.tree_util.tree_map(lambda a: a + jnp.mean(b), s), \
            jnp.mean(b)
    tr = build(pstep, {"w": jnp.ones((4,))}, jnp.ones((2,)))
    st, aux = tr.step({"w": jnp.ones((4,))}, jnp.full((2,), 2.0))
    tr.drain()
    np.testing.assert_allclose(np.asarray(st["w"]), 3.0)
    assert float(aux) == 2.0


def test_stack_batches():
    stacked = stack_batches([_batch(0), _batch(1)])
    assert stacked[0].shape == (2, 8, 2)
    assert stacked[1].shape == (2, 2)
    np.testing.assert_array_equal(np.asarray(stacked[0][1]),
                                  np.asarray(_batch(1)[0]))


def test_build_accepts_avals():
    (params, opt), batch = _state(), _batch()
    avals = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
        ((params, opt), batch))
    tr = build(tstep, avals[0], avals[1], mesh=_mesh(), state_spec=REP,
               batch_spec=BATCH_SPEC)
    assert tr.donation is not None and tr.donation.ok
    state, _ = tr.step((params, opt), batch)
    tr.drain()
