"""Paged KV-cache unit tests: allocator semantics (atomicity, LIFO
determinism, double-free), page write/gather round-trips, and the
dead-slot drop contract the engine's static shapes depend on."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.serve import kvcache
from apex_tpu.serve.kvcache import (KVPool, PageAllocator, PoolFullError,
                                    SlotPages, create_pool, gather_pages,
                                    write_prompt, write_token)


class TestPageAllocator:
    def test_alloc_free_roundtrip(self):
        a = PageAllocator(8)
        assert a.free_pages == 8 and a.used_pages == 0
        got = a.alloc(3)
        assert len(got) == 3 and len(set(got)) == 3
        assert a.free_pages == 5 and a.used_pages == 3
        a.free(got)
        assert a.free_pages == 8

    def test_lifo_determinism(self):
        """Most recently freed pages come back first — the property the
        bitwise replay tests rely on (identical schedules allocate
        identical page ids)."""
        a = PageAllocator(8)
        first = a.alloc(2)
        a.free(first)
        assert a.alloc(2) == list(reversed(first))

    def test_alloc_atomic_on_exhaustion(self):
        """A too-large request takes NOTHING — a partial grant would
        leak pages when admission aborts."""
        a = PageAllocator(4)
        a.alloc(3)
        before = a.free_pages
        with pytest.raises(PoolFullError):
            a.alloc(2)
        assert a.free_pages == before

    def test_alloc_zero_and_negative(self):
        a = PageAllocator(2)
        assert a.alloc(0) == []
        with pytest.raises(ValueError):
            a.alloc(-1)

    def test_double_free_raises(self):
        a = PageAllocator(4)
        got = a.alloc(1)
        a.free(got)
        with pytest.raises(ValueError, match="double free"):
            a.free(got)

    def test_out_of_range_free_raises(self):
        a = PageAllocator(4)
        with pytest.raises(ValueError, match="out of range"):
            a.free([4])

    def test_invalid_pool_size(self):
        with pytest.raises(ValueError):
            PageAllocator(0)


class TestPool:
    def test_create_pool_shapes(self):
        pool = create_pool(layers=3, num_pages=6, heads=2, page=4,
                           head_dim=8, dtype=jnp.bfloat16)
        assert isinstance(pool, KVPool)
        assert pool.layers == 3
        assert pool.num_pages == 6
        assert pool.page == 4
        assert pool.k[0].shape == (6, 2, 4, 8)
        assert pool.k[0].dtype == jnp.bfloat16
        assert pool.bytes() == 3 * 2 * 6 * 2 * 4 * 8 * 2

    def test_write_token_and_dead_slot_drop(self):
        pool = create_pool(layers=1, num_pages=4, heads=2, page=4,
                           head_dim=8)
        k = jnp.ones((2, 2, 8))          # (B, H, D), B=2
        v = 2.0 * jnp.ones((2, 2, 8))
        # slot 0 writes page 1 row 2; slot 1 is dead (id == num_pages)
        page_ids = jnp.array([1, 4], jnp.int32)
        offsets = jnp.array([2, 0], jnp.int32)
        kp, vp = write_token(pool.k[0], pool.v[0], k, v, page_ids,
                             offsets)
        assert bool(jnp.all(kp[1, :, 2, :] == 1.0))
        assert bool(jnp.all(vp[1, :, 2, :] == 2.0))
        # everything else (including the dead slot's would-be target)
        # stays zero
        mask = jnp.ones_like(kp, bool).at[1, :, 2, :].set(False)
        assert bool(jnp.all(jnp.where(mask, kp, 0) == 0))
        assert bool(jnp.all(jnp.where(mask, vp, 0) == 0))

    def test_write_prompt_gather_roundtrip(self):
        """A dense (H, S, D) prompt cache scattered into pages gathers
        back exactly, rows past `length` dropped."""
        h, s_max, d, page = 2, 12, 8, 4
        key = jax.random.PRNGKey(0)
        k = jax.random.normal(key, (h, s_max, d))
        v = jax.random.normal(jax.random.fold_in(key, 1), (h, s_max, d))
        pool = create_pool(layers=1, num_pages=5, heads=h, page=page,
                           head_dim=d)
        block_row = jnp.array([3, 1, 0], jnp.int32)     # 3 pages
        length = 9                                      # partial page 3
        kp, vp = write_prompt(pool.k[0], pool.v[0], k, v, block_row,
                              jnp.int32(length))
        gk = gather_pages(kp, block_row[None])[0]       # (H, 12, D)
        gv = gather_pages(vp, block_row[None])[0]
        np.testing.assert_array_equal(np.asarray(gk[:, :length]),
                                      np.asarray(k[:, :length]))
        np.testing.assert_array_equal(np.asarray(gv[:, :length]),
                                      np.asarray(v[:, :length]))
        # padding rows were dropped, not written
        assert bool(jnp.all(gk[:, length:] == 0))
        # page 2 (never in the block row) untouched
        assert bool(jnp.all(kp[2] == 0))

    def test_gather_pages_order(self):
        """Token t of a slot lands at row t — page lists are
        position-ordered, masking is a plain col < seq_len."""
        page, d = 4, 8
        pool_k = jnp.arange(3 * 1 * page * d, dtype=jnp.float32).reshape(
            3, 1, page, d)
        bt = jnp.array([[2, 0]], jnp.int32)
        g = gather_pages(pool_k, bt)
        assert g.shape == (1, 1, 2 * page, d)
        np.testing.assert_array_equal(np.asarray(g[0, 0, :page]),
                                      np.asarray(pool_k[2, 0]))
        np.testing.assert_array_equal(np.asarray(g[0, 0, page:]),
                                      np.asarray(pool_k[0, 0]))

    def test_slot_pages_capacity(self):
        sp = SlotPages(pages=[1, 2, 3], tokens=5)
        assert sp.capacity(16) == 48
