"""Convergence gate, CPU tier (VERDICT r3 next #7): the stack must
OPTIMIZE — several-hundred-step memorization on fixed synthetic data —
not merely step 20 times like the L1 trajectory tier. Full-size on-chip
runs live in ``benchmarks/convergence_gate.py`` (endpoints recorded in
BASELINE.md); this runs its ``--quick`` tier: ResNet-18 to 100% train
accuracy and GPTTiny to near-zero loss at O1 and O5."""

import json
import os
import subprocess
import sys

import pytest

GATE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks", "convergence_gate.py")


# Integration tier (PR 2): ~350 s of several-hundred-step training — 40%
# of the whole 870 s tier-1 budget for one test, which no longer fits now
# that the suite has grown (912 s measured). Rides `-m slow` like the
# other heavy integration modules (PR 1 tiering); ci/gate.sh --full runs
# the suite WITHOUT the slow filter, so the gate still executes there,
# and the on-chip endpoints in BASELINE.md are unaffected.
@pytest.mark.slow
def test_quick_convergence_gate():
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    try:
        proc = subprocess.run(
            [sys.executable, GATE, "--quick"], env=env,
            capture_output=True, text=True, timeout=1200)
    except OSError as e:
        pytest.skip(f"cannot spawn subprocess: {e}")

    recs = [json.loads(l) for l in proc.stdout.splitlines()
            if l.startswith("{")]
    assert proc.returncode == 0, (
        f"gate failed (rc={proc.returncode}):\n{proc.stdout}\n"
        f"{proc.stderr[-2000:]}")
    assert len(recs) == 8, recs  # 4 configs (MoE, rel-bias) x 2 levels
    for r in recs:
        assert r["ok"], r
        assert r["loss_last10_mean"] < r["loss_thresh"], r
    accs = [r["final_train_acc"] for r in recs
            if "final_train_acc" in r]
    assert accs and all(a >= 0.99 for a in accs), recs
