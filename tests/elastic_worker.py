"""Subprocess member for the elastic node-loss tests and the CI gate's
elastic smoke (stage 14): one fleet member running a tiny ZeRO
(``DistributedFusedAdam``) train at ``world = APEX_TPU_WORLD`` on a
virtual CPU mesh, driven by ``resilient_loop`` with an
``elastic=Elastic(opt, params)`` resume seam — so a relaunch at a
DIFFERENT world size restores through the deterministic re-shard
(``resilience/reshard`` marker in the telemetry JSONL).

Spawned by ``python -m apex_tpu.parallel.multiproc --elastic N -- ...``
(which sets APEX_TPU_WORLD/APEX_TPU_RANK/APEX_TPU_RENDEZVOUS and
substitutes {rank}/{world} in the args), or standalone with the env
set by hand for the fresh-run baseline.

Usage: python elastic_worker.py --steps N --snap DIR --out OUT.npz
         [--telemetry PATH] [--resume auto|none] [--snap-every K]
         [--step-ms MS] [--chunk N]
         [--supervise] [--sup-window W] [--sup-threshold X]
         [--sup-hysteresis H] [--sup-cooldown C] [--sup-evict-after E]

``--supervise`` runs the degradation supervisor
(apex_tpu.resilience.rebalance) over the rendezvous profiles: a
sustained straggler (e.g. the ``slow_node`` fault) is detected, the
fleet rebalances to weighted shards (gather-verified, persisted), and
a persisting straggler self-evicts through the exit-75 cooperative
leave — the CI rebalance smoke drives exactly this path.

Writes OUT.npz with the (step, loss) trajectory observed by THIS
process, the final replicated params, and the CANONICAL (unsharded,
world-independent) fp32 master + Adam moments — so runs at different
world sizes compare directly.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, required=True)
    ap.add_argument("--snap", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--telemetry", default=None)
    ap.add_argument("--resume", default="auto", choices=["auto", "none"])
    ap.add_argument("--snap-every", type=int, default=2)
    ap.add_argument("--step-ms", type=float, default=0.0,
                    help="host-side sleep per step — makes the node-loss "
                    "window deterministic in the supervisor tests")
    ap.add_argument("--chunk", type=int, default=256)
    ap.add_argument("--keep-last", type=int, default=None,
                    help="snapshot retention (default: manager's) — the "
                    "rebalance smoke keeps everything so the weighted "
                    "generation survives for inspection")
    ap.add_argument("--supervise", action="store_true",
                    help="run the degradation supervisor (needs the "
                    "rendezvous env from multiproc --elastic)")
    ap.add_argument("--sup-window", type=int, default=3)
    ap.add_argument("--sup-threshold", type=float, default=1.5)
    ap.add_argument("--sup-hysteresis", type=int, default=2)
    ap.add_argument("--sup-cooldown", type=int, default=4)
    ap.add_argument("--sup-evict-after", type=int, default=4)
    args = ap.parse_args()

    from apex_tpu import parallel, resilience, telemetry
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.parallel import multiproc
    from jax import shard_map

    world, rank = multiproc.elastic_world()
    if jax.device_count() < world:
        print(f"elastic_worker: {jax.device_count()} devices < world "
              f"{world}", file=sys.stderr)
        sys.exit(2)

    rdzv = None
    rdzv_dir = os.environ.get(multiproc.ENV_RENDEZVOUS)
    if rdzv_dir:
        # join barrier: the fleet agrees on membership before the mesh
        # forms at this world size
        rdzv = multiproc.Rendezvous(rdzv_dir, member=f"{rank:04d}")
        rdzv.announce()
        rdzv.wait_world(world, timeout_s=60)

    if args.telemetry:
        telemetry.enable()

    mesh = parallel.reform_mesh(world)
    key = jax.random.PRNGKey(7)
    ks = jax.random.split(key, 3)
    params = {"w1": jax.random.normal(ks[0], (37, 11)),
              "w2": jax.random.normal(ks[1], (501,)),
              "b": jax.random.normal(ks[2], (3,))}
    opt = DistributedFusedAdam(lr=0.05, shard_count=world,
                               chunk_elements=args.chunk)
    zstate = opt.init(params)
    layout = opt.layout_fingerprint(params)
    specs = opt.state_pspec()

    def loss_fn(p, x):
        return sum(jnp.mean((leaf * x - 0.5) ** 2)
                   for leaf in jax.tree_util.tree_leaves(p))

    sharded_step = shard_map(
        opt.step, mesh=mesh, in_specs=(P(), P(), specs),
        out_specs=(P(), specs), check_vma=False)

    @jax.jit
    def train_step(st, x):
        p, z = st
        loss, g = jax.value_and_grad(loss_fn)(p, x)
        new_p, new_z = sharded_step(g, p, z)
        return (new_p, new_z), loss

    def make_x(i):
        # addressable by step index: every member (and every resumed
        # world) regenerates the identical batch stream
        return jnp.asarray(
            np.random.default_rng([11, i]).uniform(0.5, 1.5), jnp.float32)

    losses = []

    def step_fn(st, x, i):
        if args.step_ms:
            time.sleep(args.step_ms / 1e3)
        return train_step(st, x)

    supervisor = None
    if args.supervise:
        if rdzv is None:
            print("elastic_worker: --supervise needs the rendezvous "
                  "env (multiproc --elastic --rendezvous DIR)",
                  file=sys.stderr)
            sys.exit(2)
        supervisor = resilience.DegradationSupervisor(
            rdzv, rank=rank,
            window=args.sup_window, threshold=args.sup_threshold,
            hysteresis=args.sup_hysteresis, cooldown=args.sup_cooldown,
            evict_after=args.sup_evict_after)

    mgr_kwargs = {}
    if args.keep_last is not None:
        mgr_kwargs["keep_last"] = args.keep_last
    result = resilience.resilient_loop(
        step_fn, (params, zstate), make_x, steps=args.steps,
        snapshot_dir=args.snap, snapshot_every=args.snap_every,
        resume=args.resume, layout=layout,
        elastic=resilience.Elastic(opt, params),
        supervisor=supervisor,
        on_step=lambda i, st, loss: losses.append((i, float(loss))),
        **mgr_kwargs)

    if result.preempted and rdzv is not None:
        rdzv.leave()   # cooperative departure: next world() excludes us

    if args.telemetry:
        telemetry.write_jsonl(args.telemetry)

    final_params, final_z = result.state
    src_spec = resilience.elastic.spec_for(params, layout)
    out = {
        "losses": np.asarray(losses, np.float64),
        "world": np.asarray(world),
        "resumed_from": np.asarray(
            -1 if result.resumed_from is None else result.resumed_from),
        # canonical (world-independent) sharded-state views
        "master": resilience.elastic.unshard(
            np.asarray(final_z.master), src_spec),
        "exp_avg": resilience.elastic.unshard(
            np.asarray(final_z.exp_avg), src_spec),
        "exp_avg_sq": resilience.elastic.unshard(
            np.asarray(final_z.exp_avg_sq), src_spec),
    }
    for i, leaf in enumerate(jax.tree_util.tree_leaves(final_params)):
        out[f"param_{i}"] = np.asarray(leaf)
    np.savez(args.out, **out)
    print(f"done: rank {rank}/{world} step {result.step} "
          f"resumed_from={result.resumed_from} "
          f"preempted={result.preempted}")
    sys.exit(result.exit_code)


if __name__ == "__main__":
    main()
