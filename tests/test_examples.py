"""Example-trainer smoke tests: every shipped trainer must run end to end
on the 8-device virtual mesh with tiny configs — the analog of the
reference's L1 'the examples are the integration tests' stance
(tests/L1/common/main_amp.py IS examples/imagenet instrumented)."""

import importlib.util
import os
import sys

import pytest

# Integration tier (PR 1): this whole module rides `-m slow` — full example-trainer smokes (minutes each).
# Tier-1 (-m 'not slow') must fit the 870 s gate budget; the fast cross-
# sections of this stack stay in tier-1 via test_zero/test_parallel/
# test_param_groups/test_attention and the ci/gate.sh dryrun parts.
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(relpath, argv):
    path = os.path.join(REPO, relpath)
    spec = importlib.util.spec_from_file_location("example_main", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main(argv)


def test_imagenet_example_smoke():
    img_s = _run("examples/imagenet/main_amp.py",
                 ["--arch", "resnet18", "--batch-size", "16",
                  "--image-size", "32", "--num-classes", "10",
                  "--steps", "3", "--warmup-steps", "1", "--sync-bn"])
    assert img_s > 0


def test_imagenet_example_host_pipeline(tmp_path):
    ck = str(tmp_path / "ck.npz")
    _run("examples/imagenet/main_amp.py",
         ["--arch", "resnet18", "--batch-size", "16",
          "--image-size", "32", "--num-classes", "10",
          "--steps", "3", "--warmup-steps", "1",
          "--data-pipeline", "host", "--checkpoint-path", ck])
    _run("examples/imagenet/main_amp.py",
         ["--arch", "resnet18", "--batch-size", "16",
          "--image-size", "32", "--num-classes", "10",
          "--steps", "2", "--warmup-steps", "0", "--resume", ck])


def test_dcgan_example_smoke():
    _run("examples/dcgan/main_amp.py",
         ["--steps", "2", "--batch-size", "8"])


def test_bert_example_smoke():
    _run("examples/bert/pretrain_lamb.py", ["--steps", "2"])


def test_bert_example_zero_smoke():
    _run("examples/bert/pretrain_lamb.py", ["--steps", "2", "--zero"])


@pytest.mark.parametrize("sp", [None, "ring", "ulysses"])
def test_gpt_example_smoke(sp):
    argv = ["--vocab", "512", "--layers", "2", "--embed-dim", "128",
            "--heads", "8", "--batch-size", "1", "--seq-len", "128",
            "--steps", "3", "--warmup-steps", "1"]
    if sp:
        argv += ["--seq-parallel", sp]
    tok_s = _run("examples/gpt/train_lm.py", argv)
    assert tok_s > 0


@pytest.mark.parametrize("sp", [None, "ring"])
def test_gpt_example_scan_mode_smoke(sp):
    """--scan N: dispatch-proof mode (N steps per jitted scan dispatch,
    on-device token generation) must train on both the dense and the
    seq-parallel paths."""
    argv = ["--vocab", "512", "--layers", "2", "--embed-dim", "128",
            "--heads", "8", "--batch-size", "1", "--seq-len", "128",
            "--steps", "4", "--scan", "2"]
    if sp:
        argv += ["--seq-parallel", sp]
    tok_s = _run("examples/gpt/train_lm.py", argv)
    assert tok_s > 0


def test_gpt_example_moe_smoke():
    """--moe N: alternating Switch-MoE blocks with the balance +
    router-z losses in the objective, scan dispatch mode."""
    tok_s = _run("examples/gpt/train_lm.py",
                 ["--vocab", "512", "--layers", "2", "--embed-dim", "128",
                  "--heads", "8", "--batch-size", "1", "--seq-len", "128",
                  "--steps", "4", "--scan", "2", "--moe", "4"])
    assert tok_s > 0


def test_gpt_example_generate_smoke():
    """--generate: KV-cache decode path (prefill + scanned 1-token
    steps) produces a throughput number."""
    tok_s = _run("examples/gpt/train_lm.py",
                 ["--vocab", "128", "--layers", "1", "--embed-dim", "64",
                  "--heads", "4", "--batch-size", "1",
                  "--prompt-len", "8", "--generate", "8"])
    assert tok_s > 0
