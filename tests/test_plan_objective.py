"""plan objective=p99_decode tests (ISSUE 17): the serving objective
ranks by modeled per-token decode latency (memory-bound roofline — the
axis algebra flips vs training throughput), and the flip is pinned on a
shape where the two objectives disagree."""

import pytest

from apex_tpu import plan as _plan
from apex_tpu.plan import cost as _cost
from apex_tpu.plan import get_adapter
from apex_tpu.plan.search import (Constraints, enumerate_candidates,
                                  prune, rank)

# a serving-sized shape: big vocab + embed makes decode weight-read
# bound, so tensor parallelism (divides the weight bytes each token
# must stream) beats pure data parallelism (which only helps batch
# throughput) on the decode clock
SHAPE = dict(vocab=32000, layers=8, embed=1024, heads=16, batch=16,
             seq=512)


@pytest.fixture(scope="module")
def verdicts():
    ad = get_adapter("gpt", **SHAPE)
    desc = ad.describe(compile_reference=False)
    cons = Constraints(validate="none", hbm_bytes=float(1 << 40))
    cands = enumerate_candidates(8, desc, cons)
    return prune(cands, desc, adapter=ad, constraints=cons), desc


def test_objective_flips_the_pick(verdicts):
    """The acceptance pin: at this shape on 8 devices the two
    objectives choose DIFFERENT layouts — throughput wants data
    parallelism, p99_decode wants the weight stream divided."""
    vs, _ = verdicts
    thr = [v for v in rank(vs, "throughput") if v.feasible]
    dec = [v for v in rank(vs, "p99_decode") if v.feasible]
    assert thr and dec
    thr_pick = thr[0].layout.layout_id()
    dec_pick = dec[0].layout.layout_id()
    assert thr_pick != dec_pick
    assert thr[0].layout.dp == 8          # pure data parallel wins tput
    assert dec[0].layout.tp > 1           # decode wants tensor parallel


def test_rank_orders_by_decode_latency(verdicts):
    vs, _ = verdicts
    dec = [v for v in rank(vs, "p99_decode") if v.feasible]
    times = [v.decode_s for v in dec]
    assert all(t is not None for t in times)
    assert times == sorted(times)


def test_decode_model_monotone_in_tp(verdicts):
    """More tensor parallelism streams fewer weight bytes per token —
    decode_step_s must fall from tp=1 to tp=2 at fixed dp=1 (the
    memory-bound regime this shape sits in)."""
    vs, desc = verdicts
    by_id = {v.layout.layout_id(): v for v in vs if v.feasible}
    t1 = _cost.decode_step_s(desc, by_id["dp1-tp8"].layout)
    t0 = _cost.decode_step_s(desc, by_id["dp8"].layout)
    assert t1 < t0


def test_verdict_row_carries_decode_ms(verdicts):
    vs, _ = verdicts
    row = next(v for v in vs if v.feasible).row()
    assert "decode_ms" in row
    assert row["decode_ms"] is not None and row["decode_ms"] > 0


def test_constraints_validates_objective():
    assert Constraints(objective="p99_decode").objective == "p99_decode"
    assert Constraints().objective == "throughput"
    with pytest.raises(ValueError, match="objective"):
        Constraints(objective="latency")


def test_auto_honors_objective():
    """plan.auto end-to-end (validate='none' keeps it analytic): the
    emitted pick follows the constraint's objective."""
    ad = get_adapter("gpt", **SHAPE)
    picks = {}
    for obj in ("throughput", "p99_decode"):
        p = _plan.auto(ad, n_devices=8,
                       constraints=Constraints(
                           validate="none", objective=obj,
                           hbm_bytes=float(1 << 40)),
                       write_cache=False, compile_reference=False)
        picks[obj] = p.layout_id
    assert picks["throughput"] != picks["p99_decode"]


def test_cli_objective_flag():
    from apex_tpu.plan.cli import build_parser
    args = build_parser().parse_args(
        ["auto", "--objective", "p99_decode", "--validate", "none"])
    assert args.objective == "p99_decode"
    with pytest.raises(SystemExit):
        build_parser().parse_args(["auto", "--objective", "qps"])
