"""apex_tpu.telemetry.health: trace-safe grad stats (global/per-layer,
bounded cardinality), non-finite provenance + overflow attribution
through the amp optimizer, divergence detection (live + offline + CLI
exit codes), the DDP/ZeRO per-bucket grad-norm producers, and the PR's
satellites: rotation-following export.load, Collector.dropped
surfacing, concurrent-producer safety, cost-analysis key spellings."""

import json
import math
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import telemetry
from apex_tpu.telemetry import events as tel_events
from apex_tpu.telemetry import export as tel_export
from apex_tpu.telemetry import health
from apex_tpu.telemetry.cli import main as cli_main


@pytest.fixture
def col():
    """Fresh collector with HEALTH (and telemetry) enabled; all global
    flags restored afterwards."""
    prev = health._health_enabled
    with tel_events.capture() as c:
        health.enable()
        try:
            yield c
        finally:
            if not prev:
                health.disable()


def _by_name(col, name):
    return [e for e in col.snapshot() if e.name == name]


def _names(col):
    return {e.name for e in col.snapshot()}


# ---------------------------------------------------------------------------
# enable semantics / disabled-is-free
# ---------------------------------------------------------------------------

def test_disabled_grad_stats_is_noop():
    telemetry.get_collector().clear()
    assert not health.enabled()
    health.grad_stats({"a": jnp.ones((4,))})
    health.attribute_overflow(jnp.array(True), {"a": jnp.ones((4,))})
    assert len(telemetry.get_collector()) == 0


def test_health_enable_implies_telemetry():
    prev_t, prev_h = telemetry.enabled(), health._health_enabled
    try:
        telemetry.disable()
        health.disable()
        health.enable()
        assert telemetry.enabled() and health.enabled()
        # base telemetry off -> health off too (events would be dropped)
        telemetry.disable()
        assert not health.enabled()
    finally:
        health.disable()
        if prev_h:
            health.enable()
        elif prev_t:
            telemetry.enable()
        else:
            telemetry.disable()


def test_jaxpr_identical_when_health_disabled():
    """The acceptance property: with health disabled, the traced step is
    bit-identical to one with no health hooks at all."""
    from apex_tpu import amp, optimizers

    inner = optimizers.FusedSGD(lr=0.1)
    _, aopt = amp.initialize(None, inner, opt_level="O2", verbosity=0)
    params = {"a": jnp.ones((4, 4), jnp.float16)}
    state = aopt.init(params)

    def step(g, p, s):
        return aopt.step(g, p, s)

    def with_hook(g, p, s):
        out = aopt.step(g, p, s)
        health.grad_stats(g, params=p)      # disabled -> must trace nothing
        return out

    assert not health.enabled()
    j_plain = str(jax.make_jaxpr(step)(params, params, state))
    j_hooked = str(jax.make_jaxpr(with_hook)(params, params, state))
    assert j_plain == j_hooked
    assert "debug_callback" not in j_hooked


def test_jaxpr_changes_when_health_enabled(col):
    def f(g):
        health.grad_stats(g)
        return g

    j = str(jax.make_jaxpr(f)({"a": jnp.ones((4,))}))
    assert "debug_callback" in j


# ---------------------------------------------------------------------------
# grad_stats
# ---------------------------------------------------------------------------

def test_grad_stats_global_values(col):
    g = {"emb": jnp.full((3,), 2.0), "head": jnp.full((4,), 1.0)}
    p = {"emb": jnp.full((3,), 4.0), "head": jnp.full((4,), 3.0)}
    u = {"emb": jnp.full((3,), 0.4), "head": jnp.full((4,), 0.3)}
    health.grad_stats(g, params=p, updates=u, step=2)
    jax.effects_barrier()
    (gn,) = _by_name(col, "health/grad_norm")
    assert gn.value == pytest.approx(math.sqrt(3 * 4 + 4 * 1))
    assert gn.step == 2
    (wn,) = _by_name(col, "health/weight_norm")
    assert wn.value == pytest.approx(math.sqrt(3 * 16 + 4 * 9))
    (ur,) = _by_name(col, "health/update_ratio")
    assert ur.value == pytest.approx(
        math.sqrt(3 * 0.16 + 4 * 0.09) / wn.value)
    (nf,) = _by_name(col, "health/nonfinite")
    assert nf.value == 0.0
    # per-layer series for both groups (2 <= default top_k)
    assert _by_name(col, "health/layer/emb/grad_norm")[0].value == \
        pytest.approx(math.sqrt(12))
    assert _by_name(col, "health/layer/head/grad_norm")[0].value == \
        pytest.approx(2.0)


def test_grad_stats_bounded_cardinality_topk_other(col):
    # 5 groups, top_k=2: the two largest by norm get named series, the
    # remaining three fold into layer/(rest)
    g = {f"g{i}": jnp.full((2,), float(i)) for i in range(5)}
    health.grad_stats(g, top_k=2)
    jax.effects_barrier()
    layer_names = {n for n in _names(col) if n.startswith("health/layer/")}
    assert layer_names == {"health/layer/g4/grad_norm",
                           "health/layer/g3/grad_norm",
                           "health/layer/(rest)/grad_norm"}
    (other,) = _by_name(col, "health/layer/(rest)/grad_norm")
    assert other.value == pytest.approx(math.sqrt(2 * (0 + 1 + 4)))


def test_grad_stats_nonfinite_group_ranks_first(col):
    # the NaN group must be named even when its finite norm would lose
    g = {"big": jnp.full((4,), 100.0),
         "mid": jnp.full((4,), 10.0),
         "sick": jnp.array([jnp.nan, 0.1])}
    health.grad_stats(g, top_k=1)
    jax.effects_barrier()
    layer = {n for n in _names(col) if n.startswith("health/layer/")}
    assert "health/layer/sick/grad_norm" in layer
    assert "health/layer/sick/nonfinite" in layer
    (nan_ev,) = _by_name(col, "health/nan")
    assert nan_ev.value == 1.0


def test_grad_stats_scale_divides_norms(col):
    g = {"a": jnp.full((4,), 8.0)}
    health.grad_stats(g, scale=8.0)
    jax.effects_barrier()
    (gn,) = _by_name(col, "health/grad_norm")
    assert gn.value == pytest.approx(2.0)   # sqrt(4 * 64) / 8


def test_grad_stats_prefixes_grouping(col):
    g = {"enc": {"l0": jnp.ones((2,)), "l1": jnp.ones((2,))},
         "dec": {"l0": jnp.ones((2,))},
         "head": jnp.ones((3,))}
    health.grad_stats(g, prefixes=["enc", "dec/l0"])
    jax.effects_barrier()
    layer = {n for n in _names(col) if n.startswith("health/layer/")}
    assert layer == {"health/layer/enc/grad_norm",
                     "health/layer/dec/l0/grad_norm",
                     "health/layer/other/grad_norm"}


def test_grad_stats_real_other_group_distinct_from_fold(col):
    # the unmatched-prefix bucket is a REAL group named "other"; when it
    # ranks in top-K while other groups fold, the fold's (rest) series
    # must stay a separate name — a collision would average the two in
    # summarize's (name, step) dedup.
    g = {"embed": jnp.full((2,), 1.0),
         "huge_unmatched": jnp.full((2,), 100.0),
         "small_a": jnp.full((2,), 0.5),
         "small_b": jnp.full((2,), 0.25)}
    health.grad_stats(g, prefixes=["embed", "small_a", "small_b"],
                      top_k=1)
    jax.effects_barrier()
    layer = {n for n in _names(col) if n.startswith("health/layer/")}
    assert layer == {"health/layer/other/grad_norm",
                     "health/layer/(rest)/grad_norm"}
    (other,) = _by_name(col, "health/layer/other/grad_norm")
    assert other.value == pytest.approx(100.0 * math.sqrt(2))
    (rest,) = _by_name(col, "health/layer/(rest)/grad_norm")
    assert rest.value == pytest.approx(
        math.sqrt(2 * (1.0 + 0.25 + 0.0625)))


def test_grad_stats_mismatched_trees_align_by_name(col):
    # frozen-embedding training: params carry a group grads don't.
    # The weight/update norms must pair groups BY NAME — the emb group
    # is excluded, never index-mispaired onto head.
    g = {"head": jnp.full((4,), 1.0)}
    p = {"emb": jnp.full((3,), 100.0), "head": jnp.full((4,), 3.0)}
    u = {"emb": jnp.zeros((3,)), "head": jnp.full((4,), 0.3)}
    health.grad_stats(g, params=p, updates=u)
    jax.effects_barrier()
    (wn,) = _by_name(col, "health/weight_norm")
    assert wn.value == pytest.approx(6.0)       # head only, not emb's 100s
    (ur,) = _by_name(col, "health/update_ratio")
    assert ur.value == pytest.approx(0.1)       # 0.6 / 6.0
    (lur,) = _by_name(col, "health/layer/head/update_ratio")
    assert lur.value == pytest.approx(0.1)


def test_grad_stats_more_grad_groups_than_params(col):
    # grads with a group params lack must not index out of bounds in the
    # host callback; the uncovered group just has no per-layer ratio
    g = {"a": jnp.full((2,), 1.0), "b": jnp.full((2,), 2.0)}
    p = {"a": jnp.full((2,), 3.0)}
    u = {"a": jnp.full((2,), 0.3)}
    health.grad_stats(g, params=p, updates=u)
    jax.effects_barrier()
    assert _by_name(col, "health/layer/a/update_ratio")
    assert not _by_name(col, "health/layer/b/update_ratio")
    (wn,) = _by_name(col, "health/weight_norm")
    assert wn.value == pytest.approx(math.sqrt(2 * 9))


def test_grad_stats_under_jit_with_traced_step(col):
    @jax.jit
    def f(g, s):
        health.grad_stats(g, step=s)
        return g

    jax.block_until_ready(f({"w": jnp.full((9,), 2.0)}, jnp.int32(7)))
    jax.effects_barrier()
    (gn,) = _by_name(col, "health/grad_norm")
    assert (gn.value, gn.step) == (pytest.approx(6.0), 7)


def test_grad_stats_under_shard_map_psum(col):
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))

    def body(x):
        health.grad_stats({"w": x}, axis_name="data", step=0)
        return jax.lax.psum(jnp.sum(x), "data")

    f = jax.jit(shard_map(body, mesh=mesh, in_specs=P("data"),
                          out_specs=P(), check_vma=False))
    jax.block_until_ready(f(jnp.ones((8, 4))))
    jax.effects_barrier()
    evs = _by_name(col, "health/grad_norm")
    # one callback per shard, each carrying the psum'd global value
    assert 1 <= len(evs) <= 8
    assert all(e.value == pytest.approx(math.sqrt(32)) for e in evs)
    # summarize's (name, step) dedup collapses the replicas
    agg = tel_export.summarize([e.to_dict() for e in col.snapshot()])
    assert agg["health"]["grad_norm"]["count"] == 1


# ---------------------------------------------------------------------------
# overflow attribution
# ---------------------------------------------------------------------------

def test_attribute_overflow_names_first_group_in_tree_order(col):
    g = {"a": jnp.ones((4,)),
         "b": jnp.array([jnp.nan, 1.0]),
         "c": jnp.array([jnp.inf, jnp.inf])}
    health.attribute_overflow(jnp.array(True), g, step=3)
    jax.effects_barrier()
    (e,) = _by_name(col, "health/overflow_source")
    assert e.step == 3 and e.value == 3.0
    assert e.meta["group"] == "b"           # first offender, tree order
    assert e.meta["nan"] == 1 and e.meta["inf"] == 2
    assert e.meta["per_group"] == {"b": 1, "c": 2}


def test_attribute_overflow_silent_without_overflow(col):
    health.attribute_overflow(
        jnp.array(False), {"a": jnp.array([jnp.nan])})
    jax.effects_barrier()
    assert not _by_name(col, "health/overflow_source")


def test_attribute_overflow_under_jit_cond(col):
    @jax.jit
    def f(g, flag):
        health.attribute_overflow(flag, g, step=1)
        return flag

    g = {"x": jnp.ones((2,)), "y": jnp.array([jnp.inf])}
    jax.block_until_ready(f(g, jnp.array(True)))
    jax.block_until_ready(f(g, jnp.array(False)))
    jax.effects_barrier()
    evs = _by_name(col, "health/overflow_source")
    assert len(evs) == 1                    # False run emitted nothing
    assert evs[0].meta["group"] == "y"


def test_amp_optimizer_attributes_overflow(col):
    from apex_tpu import amp, optimizers

    inner = optimizers.FusedSGD(lr=0.1)
    _, aopt = amp.initialize(None, inner, opt_level="O2", verbosity=0)
    params = {"a": jnp.ones((4, 4), jnp.float16),
              "b": jnp.ones((4,), jnp.float16)}
    state = aopt.init(params)
    step = jax.jit(lambda g, p, s: aopt.step(g, p, s))

    good = {"a": jnp.ones((4, 4), jnp.float16),
            "b": jnp.ones((4,), jnp.float16)}
    bad = {"a": jnp.ones((4, 4), jnp.float16),
           "b": jnp.full((4,), jnp.nan, jnp.float16)}
    params, state, _ = step(good, params, state)
    params, state, _ = step(bad, params, state)
    jax.block_until_ready(state.scaler.loss_scale)
    jax.effects_barrier()
    (e,) = _by_name(col, "health/overflow_source")
    assert e.meta["group"] == "b" and e.meta["nan"] == 4
    assert e.step == 1                      # execution index attribution


# ---------------------------------------------------------------------------
# divergence detector (live + offline + CLI)
# ---------------------------------------------------------------------------

def test_detector_loss_nonfinite_fires_immediately():
    det = health.DivergenceDetector(emit=False)
    assert det.update(0, loss=1.0) == []
    (a,) = det.update(1, loss=float("nan"))
    assert a["reason"] == "loss_nonfinite" and a["step"] == 1


def test_detector_loss_spike_zscore():
    det = health.DivergenceDetector(emit=False, min_history=4,
                                    z_threshold=6.0)
    for i in range(8):
        assert det.update(i, loss=2.0 + 0.01 * (i % 2)) == []
    (a,) = det.update(8, loss=50.0)
    assert a["reason"] == "loss_spike"


def test_detector_small_window_clamps_min_history():
    # window < default min_history (8) must not silently disable the
    # spike/explosion rules: the deques cap at maxlen=window, so an
    # unclamped gate len >= 8 could never open.
    det = health.DivergenceDetector(emit=False, window=6,
                                    z_threshold=6.0,
                                    explosion_ratio=10.0)
    assert det.min_history <= det.window
    for i in range(6):
        assert det.update(i, loss=2.0, grad_norm=1.0) == []
    alerts = det.update(6, loss=50.0, grad_norm=100.0)
    assert {a["reason"] for a in alerts} == {"loss_spike",
                                             "grad_explosion"}


def test_detector_grad_explosion_and_nan():
    det = health.DivergenceDetector(emit=False, min_history=4,
                                    explosion_ratio=10.0)
    for i in range(6):
        assert det.update(i, grad_norm=1.0) == []
    (a,) = det.update(6, grad_norm=100.0)
    assert a["reason"] == "grad_explosion"
    (b,) = det.update(7, nan_count=3.0)
    assert b["reason"] == "nan_grads"


def test_detector_persistent_conditions_fire_once_per_episode():
    # a run stuck at NaN reports ONE alert per episode, not one per step
    det = health.DivergenceDetector(emit=False)
    assert len(det.update(0, loss=float("nan"), nan_count=5.0)) == 2
    for s in range(1, 40):      # condition persists: no re-fire
        assert det.update(s, loss=float("nan"), nan_count=5.0) == []
    # clears, then sets in again: a NEW episode fires
    assert det.update(40, loss=1.0, nan_count=0.0) == []
    assert len(det.update(41, loss=float("nan"), nan_count=2.0)) == 2


def test_detector_inf_with_overflow_is_benign_nan_is_not():
    det = health.DivergenceDetector(emit=False)
    # inf grad norm on a scaler-flagged step: normal saturate-skip-halve
    assert det.update(0, grad_norm=float("inf"), overflow=1.0) == []
    # same without the overflow flag: something else went non-finite
    (a,) = det.update(1, grad_norm=float("inf"), overflow=0.0)
    assert a["reason"] == "grad_nonfinite"


def test_detector_overflow_streak():
    det = health.DivergenceDetector(emit=False, overflow_streak=3)
    assert det.update(0, overflow=0.0) == []   # scale found footing
    assert det.update(1, overflow=1.0) == []
    assert det.update(2, overflow=1.0) == []
    (a,) = det.update(3, overflow=1.0)
    assert a["reason"] == "overflow_streak"
    assert det.update(4, overflow=1.0) == []   # fires once per streak


def test_detector_overflow_streak_warmup_grace():
    # the dynamic scaler's initial scale search (2^16 halved down) is a
    # legitimate overflow streak: before any clean step the threshold is
    # overflow_streak + grace, so healthy warmups don't trip CI gates
    det = health.DivergenceDetector(emit=False, overflow_streak=3)
    grace = health.DivergenceDetector._SCALE_SEARCH_GRACE
    alerts = []
    for s in range(3 + grace - 1):
        alerts += det.update(s, overflow=1.0)
    assert alerts == []            # a plausible scale search stays quiet
    (a,) = det.update(3 + grace - 1, overflow=1.0)  # beyond a real search
    assert a["reason"] == "overflow_streak"


def test_detector_emits_alert_events(col):
    det = health.DivergenceDetector()
    det.update(4, loss=float("inf"))
    (e,) = _by_name(col, "health/alert")
    assert e.kind == "counter" and e.step == 4
    assert e.meta["reason"] == "loss_nonfinite"


def test_detector_tiny_window_keeps_rules_armed():
    # window=1 clamps to 2 and the deques must use the CLAMPED value —
    # deque(maxlen=1) with min_history=2 could never open the gate and
    # both statistical rules would be silently off.
    det = health.DivergenceDetector(emit=False, window=1,
                                    z_threshold=6.0,
                                    explosion_ratio=10.0)
    assert det._losses.maxlen == det.window >= det.min_history
    for i in range(4):
        det.update(i, loss=2.0, grad_norm=1.0)
    alerts = det.update(4, loss=2000.0, grad_norm=1000.0)
    assert {a["reason"] for a in alerts} == {"loss_spike",
                                             "grad_explosion"}


def test_detect_prefers_train_loss_over_other_loss_series():
    # a second */loss series (val/loss at eval steps) must NOT blend
    # into the detector's loss signal: averaging train+val at shared
    # steps jumps vs the train-only window and fakes a loss_spike.
    evs = [{"name": "train/loss", "value": 2.0, "ts": float(s),
            "step": s} for s in range(12)]
    evs += [{"name": "val/loss", "value": 40.0, "ts": float(s),
             "step": s} for s in (5, 10)]
    assert health.detect(evs) == []


def test_detect_offline_merges_sources():
    evs = [{"name": "train/loss", "value": 2.0, "ts": 0.0, "step": 0},
           {"name": "train/loss", "value": float("nan"), "ts": 1.0,
            "step": 1},
           {"name": "health/overflow_source", "value": 4.0, "ts": 1.0,
            "step": 1,
            "meta": {"group": "blk/w", "nan": 4, "inf": 0}},
           {"name": "health/alert", "value": 1.0, "ts": 2.0, "step": 2,
            "kind": "counter",
            "meta": {"reason": "custom", "detail": "live"}}]
    alerts = health.detect(evs)
    reasons = {(a["step"], a["reason"]) for a in alerts}
    assert (1, "loss_nonfinite") in reasons
    assert (1, "nan_grads") in reasons
    assert (2, "custom") in reasons
    nan_a = next(a for a in alerts if a["reason"] == "nan_grads")
    assert "blk/w" in nan_a["detail"]       # names the offending group


def test_health_cli_healthy_exit_zero(tmp_path, capsys):
    path = str(tmp_path / "ok.jsonl")
    evs = [{"name": "train/loss", "value": 2.0 - 0.1 * s, "ts": float(s),
            "step": s} for s in range(5)]
    evs += [{"name": "health/grad_norm", "value": 1.0, "ts": float(s),
             "step": s} for s in range(5)]
    tel_export.write_jsonl(path, evs)
    assert cli_main(["health", path]) == 0
    out = capsys.readouterr().out
    assert "healthy" in out and "grad norm" in out


def test_health_cli_surfaces_dropped_events(tmp_path, capsys):
    # a verdict over a lossy stream must be qualified: the events that
    # would have alerted may be among the dropped ones.
    path = str(tmp_path / "lossy.jsonl")
    evs = [{"name": "train/loss", "value": 2.0, "ts": float(s),
            "step": s} for s in range(5)]
    evs.append({"name": "telemetry/dropped", "value": 7.0, "ts": 5.0,
                "kind": "counter"})
    tel_export.write_jsonl(path, evs)
    assert cli_main(["health", path]) == 0
    cap = capsys.readouterr()
    assert "healthy" in cap.out
    assert "7 events were dropped" in cap.err
    assert cli_main(["health", path, "--json"]) == 0
    cap = capsys.readouterr()
    assert json.loads(cap.out)["dropped"] == 7
    assert "7 events were dropped" in cap.err


def test_health_cli_injected_nan_run(tmp_path, capsys, col):
    """The acceptance fixture: an amp step fed NaN grads in one named
    param group -> `telemetry health` exits nonzero AND the report names
    the first non-finite group."""
    from apex_tpu import amp, optimizers

    inner = optimizers.FusedSGD(lr=0.1)
    _, aopt = amp.initialize(None, inner, opt_level="O2", verbosity=0)
    params = {"emb": jnp.ones((4, 4), jnp.float16),
              "blocks_1": jnp.ones((8,), jnp.float16)}
    state = aopt.init(params)
    step = jax.jit(lambda g, p, s: aopt.step(g, p, s))
    for i in range(4):
        g = jax.tree_util.tree_map(jnp.ones_like, params)
        if i == 2:   # the injected-NaN step
            g["blocks_1"] = jnp.full((8,), jnp.nan, jnp.float16)
        params, state, _ = step(g, params, state)
    jax.block_until_ready(state.scaler.loss_scale)
    jax.effects_barrier()
    path = str(tmp_path / "nan_run.jsonl")
    telemetry.write_jsonl(path)
    rc = cli_main(["health", path])
    out = capsys.readouterr().out
    assert rc == 3
    assert "blocks_1" in out                # names the offending group
    assert "nan_grads" in out


def test_health_cli_json_strict_on_nonfinite_stats(tmp_path, capsys):
    # the --json contract: even a diverged run (NaN stats — the health
    # command's core case) must emit RFC 8259 JSON a strict parser takes
    path = str(tmp_path / "div.jsonl")
    # every sample non-finite: the stats themselves are NaN (a finite
    # subset would instead carry finite stats + a "nonfinite" count)
    evs = [{"name": "health/grad_norm", "value": float("nan"),
            "ts": float(s), "step": s} for s in range(5)]
    tel_export.write_jsonl(path, evs)
    cli_main(["health", path, "--json"])
    out = capsys.readouterr().out
    parsed = json.loads(out, parse_constant=lambda c: pytest.fail(
        f"non-strict JSON constant {c!r} in --json output"))
    assert parsed["grad_norm"]["mean"] == "NaN"
    assert parsed["grad_norm"]["nonfinite"] == 5


def test_jsonl_file_is_strict_json_and_roundtrips_nonfinite(tmp_path):
    # the run FILE must also be RFC 8259 strict — a diverged run's NaN
    # loss is exactly the value worth exporting. Strings on disk, floats
    # back in memory.
    path = str(tmp_path / "strict.jsonl")
    tel_export.write_jsonl(path, [
        {"name": "train/loss", "value": float("nan"), "ts": 0.0, "step": 0},
        {"name": "health/grad_norm", "value": float("inf"), "ts": 1.0,
         "step": 1},
        {"name": "train/loss", "value": 2.0, "ts": 2.0, "step": 2}])
    with open(path) as f:
        for line in f:
            json.loads(line, parse_constant=lambda c: pytest.fail(
                f"non-strict JSON constant {c!r} in run file"))
    evs = tel_export.read_jsonl(path)
    assert math.isnan(evs[0]["value"])
    assert evs[1]["value"] == float("inf")
    assert evs[2]["value"] == 2.0
    # and the NaN still drives detection after the round-trip
    alerts = health.detect(evs)
    assert any(a["reason"] == "loss_nonfinite" for a in alerts)


def test_collector_last():
    with tel_events.capture() as c:
        assert c.last("a") is None
        telemetry.record("a", 1.0, step=0)
        telemetry.record("b", 5.0, step=0)
        telemetry.record("a", 2.0, step=1)
        assert c.last("a").value == 2.0
        assert c.last("b").value == 5.0


def test_summarize_health_section_and_format(tmp_path):
    evs = []
    for s in range(4):
        evs.append({"name": "health/grad_norm", "value": 1.0 + s,
                    "ts": float(s), "step": s})
        evs.append({"name": "health/update_ratio", "value": 1e-3,
                    "ts": float(s), "step": s})
        evs.append({"name": "health/nonfinite", "value": 0.0,
                    "ts": float(s), "step": s})
        evs.append({"name": "health/layer/emb/grad_norm", "value": 0.5,
                    "ts": float(s), "step": s})
    s = tel_export.summarize(evs)
    h = s["health"]
    assert h["grad_norm"]["count"] == 4
    assert h["grad_norm"]["max"] == 4.0
    assert h["update_ratio"]["mean"] == pytest.approx(1e-3)
    assert h["layers"] == {"emb": 0.5}
    assert "alerts" not in h
    text = tel_export.format_summary(s)
    assert "health:" in text and "update ratio" in text


def test_summarize_health_stats_robust_to_nonfinite():
    # diverged runs carry NaN/Inf samples BY DESIGN; order statistics
    # must run on the finite subset (NaN is incomparable under sort and
    # would poison the percentiles / hide the finite peak from max)
    evs = [{"name": "health/grad_norm", "value": v, "ts": float(i),
            "step": i}
           for i, v in enumerate([5.0, math.nan, 1.0])]
    g = tel_export.summarize(evs)["health"]["grad_norm"]
    assert g["count"] == 3 and g["nonfinite"] == 1
    assert g["max"] == 5.0 and g["p50"] == 3.0
    evs.append({"name": "health/grad_norm", "value": math.inf,
                "ts": 3.0, "step": 3})
    g = tel_export.summarize(evs)["health"]["grad_norm"]
    assert g["max"] == math.inf and g["mean"] == 3.0  # finite mean


def test_summarize_overflow_sources_dedup_shard_replicas():
    # attribute_overflow's callback fires once PER SHARD under
    # shard_map/pmap: 8 replicas of each overflow must collapse to one
    # report row per (step, group), not flood the 20-row cap
    evs = []
    for step in (3, 7):
        for _ in range(8):
            evs.append({"name": "health/overflow_source", "value": 2.0,
                        "ts": float(step), "step": step,
                        "meta": {"group": "blk", "nan": 1}})
    h = tel_export.summarize(evs)["health"]
    assert [s["step"] for s in h["overflow_sources"]] == [3, 7]


# ---------------------------------------------------------------------------
# producer wiring: DDP / ZeRO per-bucket grad norms
# ---------------------------------------------------------------------------

def test_ddp_bucket_grad_norms(col):
    from apex_tpu import parallel

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))
    grads = {"a": jnp.ones((16, 8), jnp.float32),
             "b": jnp.ones((32,), jnp.bfloat16)}
    f = jax.jit(shard_map(
        lambda g, s: parallel.allreduce_gradients(g, "data",
                                                  telemetry_step=s),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False))
    jax.block_until_ready(f(grads, jnp.int32(5)))
    jax.effects_barrier()
    names = {n for n in _names(col) if n.startswith("health/ddp/")}
    assert names == {"health/ddp/bucket0/grad_norm",
                     "health/ddp/bucket1/grad_norm"}
    # step attribution: per-shard replicas carry the step so summarize's
    # (name, step) dedup collapses them to one sample per bucket
    assert all(e.step == 5 for n in names for e in _by_name(col, n))
    agg = tel_export.summarize([e.to_dict() for e in col.snapshot()])
    # producer series report under "buckets", NOT mixed into the
    # (unscaled) grad_stats "layers" table
    assert agg["health"]["buckets"]["ddp/bucket0"] == pytest.approx(
        math.sqrt(128), rel=1e-3)
    assert "ddp/bucket0" not in agg["health"].get("layers", {})
    # grads are replicated ones; pmean keeps them ones -> norm = sqrt(n)
    vals = sorted({e.value for n in names for e in _by_name(col, n)})
    assert vals[0] == pytest.approx(math.sqrt(32), rel=1e-3)
    assert vals[-1] == pytest.approx(math.sqrt(128), rel=1e-3)


def test_zero_bucket_grad_norms(col):
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    n = 8
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("data",))
    opt = DistributedFusedAdam(lr=1e-3, axis_name="data", shard_count=n)
    p = {"w": jnp.ones((8, 16)), "b": jnp.ones((8,))}    # 136 elements
    st = opt.init(p)
    f = jax.jit(shard_map(
        lambda g, p, s: opt.step(g, p, s), mesh=mesh,
        in_specs=(P(), P(), opt.state_pspec()),
        out_specs=(P(), opt.state_pspec()), check_vma=False))
    _, new_st = f(p, p, st)
    jax.block_until_ready(new_st.master)
    jax.effects_barrier()
    evs = _by_name(col, "health/zero/bucket0/grad_norm")
    assert evs
    # replicated ones-grads, mean over 8 devices is ones: norm sqrt(136)
    assert all(e.value == pytest.approx(math.sqrt(136)) for e in evs)
    # step rides in from ZeroState.step so shard replicas dedup
    assert all(e.step == 1 for e in evs)


# ---------------------------------------------------------------------------
# satellites: load(follow_rotations), dropped surfacing, concurrency,
# cost-analysis key spellings
# ---------------------------------------------------------------------------

def test_load_follows_rotations_oldest_first(tmp_path):
    path = str(tmp_path / "r.jsonl")
    with tel_export.JsonlWriter(path, max_bytes=300, max_files=3) as w:
        for i in range(30):
            w.write(tel_events.Event("n", float(i), ts=0.0))
    import os
    assert os.path.exists(path + ".1")      # rotation actually happened
    all_evs = tel_export.load(path)
    vals = [e["value"] for e in all_evs]
    assert vals == sorted(vals)             # oldest-first, in order
    assert vals[-1] == 29.0
    live_only = tel_export.load(path, follow_rotations=False)
    assert live_only == tel_export.read_jsonl(path)
    assert len(live_only) < len(all_evs)


def test_cli_summarize_includes_rotated_generations(tmp_path, capsys):
    path = str(tmp_path / "rot.jsonl")
    with tel_export.JsonlWriter(path, max_bytes=400, max_files=5) as w:
        for s in range(40):
            w.write(tel_events.Event("step/time_s", 0.1, ts=float(s),
                                     step=s))
    assert cli_main(["summarize", path, "--json"]) == 0
    agg = json.loads(capsys.readouterr().out)
    n_live = len(tel_export.read_jsonl(path))
    assert agg["step_time_s"]["count"] > n_live
    assert cli_main(["summarize", path, "--json", "--no-follow"]) == 0
    agg2 = json.loads(capsys.readouterr().out)
    assert agg2["step_time_s"]["count"] == n_live


def test_cli_tail_reads_rotations_newest_first(tmp_path, capsys):
    path = str(tmp_path / "t.jsonl")
    with tel_export.JsonlWriter(path, max_bytes=300, max_files=5) as w:
        for i in range(30):
            w.write(tel_events.Event("n", float(i), ts=0.0))
    n_live = len(tel_export.read_jsonl(path))
    # ask for more than the live file holds: rotated generations must
    # contribute, in order, without loading the whole history
    want = n_live + 2
    assert cli_main(["tail", path, "-n", str(want)]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == want
    assert out[-1].startswith("0.000 n=29")
    vals = [float(line.split("n=")[1].split()[0]) for line in out]
    assert vals == sorted(vals)


def test_dropped_events_surfaced(tmp_path):
    with tel_events.capture(capacity=3) as c:
        for i in range(8):
            telemetry.record("x", float(i))
        assert c.dropped == 5
        path = str(tmp_path / "drop.jsonl")
        telemetry.write_jsonl(path)         # drains + appends the marker
    evs = tel_export.read_jsonl(path)
    drop = [e for e in evs if e["name"] == "telemetry/dropped"]
    assert len(drop) == 1
    assert drop[0]["value"] == 5.0 and drop[0]["kind"] == "counter"
    assert drop[0]["meta"]["capacity"] == 3
    s = tel_export.summarize(evs)
    assert s["dropped"] == 5.0
    assert "WARNING" in tel_export.format_summary(s)
    assert "dropped" in tel_export.format_summary(s)


def test_drain_resets_dropped_between_runs(tmp_path):
    # a lossy run A must not contaminate a clean run B written from the
    # same collector: drain() resets dropped alongside the buffer
    with tel_events.capture(capacity=3) as c:
        for i in range(8):
            telemetry.record("x", float(i))
        path_a = str(tmp_path / "a.jsonl")
        telemetry.write_jsonl(path_a)
        assert c.dropped == 0
        telemetry.record("y", 1.0)
        path_b = str(tmp_path / "b.jsonl")
        telemetry.write_jsonl(path_b)
    assert any(e["name"] == "telemetry/dropped"
               for e in tel_export.read_jsonl(path_a))
    evs_b = tel_export.read_jsonl(path_b)
    assert [e["name"] for e in evs_b] == ["y"]
    assert "dropped" not in tel_export.summarize(evs_b)


def test_no_dropped_event_when_nothing_dropped(tmp_path):
    with tel_events.capture() as c:
        telemetry.record("x", 1.0)
        path = str(tmp_path / "ok.jsonl")
        telemetry.write_jsonl(path)
    evs = tel_export.read_jsonl(path)
    assert [e["name"] for e in evs] == ["x"]
    assert "dropped" not in tel_export.summarize(evs)


def test_collector_concurrent_producers_no_loss_unaccounted():
    n_threads, n_events, cap = 8, 500, 64
    c = tel_events.Collector(capacity=cap)

    def worker(t):
        for i in range(n_events):
            c.record(f"t{t}", float(i), step=i)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # conservation: every event either survived or was counted dropped
    assert len(c) + c.dropped == n_threads * n_events
    assert len(c) == cap
    # no duplication/corruption: each surviving event is a well-formed
    # (thread, step, value) fact and no (name, step) pair appears twice
    seen = set()
    for e in c.snapshot():
        assert e.name in {f"t{t}" for t in range(n_threads)}
        assert e.value == float(e.step)
        assert (e.name, e.step) not in seen
        seen.add((e.name, e.step))


def test_cost_analysis_value_both_spellings():
    from apex_tpu._compat import cost_analysis_value

    assert cost_analysis_value({"bytes accessed": 5.0},
                               "bytes accessed") == 5.0
    assert cost_analysis_value({"bytes_accessed": 7.0},
                               "bytes accessed") == 7.0
    assert cost_analysis_value({"optimal seconds": 1.0},
                               "optimal_seconds") == 1.0
    assert cost_analysis_value({}, "bytes accessed", 0.0) == 0.0
    assert cost_analysis_value(None, "bytes accessed") is None
    # the spelled key wins over the variant when both exist
    assert cost_analysis_value(
        {"bytes accessed": 1.0, "bytes_accessed": 2.0},
        "bytes accessed") == 1.0


def test_analyze_reports_flops_via_compat():
    from apex_tpu.pyprof import prof

    out = prof.analyze(lambda x: x @ x, jnp.ones((16, 16)))
    assert out["flops"] and out["flops"] > 0
    if out["bytes_accessed"] is not None:
        assert out["arithmetic_intensity"] > 0
