"""apex_tpu.parallel.pipeline_schedule — timetable pipeline parallelism.

The load-bearing pins:

  * both timetables (GPipe, 1F1B) realize the analytic schedule
    formulas slot-for-slot over a (stages, microbatches) grid: tick
    count ``2*(M + P - 1)``, per-stage bubble ``2*(P - 1)``, dependency
    order (a microbatch is forwarded upstream before downstream,
    backwarded downstream before upstream), and 1F1B's activation
    high-water mark ``min(P - r, M)`` vs GPipe's ``M``.
  * the executor is BITWISE: 2-stage 1F1B == 2-stage GPipe == the
    single-stage :func:`accumulate_grads` baseline, loss and every
    gradient leaf (``np.array_equal``, no tolerance).
  * the same equality holds end to end through ``trainer.build``:
    final params after 3 compiled, donated steps.
  * inert default: at pipe world 1 :func:`pipelined_grads` traces the
    IDENTICAL jaxpr to :func:`accumulate_grads` on the composed
    function (the repo's opt-in-axis doctrine).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import parallel, plan, trainer
from apex_tpu.models import TransformerLM
from apex_tpu.models.gpt import Block, next_token_loss
from apex_tpu.normalization import layer_norm
from apex_tpu.parallel.mesh import named_mesh
from apex_tpu.parallel.pipeline import lm_stack_blocks, stacked_block_pspecs
from apex_tpu.parallel.pipeline_schedule import (
    SCHEDULES, accumulate_grads, bubble_fraction, make_schedule,
    pipelined_grads, schedule_1f1b, schedule_gpipe, stage_partition)
from apex_tpu.plan.layout import Layout

GRID = [(1, 1), (1, 4), (2, 1), (2, 4), (4, 2), (4, 4), (3, 5)]


def _slots(table, plane):
    """(tick, stage) -> microbatch for one plane ('fwd'/'bwd')."""
    rows = getattr(table, plane)
    return {(t, r): rows[t][r]
            for t in range(table.ticks)
            for r in range(table.stages) if rows[t][r] >= 0}


def _tick_of(table, plane, rank, j):
    rows = getattr(table, plane)
    (t,) = [t for t in range(table.ticks) if rows[t][rank] == j]
    return t


# ---------------------------------------------------------------------------
# timetables vs the analytic formulas
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("stages,mb", GRID)
@pytest.mark.parametrize("name", SCHEDULES)
def test_table_matches_analytic_shape(name, stages, mb):
    t = make_schedule(name, stages, mb)
    assert t.ticks == 2 * (mb + stages - 1)
    for r in range(stages):
        assert t.busy_slots(r) == 2 * mb
        assert t.bubble_slots(r) == 2 * (stages - 1)
        # the per-stage slot count realizes the closed-form fraction
        assert t.bubble_slots(r) / t.ticks == pytest.approx(
            bubble_fraction(stages, mb))
    # every microbatch forwarded and backwarded exactly once per stage,
    # and no (tick, stage) slot hosts both directions
    fwd, bwd = _slots(t, "fwd"), _slots(t, "bwd")
    assert len(fwd) == len(bwd) == stages * mb
    assert sorted(fwd.values()) == sorted(bwd.values())
    assert not set(fwd) & set(bwd)


@pytest.mark.parametrize("stages,mb", GRID)
@pytest.mark.parametrize("name", SCHEDULES)
def test_table_dependency_order(name, stages, mb):
    """A microbatch moves right through forwards, left through
    backwards, and never backwards before its own forward."""
    t = make_schedule(name, stages, mb)
    for j in range(mb):
        for r in range(stages):
            assert _tick_of(t, "bwd", r, j) > _tick_of(t, "fwd", r, j)
            if r > 0:
                assert _tick_of(t, "fwd", r, j) \
                    > _tick_of(t, "fwd", r - 1, j)
                assert _tick_of(t, "bwd", r - 1, j) \
                    > _tick_of(t, "bwd", r, j)


@pytest.mark.parametrize("stages,mb", GRID)
def test_1f1b_ordering_formulas(stages, mb):
    """The exact 1F1B timetable: warmup forwards at ``r + j``, steady
    forwards at ``2j + r``, every backward at ``2P - 1 - r + 2j``."""
    t = schedule_1f1b(stages, mb)
    for r in range(stages):
        for j in range(mb):
            want_f = r + j if j < stages - r else 2 * j + r
            assert _tick_of(t, "fwd", r, j) == want_f
            assert _tick_of(t, "bwd", r, j) == 2 * stages - 1 - r + 2 * j


@pytest.mark.parametrize("stages,mb", GRID)
def test_max_in_flight_is_1f1bs_point(stages, mb):
    g, f = schedule_gpipe(stages, mb), schedule_1f1b(stages, mb)
    for r in range(stages):
        assert g.max_in_flight(r) == mb
        assert f.max_in_flight(r) == min(stages - r, mb)


def test_make_schedule_loud():
    with pytest.raises(ValueError, match="known:"):
        make_schedule("interleaved", 2, 4)
    with pytest.raises(ValueError, match="stages >= 1"):
        schedule_gpipe(0, 4)


def test_stage_partition():
    assert stage_partition(8, 2) == [(0, 4), (4, 8)]
    ranges = stage_partition(7, 3)
    assert ranges == [(0, 3), (3, 5), (5, 7)]
    assert ranges[0][0] == 0 and ranges[-1][1] == 7
    with pytest.raises(ValueError, match="cannot split"):
        stage_partition(2, 4)


# ---------------------------------------------------------------------------
# the executor: bitwise across schedules and vs the single-stage baseline
# ---------------------------------------------------------------------------

V, L, E, H, S, B, MB = 32, 4, 16, 2, 8, 8, 4


@pytest.fixture(scope="module")
def lm_pieces():
    model = TransformerLM(vocab_size=V, num_layers=L, embed_dim=E,
                          num_heads=H, max_seq=S)
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, V)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    stacked, rest = lm_stack_blocks(params)

    def embed_fn(rst, t):
        return (rst["tok_emb"]["embedding"][t]
                + rst["pos_emb"]["embedding"][jnp.arange(t.shape[1])][None])

    def stage_fn(p_loc, h):
        def body(hh, p):
            return Block(E, H, name="b").apply({"params": p}, hh), ()
        return jax.lax.scan(body, h, p_loc)[0]

    def loss_fn(rst, h, t):
        hh = layer_norm(h.reshape(-1, E), rst["ln_f"]["weight"],
                        rst["ln_f"]["bias"]).reshape(h.shape)
        logits = hh @ rst["head"]["kernel"] + rst["head"]["bias"]
        return next_token_loss(logits.astype(jnp.float32), t)

    return embed_fn, stage_fn, loss_fn, stacked, rest, toks


def _run_pipeline(lm_pieces, world, schedule):
    embed_fn, stage_fn, loss_fn, stacked, rest, toks = lm_pieces
    mesh = parallel.make_mesh((world,), ("pipe",),
                              devices=jax.devices()[:world])
    sspecs = stacked_block_pspecs(stacked)
    stk = jax.device_put(stacked, jax.tree_util.tree_map(
        lambda sp: NamedSharding(mesh, sp), sspecs))

    def per_device(stk_, rst_, t):
        return pipelined_grads(embed_fn, stage_fn, loss_fn, stk_, rst_,
                               t, MB, axis_name="pipe",
                               schedule=schedule)

    fn = jax.jit(shard_map(per_device, mesh=mesh,
                           in_specs=(sspecs, P(), P()),
                           out_specs=(P(), (sspecs, P())),
                           check_vma=False))
    loss, grads = fn(stk, rest, toks)
    return jax.device_get((loss, grads))


def _assert_trees_bitwise(a, b):
    fa = jax.tree_util.tree_flatten_with_path(a)[0]
    fb = jax.tree_util.tree_flatten_with_path(b)[0]
    assert len(fa) == len(fb)
    for (path, la), (_, lb) in zip(fa, fb):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), \
            jax.tree_util.keystr(path)


def test_two_stage_bitwise_vs_single_stage_and_across_schedules(lm_pieces):
    """THE acceptance pin: 2-stage 1F1B == 2-stage GPipe == the world-1
    fallback (= accumulate_grads), bitwise on loss and every grad."""
    base = _run_pipeline(lm_pieces, 1, "1f1b")
    for schedule in SCHEDULES:
        out = _run_pipeline(lm_pieces, 2, schedule)
        _assert_trees_bitwise(base, out)


def test_four_stage_1f1b_bitwise(lm_pieces):
    base = _run_pipeline(lm_pieces, 1, "1f1b")
    _assert_trees_bitwise(base, _run_pipeline(lm_pieces, 4, "1f1b"))


def test_pp1_traces_identical_jaxpr_to_accumulate_grads(lm_pieces):
    """Inert default: at pipe world 1 pipelined_grads IS the
    accumulation baseline — identical jaxpr, not merely close."""
    embed_fn, stage_fn, loss_fn, stacked, rest, toks = lm_pieces
    mesh = named_mesh([("pipe", 1)])
    sspecs = stacked_block_pspecs(stacked)

    def loss_of(pr, t):
        p, r = pr
        return loss_fn(r, stage_fn(p, embed_fn(r, t)), t)

    def via_pipeline(stk_, rst_, t):
        return pipelined_grads(embed_fn, stage_fn, loss_fn, stk_, rst_,
                               t, MB, axis_name="pipe")

    def via_accumulate(stk_, rst_, t):
        return accumulate_grads(loss_of, (stk_, rst_), t, MB)

    def jx(fn):
        smapped = shard_map(fn, mesh=mesh, in_specs=(sspecs, P(), P()),
                            out_specs=(P(), (sspecs, P())),
                            check_vma=False)
        return str(jax.make_jaxpr(smapped)(stacked, rest, toks))

    assert jx(via_pipeline) == jx(via_accumulate)


# ---------------------------------------------------------------------------
# end to end through trainer.build (the planner's delivery point)
# ---------------------------------------------------------------------------

ADAPTER = plan.GPTAdapter(vocab=32, layers=2, embed=32, heads=2,
                          batch=8, seq=16)


def _train(built, mesh, steps=3):
    tr = trainer.build(built.step, built.state_avals, built.batch_avals,
                       mesh=mesh, state_spec=built.state_spec,
                       batch_spec=built.batch_spec,
                       config=trainer.TrainerConfig(mode="per_step",
                                                    donate=True))
    # host copy: the same initial values regardless of source placement
    state0 = jax.device_get(built.init_state())
    state = tr.run(state0, built.batch_fn, steps)
    jax.block_until_ready(state)
    return jax.device_get(state)


def test_trainer_build_two_stage_1f1b_bitwise_vs_single_stage(monkeypatch):
    """2-stage 1F1B through ``trainer.build`` (compiled, donated,
    dispatch-windowed) lands bitwise on the single-stage twin of the
    same program after 3 steps — and the GPipe knob changes nothing."""
    lay = Layout(dp=1, pp=2, microbatch=4)
    built = ADAPTER.build(lay)
    pp2 = _train(built, built.mesh)
    base = _train(built, named_mesh([("pipe", 1)]))
    _assert_trees_bitwise(base[0], pp2[0])

    monkeypatch.setenv("APEX_TPU_PP_SCHEDULE", "gpipe")
    gp = _train(ADAPTER.build(lay), built.mesh)
    _assert_trees_bitwise(base[0], gp[0])
