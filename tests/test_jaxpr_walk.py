"""utils.jaxpr_walk — direct coverage of the shared walker on deeply
nested programs (scan-in-while-in-cond with shard_map inside): the
PR 7 hlo.py nested-parens bug class, at the jaxpr layer. Previously this
module was only exercised indirectly through telemetry/comm and lint.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

import apex_tpu  # noqa: F401  (compat shims)
from apex_tpu.utils.jaxpr_walk import (WalkContext, mesh_axis_sizes,
                                       subjaxprs, subjaxprs_tagged,
                                       walk_jaxpr, walk_jaxpr_ctx)


def _mesh():
    return Mesh(np.asarray(jax.devices()[:1]), ("data",))


def _nested_program():
    """cond( while( scan( shard_map(psum) ) ) ) — every container the
    walker knows, nested in one program."""
    mesh = _mesh()

    def shard_psum(v):
        return jax.lax.psum(v, "data")

    smapped = jax.shard_map(shard_psum, mesh=mesh, in_specs=(P(),),
                            out_specs=P(), check_vma=False)

    def scan_body(acc, _):
        return acc + smapped(acc), acc

    def w_body(c):
        acc, i = c
        acc, _ = jax.lax.scan(scan_body, acc, None, length=2)
        return (acc, i + 1)

    def w_cond(c):
        return c[1] < 3

    def true_branch(x):
        return jax.lax.while_loop(w_cond, w_body, (x, 0))[0]

    def prog(x):
        return jax.lax.cond(jnp.sum(x) > 0, true_branch, lambda v: v, x)

    return jax.make_jaxpr(prog)(jnp.ones((4,)))


def test_walk_jaxpr_reaches_every_nesting_level():
    closed = _nested_program()
    prims = []
    walk_jaxpr(closed.jaxpr, lambda e: prims.append(e.primitive.name))
    # one psum, inside shard_map inside scan inside while inside cond
    assert prims.count("psum") == 1
    assert prims.count("cond") == 1
    assert prims.count("while") == 1
    assert prims.count("scan") == 1
    assert prims.count("shard_map") == 1


def test_subjaxprs_tagged_roles_and_operand_mapping():
    closed = _nested_program()
    cond_eqn = next(e for e in closed.jaxpr.eqns
                    if e.primitive.name == "cond")
    branches = subjaxprs_tagged(cond_eqn)
    assert {s.role for s in branches} == {"cond_branch"}
    for s in branches:
        # predicate dropped: operands map 1:1 onto branch invars
        assert s.operands is not None
        assert len(s.operands) == len(s.jaxpr.invars)

    # descend: the while lives in the true branch
    true_j = branches[0].jaxpr if any(
        e.primitive.name == "while" for e in branches[0].jaxpr.eqns
    ) else branches[1].jaxpr
    while_eqn = next(e for e in true_j.eqns
                     if e.primitive.name == "while")
    subs = {s.role: s for s in subjaxprs_tagged(while_eqn)}
    assert set(subs) == {"while_cond", "while_body"}
    # the precise const/carry split: both map 1:1
    for s in subs.values():
        assert s.operands is not None
        assert len(s.operands) == len(s.jaxpr.invars)

    scan_eqn = next(e for e in subs["while_body"].jaxpr.eqns
                    if e.primitive.name == "scan")
    (scan_sub,) = subjaxprs_tagged(scan_eqn)
    assert scan_sub.role == "scan_body"
    assert scan_sub.operands is not None

    sm_eqn = next(e for e in scan_sub.jaxpr.eqns
                  if e.primitive.name == "shard_map")
    (sm_sub,) = subjaxprs_tagged(sm_eqn)
    assert sm_sub.role == "shard_map"
    assert sm_sub.operands is not None
    assert mesh_axis_sizes(sm_eqn) == {"data": 1}


def test_subjaxprs_permissive_tier_unchanged():
    # the permissive tier must still discover every sub-jaxpr (its
    # operand mapping is best-effort; discovery is the contract)
    closed = _nested_program()
    cond_eqn = next(e for e in closed.jaxpr.eqns
                    if e.primitive.name == "cond")
    assert len(subjaxprs(cond_eqn)) == 2       # both branches


def test_walk_jaxpr_ctx_threads_context_to_the_psum():
    closed = _nested_program()
    seen = []

    def visit(eqn, ctx):
        if eqn.primitive.name == "psum":
            seen.append(ctx)

    walk_jaxpr_ctx(closed.jaxpr, visit)
    assert len(seen) == 1
    ctx = seen[0]
    assert ctx.path == ("cond_branch", "while_body", "scan_body",
                        "shard_map")
    assert ctx.depth == 4
    assert ctx.in_cond and ctx.in_while
    assert ctx.loop_mult == 2                  # the scan's static length
    assert ctx.mesh_axes == ("data",)
    assert ctx.axis_size("data") == 1
    assert ctx.axis_size("model") is None


def test_walk_jaxpr_ctx_seeded_axis_sizes_take_precedence():
    closed = _nested_program()
    seen = []
    walk_jaxpr_ctx(closed.jaxpr,
                   lambda e, c: seen.append(c)
                   if e.primitive.name == "psum" else None,
                   WalkContext(axis_sizes=(("data", 8),)))
    # caller-seeded size wins over the (1-device) mesh param
    assert seen[0].axis_size("data") == 8


def test_walk_jaxpr_ctx_root_context_defaults():
    closed = _nested_program()
    roots = []

    def visit(eqn, ctx):
        if ctx.depth == 0:
            roots.append((eqn.primitive.name, ctx))

    walk_jaxpr_ctx(closed.jaxpr, visit)
    assert roots, "top-level equations must see the root context"
    for _, ctx in roots:
        assert ctx.path == () and ctx.loop_mult == 1
        assert not ctx.in_while and not ctx.in_cond


def test_comm_stats_on_nested_program_regression():
    # telemetry's comm walker consumes the same program: the psum must
    # be counted once per scan iteration (x2), flagged as a while lower
    # bound, with the shard_map-resolved axis size
    from apex_tpu.telemetry.comm import comm_stats
    mesh = _mesh()

    def shard_psum(v):
        return jax.lax.psum(v, "data")

    smapped = jax.shard_map(shard_psum, mesh=mesh, in_specs=(P(),),
                            out_specs=P(), check_vma=False)

    def scan_body(acc, _):
        return acc + smapped(acc), acc

    def w_body(c):
        acc, i = c
        acc, _ = jax.lax.scan(scan_body, acc, None, length=2)
        return (acc, i + 1)

    def prog(x):
        return jax.lax.cond(
            jnp.sum(x) > 0,
            lambda v: jax.lax.while_loop(lambda c: c[1] < 3, w_body,
                                         (v, 0))[0],
            lambda v: v, x)

    (rec,) = comm_stats(prog, jnp.ones((4,)))
    assert (rec.axis, rec.primitive) == ("data", "psum")
    assert rec.count == 2
    assert rec.in_while
    assert rec.bytes_wire is not None          # axis size resolved (1)
