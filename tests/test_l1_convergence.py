"""L1-tier tests — port of the reference cross-product harness
(tests/L1/common/main_amp.py + compare.py:36-46): run a small model with
``--deterministic`` semantics, dump per-iteration losses, and assert

  * bitwise reproducibility: two identical runs produce IDENTICAL losses
    (``assert loss_e == loss_p`` in the reference), and
  * cross-opt-level consistency: every opt level converges on the same
    problem with losses tracking the fp32 run.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu import amp, optimizers


class SmallNet(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.Conv(8, (3, 3), padding="SAME")(x)
        x = nn.BatchNorm(use_running_average=False, name="bn")(x)
        x = nn.relu(x)
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(32)(x)
        x = nn.relu(x)
        return nn.Dense(10)(x)


def run_training(opt_level, steps=20, seed=0):
    jax.config.update("jax_default_matmul_precision", "highest")
    model = SmallNet()
    key = jax.random.PRNGKey(seed)
    x = jax.random.normal(key, (16, 8, 8, 3))
    y = jax.random.randint(jax.random.PRNGKey(seed + 1), (16,), 0, 10)

    variables = model.init(jax.random.PRNGKey(seed + 2), x)
    params32, bs = variables["params"], variables["batch_stats"]

    apply_fn, aopt = amp.initialize(
        model.apply, optimizers.FusedSGD(lr=0.05, momentum=0.9),
        opt_level=opt_level, verbosity=0)
    params = amp.cast_model(params32, amp.resolve(opt_level))
    st = aopt.init(params)

    @jax.jit
    def step(params, bs, st, x, y):
        def scaled(p):
            logits, upd = apply_fn({"params": p, "batch_stats": bs}, x,
                                   mutable=["batch_stats"])
            onehot = jax.nn.one_hot(y, 10)
            loss = -jnp.mean(jnp.sum(
                onehot * jax.nn.log_softmax(logits.astype(jnp.float32)), -1))
            return aopt.scale_loss(loss, st), (loss, upd["batch_stats"])
        grads, (loss, new_bs) = jax.grad(scaled, has_aux=True)(params)
        new_p, new_st, _ = aopt.step(grads, params, st)
        return new_p, new_bs, new_st, loss

    losses = []
    for _ in range(steps):
        params, bs, st, loss = step(params, bs, st, x, y)
        losses.append(float(loss))
    return losses


def test_bitwise_reproducibility():
    # reference compare.py: "assert loss_e == loss_p" — bitwise
    run1 = run_training("O5")
    run2 = run_training("O5")
    assert run1 == run2, "identical seeded runs must match bitwise"


@pytest.mark.parametrize("opt_level", ["O1", "O2", "O3", "O4", "O5"])
def test_opt_level_tracks_fp32(opt_level):
    base = run_training("O0", steps=20)
    test = run_training(opt_level, steps=20)
    # both must converge (loss decreases) and end in the same neighborhood
    assert base[-1] < base[0]
    assert test[-1] < test[0]
    tol = 0.15 if opt_level in ("O2", "O3") else 0.1
    assert abs(test[-1] - base[-1]) < max(tol, 0.2 * base[-1]), (
        opt_level, base[-1], test[-1])
